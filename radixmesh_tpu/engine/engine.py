"""Continuous-batching serving engine over the radix prefix cache.

Realizes the scheduler contract the reference documents but leaves
commented out (``radix_cache.py:439-519``): prefix match → lock → compute
→ publish (``cache_unfinished_req`` mid-request, ``cache_finished_req`` at
completion) → unlock, with LRU eviction under pool pressure.

TPU-first shape discipline:

- **Prefill** runs per request with sequence/prefix lengths padded to
  power-of-two buckets — O(log max_len²) compiled variants total, each an
  MXU-dense batch-1 call. The cached prefix is gathered right-aligned so
  ragged hit lengths stay exact (``models/llama.py:prefill_forward``).
- **Decode** is ONE fixed-shape jitted step per iteration for the whole
  batch: static ``[max_batch]`` rows, static page-table width. Inactive
  rows point at a reserved scratch page and their outputs are ignored —
  shapes never depend on how many requests are live.
- The KV pool array is donated through both paths; host-side tree
  mutation happens between device steps (SURVEY §7 hard part (c)).

Mesh integration (the reference's core loop, ``radix_mesh.py:193-238``):
pass ``mesh=MeshCache(...)`` and every publish is *also* inserted into the
distributed replica at token granularity, so the ring (and through it the
router) learns which node holds which prefix. Ownership stays split: the
engine's local tree owns slot lifetime (LRU evict → ``pool.free``), the
mesh replica is advertisement-only on a serving node (construct it with
``pool=None`` so distributed GC retires attribution entries without
double-freeing slots the engine still references).
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from dataclasses import replace as dataclass_replace
from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from radixmesh_tpu.cache.kv_pool import PagedKVPool
from radixmesh_tpu.cache.radix_tree import RadixTree
from radixmesh_tpu.engine.request import Request, RequestState, SamplingParams
from radixmesh_tpu.models.llama import (
    ModelConfig,
    decode_multi,
    decode_multi_compact,
    decode_step,
    prefill_chunk_paged,
    prefill_forward,
)
from radixmesh_tpu.ops.attention import (
    default_use_kernel,
    last_dispatch,
    select_paged,
)
from radixmesh_tpu.obs.attribution import shape_bucket
from radixmesh_tpu.obs.fleet_plane import eviction_counters
from radixmesh_tpu.obs.metrics import TOKEN_LEN_BUCKETS, get_registry
from radixmesh_tpu.obs.trace_plane import get_recorder
from radixmesh_tpu.ops.sampling import sample_tokens, spec_verify_sample
from radixmesh_tpu.utils.logging import get_logger

# Per-process engine sequence: disaggregated harnesses run a prefill engine
# and a decode engine in one process, so each needs its own metric series.
_engine_seq = itertools.count()

__all__ = ["Engine", "EngineStats"]


_M64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer — the same mixing family the tree
    fingerprints use; here it turns (seed, position) into sampling-key
    material."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def _pow2_at_least(n: int, floor: int = 8) -> int:
    n = max(n, floor)
    return 1 << (n - 1).bit_length()


# Pages per attention KV block in every chunked launch (prefill, spec
# verify, pp decode). Page tables are pow2-padded with this as the floor,
# and attend_chunk_hybrid requires max_pages to divide by it — one
# constant so the padding and the kernels can't drift apart.
_KV_BLOCK_PAGES = 32


@dataclass
class _InlineJob:
    """One partially-prefilled request riding the mixed-wave backlog
    (engine/waves.py). Slots and the batch row are acquired UP FRONT by
    the normal admission path (``_acquire_prompt_slots``); only the
    compute advances chunk-by-chunk — ``pos`` is the exact resume offset
    (tokens of the prompt whose KV is already in the pool), the chunk
    interleave invariant the wave tests pin."""

    req: Request
    row: int
    reuse: int
    own: np.ndarray
    token_slots: np.ndarray  # slot per prompt position (prefix + own)
    pos: int  # next un-prefilled prompt offset (starts at reuse)
    total: int  # len(prompt)


@dataclass
class EngineStats:
    """Hit-rate + throughput counters (the reference never increments its
    ``hit_count`` and emits no metrics — SURVEY §5 'observability')."""

    prompt_tokens: int = 0
    cached_tokens: int = 0  # reused from the radix cache at prefill
    generated_tokens: int = 0
    prefills: int = 0
    decode_steps: int = 0
    finished: int = 0
    preemptions: int = 0
    spec_proposed: int = 0  # draft tokens offered for verification
    spec_accepted: int = 0  # draft tokens accepted (KV kept, step skipped)
    spec_rejected: int = 0  # draft tokens rejected by verification
    resurrections: int = 0  # resume-mode admissions (crash recovery)
    replayed_tokens: int = 0  # already-delivered tokens re-prefilled
    replayed_cached_tokens: int = 0  # ... of which the cache served
    ttft_s: list[float] = field(default_factory=list)

    @property
    def hit_rate(self) -> float:
        """Prefix-cache hit-rate over prompt tokens — the north-star
        metric (``BASELINE.json``: target ≥70% on ShareGPT)."""
        return self.cached_tokens / self.prompt_tokens if self.prompt_tokens else 0.0

    @property
    def p50_ttft_s(self) -> float:
        return float(np.median(self.ttft_s)) if self.ttft_s else 0.0


class Engine:
    def __init__(
        self,
        cfg: ModelConfig,
        params: dict,
        *,
        num_slots: int = 4096,
        page_size: int = 16,
        max_batch: int = 8,
        max_seq_len: int | None = None,
        rng_seed: int = 0,
        name: str | None = None,
        host_cache_slots: int = 0,
        pool: PagedKVPool | None = None,
        mesh=None,
        prefill_chunk: int = 512,
        prefill_wave_tokens: int = 4096,
        long_prefill_threshold: int = 1024,
        sp_prefill_threshold: int = 4096,
        decode_steps_per_launch: int = 1,
        prefill_inline_budget: int = 0,
        prefill_inline_max_defer: int = 2,
        paged_min_batch: int = 0,
        spec_decode_tokens: int = 0,
        spec_ngram: int = 3,
        spec_adaptive: bool = False,
        token_timeline_capacity: int = 4096,
        token_stall_threshold_s: float = 0.05,
        kv_quant: str | None = None,
        weight_quant: str | None = None,
        device_mesh=None,
        kv_transfer_async: bool = False,
        kv_transfer_chunk_tokens: int = 512,
        kv_transfer_min_restore_tokens: int = 0,
        kv_tier_dir: str | None = None,
        kv_tier_capacity_bytes: int = 1 << 30,
        kv_tier_watermark: float = 0.7,
        kv_tier_min_heat: float = 0.0,
        kv_tier_destage_budget: int = 16,
        kv_tier_destage_interval_s: float = 0.25,
        stream_publish_tokens: int = 0,
        step_accounting: bool = False,
        peak_tflops: float | None = None,
    ):
        if page_size & (page_size - 1):
            raise ValueError("page_size must be a power of two")
        self.cfg = cfg
        # Multi-chip serving (SURVEY §7 stage 7): tp shards heads/ffn/vocab
        # across the device mesh; the SAME scheduler/tree/publish code runs
        # unchanged — only array placement differs. Qwen2-72B cannot serve
        # on one chip by definition; this is its path.
        self.device_mesh = device_mesh
        # Pipeline-parallel serving (parallel/pp_serving.py): a "pp" mesh
        # axis shards the LAYER axis of the unchanged param/pool pytrees;
        # prefill chunks and decode steps route through pp_forward_chunk
        # while every host-side structure stays identical.
        self._pp = (
            device_mesh is not None and device_mesh.shape.get("pp", 1) > 1
        )
        if weight_quant is not None:
            # W8A16 weights (ops/wquant.py): decode streams half the
            # weight bytes and Llama-3-8B fits one 16 GB v5e. Quantize
            # BEFORE sharding so the scale leaves shard with their
            # weights.
            if weight_quant != "int8":
                raise ValueError(f"unknown weight quantization {weight_quant!r}")
            from radixmesh_tpu.ops.wquant import quantize_params

            params = quantize_params(params)
        self.weight_quant = weight_quant
        if device_mesh is not None:
            tp = device_mesh.shape.get("tp", 1)
            if cfg.n_kv_heads % tp or cfg.n_heads % tp:
                raise ValueError(
                    f"n_heads={cfg.n_heads}/n_kv_heads={cfg.n_kv_heads} must "
                    f"divide tp={tp}"
                )
            if self._pp:
                if cfg.n_layers % device_mesh.shape["pp"]:
                    raise ValueError(
                        f"n_layers={cfg.n_layers} is not divisible by "
                        f"pp={device_mesh.shape['pp']}"
                    )
                from radixmesh_tpu.parallel.pp_serving import shard_params_pp

                params = shard_params_pp(params, cfg, device_mesh)
            else:
                from radixmesh_tpu.models.llama import param_logical_axes
                from radixmesh_tpu.parallel.sharding import shard_params

                params = shard_params(
                    params, param_logical_axes(cfg, params), device_mesh
                )
        self.params = params
        self.page_size = page_size
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len or cfg.max_seq_len
        self.max_pages = -(-self.max_seq_len // page_size)
        # Long-context admission (SURVEY §5): prompts with more than
        # ``long_prefill_threshold`` uncached tokens prefill in
        # ``prefill_chunk``-token chunks against the paged pool (O(S·chunk)
        # memory) instead of the dense path (O(S²) scores).
        self.prefill_chunk = prefill_chunk
        # Cold-burst fairness (VERDICT round-4 weak #4): a prefill wave
        # wider than the compute-saturating token count only convoys —
        # every member then finalizes its first token when the LAST one
        # does, so an N-request cold burst lands p50 TTFT == p99 == the
        # whole burst's prefill time. Sub-waves are sliced to at most
        # ``prefill_wave_tokens // chunk`` rows; slices preserve arrival
        # order (FIFO within a size bucket), so with equal jobs TTFT
        # approaches the single-server SPT floor (mean ≈ half the burst).
        self.prefill_wave_tokens = prefill_wave_tokens
        self.long_prefill_threshold = long_prefill_threshold
        # Sequence-parallel prefill (SURVEY §5 serving-side): fresh prompts
        # at least this long prefill sp-sharded over the device mesh —
        # TTFT scales with the sp axis instead of one chip's FLOPs.
        self.sp_prefill_threshold = sp_prefill_threshold
        # Fused multi-step decode: sample on device and feed back, one
        # host round trip per k tokens (decode_multi). 1 = classic
        # step-at-a-time.
        self.decode_steps_per_launch = decode_steps_per_launch
        # Speculative decoding: draft γ tokens (radix-tree continuation
        # first — a replayed conversation's cached generation — then
        # prompt-lookup n-grams), verify all of them in ONE chunked
        # forward (``prefill_chunk_paged``, C=γ+1), and accept per row —
        # greedy rows by longest argmax match, stochastic rows by exact
        # rejection sampling. Decode latency is weight-streaming-bound,
        # so a verified draft turns γ sequential steps into one
        # matmul-dense pass; rejected tail KV is overwritten by later
        # positional writes.
        self.spec_decode_tokens = spec_decode_tokens
        self.spec_ngram = max(2, spec_ngram)
        # Mixed compute waves (engine/waves.py, the Sarathi-Serve
        # schedule): > 0 arms the wave scheduler — while decode rows are
        # running, admission routes new prompts into an inline backlog
        # that advances up to this many prefill tokens PER WAVE on the
        # same fused chunk launch as the decode step, so a long prompt
        # stops convoying interactive streams. 0 (default) keeps the
        # legacy whole-wave alternation every existing test pins.
        self.prefill_inline_budget = max(0, prefill_inline_budget)
        self.prefill_inline_max_defer = max(0, prefill_inline_max_defer)
        # Small-batch paged crossover (ops/attention.py::select_paged):
        # decode waves narrower than this take the dense/compact gather
        # path instead of the paged kernel (the ctx-sweep ratios say
        # dispatch overhead beats the kernel at batch ≤ 8). 0 = always
        # honor default_use_kernel.
        self.paged_min_batch = max(0, paged_min_batch)
        # Last decode dispatch decision (ops.note_dispatch mirror) for
        # /debug/state — which path ran, at what batch/bucket.
        self._last_dispatch: dict | None = None
        self.log = get_logger("engine")
        # Resolved early: the KV plane (below) and the metric labels
        # (further down) both key their series on it.
        self.name = name or f"engine{next(_engine_seq)}"
        # Distributed replica (cache/mesh_cache.py): publishes advertise
        # this node's prefixes around the ring so the router can send
        # shared-prefix requests back here (radix_mesh.py:193-238).
        self.mesh = mesh
        mesh_page = getattr(mesh, "page", 1) if mesh is not None else 1
        if mesh_page > 1 and page_size % mesh_page:
            # Page-granular replication ships pool page ids; engine
            # publishes are aligned (and contiguous) at ENGINE pages, so
            # the mesh page must divide it.
            raise ValueError(
                f"mesh page_size {mesh_page} must divide engine "
                f"page_size {page_size}"
            )

        if pool is not None:
            expected = dict(
                page_size=page_size,
                num_layers=cfg.n_layers,
                num_kv_heads=cfg.n_kv_heads,
                head_dim=cfg.head_dim,
                quant=kv_quant,
            )
            if kv_quant is None:
                expected["dtype"] = cfg.dtype
            for attr, want in expected.items():
                got = getattr(pool, attr)
                if got != want:
                    raise ValueError(
                        f"external pool {attr}={got!r} incompatible with "
                        f"model/engine {attr}={want!r}"
                    )
            self.pool = pool
        else:
            pool_sharding = None
            if device_mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                if self._pp:
                    from radixmesh_tpu.parallel.pp_serving import pp_pool_spec

                    # Each pipeline stage holds only its own layers' KV,
                    # each tp chip its kv-head shard.
                    pool_sharding = NamedSharding(device_mesh, pp_pool_spec())
                else:
                    # [2, L, Hkv, slots, D]: each chip holds its kv-head
                    # shard of every page (kv_pool.py's head-major layout
                    # rationale).
                    pool_sharding = NamedSharding(
                        device_mesh, PartitionSpec(None, None, "tp", None, None)
                    )
            self.pool = PagedKVPool(
                num_slots=num_slots,
                num_layers=cfg.n_layers,
                num_kv_heads=cfg.n_kv_heads,
                head_dim=cfg.head_dim,
                page_size=page_size,
                dtype=cfg.dtype,
                sharding=pool_sharding,
                quant=kv_quant,
            )
        if kv_tier_dir is not None and host_cache_slots <= 0:
            raise ValueError(
                "kv_tier_dir requires a host tier (host_cache_slots > 0): "
                "the disk tier demotes from and restores through host RAM"
            )
        self._kv_tier = None
        if host_cache_slots > 0:
            # Hierarchical cache: HBM-evicted prefixes fall back to a
            # host-RAM tier and are restored on hit instead of recomputed
            # (cache/host_cache.py; the reference's HiCache stubs made real).
            from radixmesh_tpu.cache.host_cache import HierarchicalCache, HostKVStore

            host_store = HostKVStore(
                num_slots=host_cache_slots,
                num_layers=cfg.n_layers,
                num_kv_heads=cfg.n_kv_heads,
                head_dim=cfg.head_dim,
                page_size=page_size,
                dtype=cfg.dtype,
                quant=self.pool.quant,
            )
            if kv_tier_dir is not None:
                # Durable third tier (cache/kv_tier.py): checksummed
                # fsynced extent files behind the staged executor, so a
                # whole-cell power loss no longer erases the working
                # set (ROADMAP item 3 + cold-cell resurrection).
                from radixmesh_tpu.cache.kv_tier import DiskKVTier

                self._kv_tier = DiskKVTier(
                    kv_tier_dir,
                    capacity_bytes=kv_tier_capacity_bytes,
                    page_size=page_size,
                    name=self.name,
                )
                # Disk I/O is only reachable through the plane worker
                # (lint-pinned): a tier without the plane would be
                # write-only dead weight, so arm it.
                if not kv_transfer_async:
                    kv_transfer_async = True
                    self.log.info(
                        "kv_tier_dir set: arming the async KV-movement "
                        "plane (disk restores/spills are staged-only)"
                    )
            self.tree: RadixTree = HierarchicalCache(
                self.pool, host_store, disk_tier=self._kv_tier
            )
        else:
            self.tree = RadixTree(page_size=page_size, on_free=self.pool.free)
        self._kv_tier_watermark = float(kv_tier_watermark)
        self._kv_tier_min_heat = float(kv_tier_min_heat)
        self._kv_tier_destage_budget = int(kv_tier_destage_budget)
        # Destage cadence: the candidate walk is O(tree nodes) of
        # engine-thread Python, so it runs at most this often — not
        # per scheduler step (durability lags pressure by at most one
        # interval, which the commit-by-rename discipline tolerates).
        # 0 = every pump (tests/drills that need deterministic spills).
        self._kv_tier_destage_interval_s = float(kv_tier_destage_interval_s)
        self._kv_tier_last_destage = 0.0
        # Async KV-movement plane (cache/kv_transfer.py): host-tier
        # restores stage off the scheduling thread (requests park in
        # RESTORING while decode keeps stepping), eviction write-backs
        # materialize on the plane worker, and PREFETCH hints start
        # restores before their request arrives. Off by default — the
        # synchronous paths remain the behavior every existing test pins.
        self.kv_transfer = None
        self._kv_min_restore = max(0, kv_transfer_min_restore_tokens)
        self._restoring: list[tuple[Request, object]] = []
        # Graceful drain (policy/lifecycle.py, set via the runner's
        # begin_drain): PREFETCH hints stop converting to restores — a
        # warm-up nobody will be routed here to use must not open
        # tickets on a departing node.
        self.draining = False
        if kv_transfer_async:
            from radixmesh_tpu.cache.kv_transfer import KVTransferPlane

            self.kv_transfer = KVTransferPlane(
                chunk_tokens=kv_transfer_chunk_tokens,
                name=self.name,
            )
            if hasattr(self.tree, "host"):
                self.tree.plane = self.kv_transfer
        # Cold-cell resurrection (cache/kv_tier.py): scan the extent
        # directory, drop torn/corrupt extents, graft the verified
        # paths back as disk-resident nodes — the node serves its
        # pre-crash working set from disk even when every replica died.
        # Boot-time cold path (file I/O never runs on the serving path).
        self.resurrected = {"extents": 0, "grafted_nodes": 0,
                            "grafted_tokens": 0, "orphaned": 0, "keys": []}
        if self._kv_tier is not None:
            self.resurrected = self.tree.resurrect_from_disk()
        # Reserved scratch page: inactive decode rows write/read here.
        scratch = self.pool.alloc(page_size)
        assert scratch is not None
        self._scratch_slot = int(scratch[0])
        self._scratch_page = self._scratch_slot // page_size

        self.waiting: list[Request] = []
        # SLO seam (radixmesh_tpu/slo/runner.py): invoked with the request
        # right after its first token is recorded — the control plane's
        # prefill service-rate feedback. None = no control plane.
        self.on_first_token = None
        # Pressure latch: set on preemption, cleared when a request finishes
        # (or the batch drains). While set, admission pauses so the
        # surviving rows run to completion instead of the preempted request
        # re-admitting into the freed row and thrashing the pool forever.
        self._pressure = False
        self._rows: list[Request | None] = [None] * max_batch
        self._tokens = np.zeros(max_batch, dtype=np.int32)
        # One backing buffer, width padded to the KV block (the chunked
        # launches' blockwise attention requires it); _page_table is the
        # live [:, :max_pages] view every in-place write flows through,
        # so pp decode can pass the padded buffer without per-step copies
        # (the scratch tail never changes).
        maxp_b = _pow2_at_least(self.max_pages, floor=_KV_BLOCK_PAGES)
        self._page_table_padded = np.full(
            (max_batch, maxp_b), self._scratch_page, dtype=np.int32
        )
        self._page_table = self._page_table_padded[:, : self.max_pages]
        self._temps = np.zeros(max_batch, dtype=np.float32)
        self._top_ps = np.ones(max_batch, dtype=np.float32)
        self._top_ks = np.zeros(max_batch, dtype=np.int32)
        self._rng = jax.random.PRNGKey(rng_seed)
        # Mid-decode publish cadence (crash recovery, server/recovery.py):
        # every N generated tokens the request's grown prefix publishes
        # to the tree AND the mesh — so surviving replicas hold
        # prompt+generated-so-far and a resurrected request's re-prefill
        # is a near-pure cache hit instead of a full recompute. 0 = only
        # publish at finish/preempt (the pre-recovery behavior).
        self.stream_publish_tokens = stream_publish_tokens
        self.stats = EngineStats()

        reg = get_registry()
        lbl = {"engine": self.name}
        self._m_prompt = reg.counter(
            "radixmesh_engine_prompt_tokens_total",
            "prompt tokens admitted",
            ("engine",),
        ).labels(**lbl)
        self._m_cached = reg.counter(
            "radixmesh_engine_cached_tokens_total",
            "prompt tokens served from the radix cache",
            ("engine",),
        ).labels(**lbl)
        self._m_generated = reg.counter(
            "radixmesh_engine_generated_tokens_total",
            "tokens produced by decode",
            ("engine",),
        ).labels(**lbl)
        self._m_preempt = reg.counter(
            "radixmesh_engine_preemptions_total",
            "requests preempted under pool pressure",
            ("engine",),
        ).labels(**lbl)
        self._m_spec_proposed = reg.counter(
            "radixmesh_engine_spec_proposed_tokens_total",
            "draft tokens offered to speculative verification",
            ("engine",),
        ).labels(**lbl)
        self._m_spec_accepted = reg.counter(
            "radixmesh_engine_spec_accepted_tokens_total",
            "draft tokens accepted by speculative verification",
            ("engine",),
        ).labels(**lbl)
        self._m_spec_rejected = reg.counter(
            "radixmesh_engine_spec_rejected_tokens_total",
            "draft tokens rejected by speculative verification "
            "(conservation: proposed == accepted + rejected)",
            ("engine",),
        ).labels(**lbl)
        self._m_ttft = reg.histogram(
            "radixmesh_engine_ttft_seconds",
            "submit-to-first-token latency",
            ("engine",),
        ).labels(**lbl)
        self._m_tpot = reg.histogram(
            "radixmesh_engine_tpot_seconds",
            "batched decode step latency (== per-token latency for each "
            "active request)",
            ("engine",),
        ).labels(**lbl)
        # Per-TENANT request latency (unlike the per-engine families
        # above): the series a fleet collector (obs/aggregator.py)
        # merges bucket-by-bucket across nodes so /cluster/slo reports
        # the TRUE fleet p50/p99 per tenant — never an average of
        # per-node percentiles. Observed with the request's trace id as
        # exemplar, so a fleet p99 outlier links to its stitched trace.
        self._m_req_ttft = reg.histogram(
            "radixmesh_request_ttft_seconds",
            "submit-to-first-token latency per tenant (fleet-mergeable "
            "buckets; exemplars carry trace ids)",
            ("tenant",),
        )
        self._m_req_e2e = reg.histogram(
            "radixmesh_request_e2e_seconds",
            "submit-to-finish latency per tenant (fleet-mergeable "
            "buckets; exemplars carry trace ids)",
            ("tenant",),
        )
        self._m_hit_len = reg.histogram(
            "radixmesh_engine_prefix_hit_tokens",
            "prefix-cache hit length per admitted request (tokens)",
            ("engine",),
            buckets=TOKEN_LEN_BUCKETS,
        ).labels(**lbl)
        # Evictions by cause (obs/fleet_plane.py registration point): the
        # engine owns capacity (admission pressure) and preempt
        # (mid-decode pressure); the mesh replica owns ttl/mesh_trim.
        self._m_evicted = eviction_counters(self.name)
        # Decode step-time EWMA (seconds per token) — the fleet digest's
        # latency signal; the histogram keeps the full distribution.
        self._decode_ewma = 0.0
        # Per-shape speculative acceptance (prompt-length bucket →
        # [proposed, accepted] draft tokens): the doctor's
        # spec-efficiency rule and the ROADMAP item 1(a) adaptive-γ EWMA
        # both need acceptance BY REQUEST CLASS, which the engine-wide
        # counters above flatten away. Scheduler-thread-only writes
        # (both spec sites run inside _decode_spec).
        self._spec_shape: dict[str, list[int]] = {}
        # Token-level speed plane (obs/token_timeline.py): the per-token
        # ITL ring + stall attribution, the per-class speculation ledger
        # (and its adaptive-γ controller, off unless spec_adaptive), and
        # the per-tenant goodput decomposition. The timeline/goodput pair
        # keeps the one-branch-when-off contract in _consume_token; the
        # ledger always exists — spec counting must stay conserved
        # whether or not anyone is watching.
        from radixmesh_tpu.obs.token_timeline import (
            GoodputLedger, SpecLedger, TokenTimeline,
        )

        self.spec_ledger = SpecLedger(adaptive=spec_adaptive, node=self.name)
        self.timeline = None
        self.goodput = None
        if token_timeline_capacity > 0:
            self.timeline = TokenTimeline(
                capacity=token_timeline_capacity,
                stall_threshold_s=token_stall_threshold_s,
                node=self.name,
            )
            self.goodput = GoodputLedger(node=self.name)
        # Stall-attribution hints: the instant the last WHOLE prefill
        # wave launched (prefill_convoy), the instant the last INLINE
        # prefill chunk rode a mixed wave (prefill_inline — distinct on
        # purpose: the bounded mitigation must not read as the convoy it
        # replaces, nor as an unexplained scheduler_wait), and a one-shot
        # cause latch external planes set via hint_stall()
        # (rebalance_handoff).
        self._last_prefill_t = 0.0
        self._last_inline_prefill_t = 0.0
        self._stall_hint: str | None = None
        # Mixed-wave state (engine/waves.py): the inline prefill backlog
        # — requests that acquired slots + a batch row but advance their
        # prefill chunk-by-chunk inside decode waves — and the rows they
        # reserve (kept OUT of _rows until install so every decode-path
        # iteration over _rows stays oblivious to them).
        self._inline: list[_InlineJob] = []
        self._inline_rows: set[int] = set()
        self.waves = None
        if self.prefill_inline_budget > 0:
            from radixmesh_tpu.engine.waves import WaveScheduler

            self.waves = WaveScheduler(
                inline_budget=self.prefill_inline_budget,
                max_defer=self.prefill_inline_max_defer,
                chunk=self.prefill_chunk,
                boost_tokens=self.prefill_wave_tokens,
                node=self.name,
            )
        # Request-flight tracing lane for engine-scope (not per-request)
        # events: evictions, preemption sweeps (obs/trace_plane.py).
        self._trace_lane = f"engine:{self.name}"
        # TPU step attribution (obs/step_plane.py): per-wave tokens,
        # pad fraction, and an analytic-FLOPs MFU estimate. OFF by
        # default — the wave hot paths keep the one-branch-when-off
        # contract (a single `is not None` test per wave).
        self.step_acct = None
        # Padded-token count of the LAST prefill launch, set by whichever
        # prefill path ran (single scheduler thread): the launch SHAPE
        # lives inside each path, so this is how the wave accounting in
        # _admit learns it without re-deriving bucket math.
        self._wave_padded = 0
        if step_accounting:
            from radixmesh_tpu.obs.step_plane import StepAccounting

            n_params = sum(
                int(np.prod(p.shape))
                for p in jax.tree_util.tree_leaves(self.params)
                if hasattr(p, "shape")
            )
            self.step_acct = StepAccounting(
                self.name, n_params, peak_tflops=peak_tflops
            )

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def make_request(
        self,
        prompt: Sequence[int],
        sampling: SamplingParams | None = None,
        *,
        tenant: str = "default",
        ttft_deadline_s: float | None = None,
        e2e_deadline_s: float | None = None,
        resume_tokens: Sequence[int] | None = None,
        trace_id: int | None = None,
    ) -> Request:
        """Build + validate a request WITHOUT queueing it — the admission
        seam the SLO control plane (``radixmesh_tpu/slo/``) holds requests
        behind before deciding to :meth:`enqueue` or shed them.

        ``resume_tokens`` switches on **resume admission** (crash
        recovery, ``server/recovery.py``): the tokens are output a prior
        life of this request already delivered to its client. They are
        appended to the prompt — so prefill replays them against the
        radix cache (a near-pure hit when the crashed node's publishes
        replicated) and the first sampled token is the CONTINUATION at
        position ``len(prompt)+len(resume_tokens)`` — but they are never
        re-emitted: ``output_tokens`` starts empty and
        ``sampling.max_new_tokens`` is debited by the tokens already
        delivered, so the request's total output budget is unchanged
        across lives."""
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt, dtype=np.int32)
        resume_offset = 0
        if resume_tokens is not None and len(resume_tokens) > 0:
            resume = np.asarray(resume_tokens, dtype=np.int32)
            resume_offset = len(resume)
            if resume_offset >= sampling.max_new_tokens:
                # The delivered tokens already cover the whole output
                # budget: there is NOTHING to resume, and admitting
                # would sample a token the first life never would have
                # (output past the requested cap). The edge holds the
                # complete stream — refuse loudly instead.
                raise ValueError(
                    f"resume_tokens ({resume_offset}) cover the full "
                    f"max_new_tokens budget ({sampling.max_new_tokens}) "
                    "— the stream is already complete"
                )
            prompt = np.concatenate([prompt, resume])
            sampling = dataclass_replace(
                sampling,
                max_new_tokens=sampling.max_new_tokens - resume_offset,
            )
        req = Request(
            prompt=prompt,
            sampling=sampling,
            tenant=tenant,
            ttft_deadline_s=ttft_deadline_s,
            e2e_deadline_s=e2e_deadline_s,
            resume_offset=resume_offset,
        )
        if not (0 < len(req.prompt) < self.max_seq_len):
            raise ValueError(f"prompt length {len(req.prompt)} out of range")
        if resume_offset:
            self.stats.resurrections += 1
            # The whole resumed prompt is replay: the original prompt AND
            # the delivered tokens all re-prefill on the new node.
            self.stats.replayed_tokens += len(prompt)
        req.submit_time = time.monotonic()
        # Request-flight tracing (obs/trace_plane.py): returns None when
        # tracing is off or the request lost the sampling coin flip —
        # every downstream span site is then one `is not None` branch.
        # ``trace_id`` ADOPTS an upstream node's 64-bit id (a resume or
        # hedge re-route carries it in the /generate body, PR 9 cross-
        # node stitching), so this node's spans land in the originating
        # request's timeline instead of under a fresh id.
        req.trace = get_recorder().trace(
            f"req:{req.rid}", trace_id=trace_id, node=self.name
        )
        return req

    def enqueue(self, req: Request) -> Request:
        """Hand a built request to the scheduler queue."""
        self.waiting.append(req)
        return req

    def add_request(
        self,
        prompt: Sequence[int],
        sampling: SamplingParams | None = None,
        **kw,
    ) -> Request:
        return self.enqueue(self.make_request(prompt, sampling, **kw))

    def cancel(self, rid: int) -> bool:
        """Abort a queued or running request. Running requests release
        their batch row; KV computed so far publishes to the radix cache
        as usual (it is a valid prefix for future hits). The request
        finishes with whatever output it had — callers check
        ``req.cancelled``. Returns False for unknown/finished rids.
        NOT thread-safe against a concurrent ``step``; serialize through
        the owner (``server/http_frontend.py::EngineRunner.cancel``)."""
        for i, req in enumerate(self.waiting):
            if req.rid == rid:
                self.waiting.pop(i)
                req.cancelled = True
                req.state = RequestState.FINISHED
                self.stats.finished += 1
                return True
        for req in self._rows:
            if req is not None and req.rid == rid:
                req.cancelled = True
                req.state = RequestState.FINISHED
                self.stats.finished += 1
                self._release(req)
                self._pressure = False  # freed a row: resume admission
                return True
        for i, (req, ticket) in enumerate(self._restoring):
            if req.rid == rid:
                # Cancel mid-restore: unlink the request; the ticket runs
                # to completion (the landed KV is a valid warm cache
                # entry) and the pump auto-releases its eviction shields,
                # so the protected pages become evictable again.
                req.cancelled = True
                req.state = RequestState.FINISHED
                self.stats.finished += 1
                ticket.auto_release = True
                self._restoring.pop(i)
                return True
        for i, job in enumerate(self._inline):
            if job.req.rid == rid:
                # Cancel mid-inline-prefill: the job never installed, so
                # nothing published — release the row reservation, the
                # prefix lock, and the acquired pages (partially-written
                # chunk KV is discarded with them).
                self._inline.pop(i)
                self._inline_rows.discard(job.row)
                req = job.req
                if job.own.size:
                    self.pool.free(job.own)
                if req.lock_node is not None:
                    self.tree.dec_lock_ref(req.lock_node)
                    req.lock_node = None
                req.cancelled = True
                req.state = RequestState.FINISHED
                self.stats.finished += 1
                return True
        return False

    def cancel_all(self) -> int:
        """Abort every queued and running request (shutdown sweep).
        Returns the number cancelled."""
        rids = (
            [r.rid for r in self.waiting]
            + [r.rid for r in self._rows if r is not None]
            + [r.rid for r, _ in self._restoring]
            + [j.req.rid for j in self._inline]
        )
        return sum(1 for rid in rids if self.cancel(rid))

    # ------------------------------------------------------------------
    # graceful drain (policy/lifecycle.py, serialized via the runner)
    # ------------------------------------------------------------------

    def drain_requeue_waiting(self) -> int:
        """Cancel-and-flag every QUEUED and parked-RESTORING request for
        requeue at the router: they have produced nothing, so bouncing
        them to a surviving node loses no work — while RUNNING rows are
        deliberately left alone to finish under the drain deadline.
        The ``drain_requeue`` shed reason tells the client (and the
        chaos workload) to resubmit via the router, not give up.
        Restore tickets flip to auto-release (the existing cancel path),
        so no eviction shield outlives the departing request.
        Mid-inline-prefill requests count too: they have not produced a
        token either (only partial KV, discarded by cancel), so bouncing
        them loses at most one chunk of compute."""
        victims = (
            list(self.waiting)
            + [r for r, _ in self._restoring]
            + [j.req for j in self._inline]
        )
        n = 0
        for req in victims:
            req.shed = True
            req.shed_reason = "drain_requeue"
            if self.cancel(req.rid):
                n += 1
        return n

    def drain_flush_hot(self) -> int:
        """Write every unlocked device-resident prefix back to the host
        tier — the PR 4 fused write-back lane does the moving (one
        gather per sweep; arena writes land on the plane worker) — so a
        warm rejoin, or a sibling's restore, finds the working set
        instead of recomputing it. Returns tokens written back; 0
        without a host tier. Run AFTER in-flight decodes finish: evict
        only touches unlocked entries, so flushing early would silently
        skip everything a running request still pins."""
        tree = self.tree
        if getattr(tree, "host", None) is None:
            return 0
        total = 0
        while True:
            n = tree.evictable_size_
            if n <= 0:
                break
            freed = tree.evict(n)
            if freed <= 0:
                break
            total += freed
        return total

    def drain_flush_disk(self, timeout_s: float = 30.0) -> tuple[int, bool]:
        """Drain step: flush hot subtrees DISK-ward — force-destage
        every host-resident prefix to checksummed extents and wait for
        the commits, so the working set survives even if the whole cell
        (this node included) later loses power before a rejoin. Returns
        ``(spills submitted, all committed)``; (0, True) without a
        tier. Run after :meth:`drain_flush_hot` so the device flush has
        landed in the arena first."""
        tree = self.tree
        plane = self.kv_transfer
        if self._kv_tier is None or plane is None:
            return 0, True
        submitted = tree.destage_cold(force=True, budget=1 << 30)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            plane.pump(tree)  # spill commits land on this (engine) thread
            if plane.spills_idle():
                return submitted, True
            plane.wait_progress(0.02)
        plane.pump(tree)
        return submitted, plane.spills_idle()

    def announce_resurrected(self) -> int:
        """Re-announce resurrected prefixes through the existing
        bootstrap/SHARD_SUMMARY path: each grafted key re-enters the
        mesh tree via the normal insert (owner-addressed under
        sharding), so summaries, fingerprints, and pull-through routing
        advertise the disk-resident working set exactly like a live
        one. Call after the mesh transport is up. Returns keys
        announced."""
        mesh = self.mesh
        keys = self.resurrected.get("keys") or []
        if mesh is None or not keys:
            return 0
        n = 0
        for key in keys:
            key = np.asarray(key, dtype=np.int32)
            if len(key) == 0:
                continue
            # Advertisement-only insert (AdvertisedValue): replicas
            # store origin-rank tags anyway, this node serves the
            # prefix through a staged disk restore at admission time,
            # and the placeholder indices are never pool-freed.
            mesh.insert(
                key, np.arange(len(key), dtype=np.int32), advertise=True
            )
            n += 1
        return n

    def step(self) -> None:
        """One scheduler iteration — ONE compute wave. Legacy schedule
        (``prefill_inline_budget == 0``): admit+prefill queued requests
        to completion, then one batched decode step for everything
        running. Mixed schedule (budget > 0, engine/waves.py): while
        decode rows are running, admission parks new prompts in the
        inline backlog and each wave packs the decode step PLUS a
        budget-bounded slice of their chunked prefill into a single
        fused launch — long prompts advance between decode steps instead
        of convoying them."""
        self._admit()
        if self._inline:
            self._wave_step()
            return
        if any(r is not None for r in self._rows):
            self._decode_once()
        elif not self.waiting and (
            self._restoring
            or (
                self.kv_transfer is not None
                and self.kv_transfer.has_engine_work()
            )
        ):
            # Nothing to decode and nothing admittable: the only live
            # work is an in-flight restore (a parked request's or a
            # prefetch hint's) — yield to the plane worker instead of
            # busy-spinning the scheduler loop against it.
            self.kv_transfer.wait_progress()

    def has_work(self) -> bool:
        return (
            bool(self.waiting)
            or bool(self._inline)
            or bool(self._restoring)
            or any(r is not None for r in self._rows)
            or (
                self.kv_transfer is not None
                and self.kv_transfer.has_engine_work()
            )
        )

    def _wave_step(self) -> None:
        """Run one wave while the inline backlog is non-empty: ask the
        wave scheduler for the wave's composition, execute it, commit
        the defer/metric accounting. Decode-bearing plans fuse the
        inline chunks into the decode launch itself; prefill/boost
        plans advance the backlog alone (and count against the
        starvation bound when decode rows are waiting)."""
        decode_rows = sum(1 for r in self._rows if r is not None)
        plan = self.waves.plan(
            decode_rows, [j.total - j.pos for j in self._inline]
        )
        if plan.decode and decode_rows:
            if self._seeded_launch(self._rows):
                # All-seeded batches keep the canonical per-row
                # (seed, position) decode launch bit-identical to the
                # legacy path (the replay-determinism contract), so the
                # wave runs as two launches: decode, then the budgeted
                # inline slice.
                self._decode_once()
                self._inline_advance(plan.allot)
            else:
                self._decode_once(inline_allot=plan.allot)
        else:
            self._inline_advance(plan.allot)
        self.waves.note(plan)

    def _inline_advance(self, allot: list[int]) -> None:
        """Advance the inline backlog WITHOUT a decode step: prefill and
        boost waves, plus the second launch of the all-seeded fallback.
        Same fused chunk builder, decode disabled."""
        if not any(allot):
            return
        self._decode_spec_once(0, {}, None, inline=allot, decode=False)

    def _note_decode_time(self, per_token_s: float) -> None:
        """Funnel for every decode-latency sample: the TPOT histogram
        keeps the distribution; the EWMA is the fleet digest's compact
        latency signal (obs/fleet_plane.py)."""
        self._m_tpot.observe(per_token_s)
        if self._decode_ewma == 0.0:
            self._decode_ewma = per_token_s
        else:
            self._decode_ewma += 0.2 * (per_token_s - self._decode_ewma)

    def telemetry(self) -> dict:
        """Point-in-time engine signals for the fleet digest
        (``obs/fleet_plane.py::FleetPlane.build_digest``). Lock-free
        snapshot reads, same rationale as the /debug endpoints: a wedged
        engine must still be describable — that is exactly when the
        stall watchdog needs this data."""
        rows = sum(1 for r in self._rows if r is not None)
        host = getattr(self.tree, "host", None)
        host_fill = 0.0
        if host is not None and getattr(host, "num_slots", 0):
            host_fill = 1.0 - host.free_slots / host.num_slots
        return {
            "batch_occupancy": rows / max(1, self.max_batch),
            # Parked-for-restore and inline-prefilling requests count as
            # waiting: they are queued demand the fleet plane should
            # see, just queued on a KV transfer / the wave scheduler's
            # chunk budget instead of a batch row.
            "waiting": len(self.waiting) + len(self._restoring)
            + len(self._inline),
            "decode_steps": self.stats.decode_steps,
            "decode_ewma_s": self._decode_ewma,
            "cache_hit_rate": self.stats.hit_rate,
            "pool_fill": 1.0 - self.pool.fill_free_fraction(),
            "host_fill": host_fill,
            "evictions": {
                c: int(m.value) for c, m in self._m_evicted.items()
            },
            "spec": self.spec_report(),
            # Durable tier occupancy (None without a tier): lock-guarded
            # snapshot reads inside stats().
            "kv_tier": (
                None if self._kv_tier is None else self._kv_tier.stats()
            ),
        }

    def spec_report(self) -> dict:
        """Per-shape speculative acceptance (prompt-length bucket →
        proposed/accepted draft tokens + acceptance rate) — the
        spec-efficiency evidence the doctor's rule and /cluster/telemetry
        surface, and the substrate the item-1(a) adaptive-γ EWMA will
        fold. Snapshot read, same lock-free rationale as telemetry() —
        but unlike telemetry()'s fixed-key dicts, _spec_shape GROWS when
        the scheduler sees a new prompt bucket, so take the one-C-call
        list() snapshot before iterating (a dict comprehension over the
        live dict can raise dictionary-changed-size mid-GET)."""
        cells = list(self._spec_shape.items())
        return {
            shape: {
                "proposed": int(p),
                "accepted": int(a),
                "acceptance": round(a / p, 4) if p else 0.0,
            }
            for shape, (p, a) in sorted(cells)
        }

    def generate(
        self,
        prompts: Iterable[Sequence[int]],
        sampling: SamplingParams | None = None,
        max_steps: int = 100_000,
    ) -> list[list[int]]:
        reqs = [self.add_request(p, sampling) for p in prompts]
        for _ in range(max_steps):
            if not self.has_work():
                break
            self.step()
        unfinished = [r.rid for r in reqs if r.state is not RequestState.FINISHED]
        if unfinished:
            # A real error, not an assert (VERDICT round-1 weak #5):
            # surfaces under ``python -O`` too, and says which requests
            # and why the loop stopped.
            raise RuntimeError(
                f"generate() exhausted max_steps={max_steps} with requests "
                f"{unfinished} unfinished (pool too small for the workload, "
                f"or a scheduling stall)"
            )
        return [r.generated for r in reqs]

    # ------------------------------------------------------------------
    # admission + prefill
    # ------------------------------------------------------------------

    def _free_row(self) -> int:
        for i, r in enumerate(self._rows):
            # Rows parked behind an inline prefill job hold a batch seat
            # but no Request yet (the install happens on the job's final
            # chunk) — not free.
            if r is None and i not in self._inline_rows:
                return i
        return -1

    def _alloc_pages(self, n_pages: int, cause: str = "capacity") -> np.ndarray | None:
        """Whole-page allocation with evict-under-pressure retry (the
        reference's evict-then-insert flow, ``radix_cache.py:179-202``).
        ``cause`` labels any eviction this allocation forces ("capacity"
        = admission pressure, "preempt" = mid-decode page growth — the
        storm detector and dashboards tell them apart)."""
        n = n_pages * self.page_size
        slots = self.pool.alloc(n)
        if slots is None:
            rec = get_recorder()
            t_ev = time.monotonic() if rec.enabled else 0.0
            if self.mesh is not None:
                # Eviction that DESTROYS KV must un-advertise the prefix
                # ring-wide — otherwise the router keeps routing
                # shared-prefix requests to a node that can no longer serve
                # them. The hook fires per destroyed node only: host-tier
                # trees invoke it just when write-back fails (a written-back
                # prefix stays servable via restore, so it stays
                # advertised).
                freed = self.tree.evict(
                    n - self.pool.free_slots, on_evict=self._unadvertise
                )
            else:
                freed = self.tree.evict(n - self.pool.free_slots)
            if freed:
                self._m_evicted[cause].inc(freed)
            slots = self.pool.alloc(n)
            if rec.enabled:
                rec.event(
                    self._trace_lane, "evict", t_ev,
                    time.monotonic() - t_ev, cat="cache",
                    need_slots=int(n), satisfied=bool(slots is not None),
                )
        return slots

    def _unadvertise(self, node) -> None:
        """Evict hook: release the node's pool slots (``on_evict`` replaces
        the tree's ``on_free`` batch, so freeing is this hook's job) and
        best-effort retract the prefix ring-wide: mesh replicas only apply
        (and replicate) the DELETE when the key lands on an unlocked leaf
        there, so a prefix another node extended survives."""
        self.pool.free(np.asarray(node.value, dtype=np.int32))
        parts = []
        while node is not None and node.parent is not None:
            parts.append(node.key)
            node = node.parent
        if parts:
            self.mesh.delete(np.concatenate(parts[::-1]))

    def _admit(self) -> None:
        """Admit waiting requests into free rows. Concurrent arrivals are
        prefilled as ONE batched chunked-paged call + ONE batched sample
        (VERDICT round-1 weak #5: per-request serial prefill made TTFT
        degrade linearly with queue depth); a lone short request keeps the
        dense single-request path (smallest-latency compile variant)."""
        self._pump_kv_transfer()
        if self._pressure and any(r is not None for r in self._rows):
            return
        self._pressure = False  # batch drained: safe to admit again
        made_progress = True
        while self.waiting and made_progress:
            # Mixed compute waves (engine/waves.py): while decode rows
            # are running, acquired prompts park in the inline backlog
            # instead of prefilling here — _wave_step rides their chunks
            # on the decode launches, budget-bounded, so the running
            # streams never see a whole-prefill convoy. With no decode
            # rows (cold start / drained batch) the legacy bulk subwave
            # path below keeps its full-width TTFT.
            mix = self.waves is not None and any(
                r is not None for r in self._rows
            )
            group: list[tuple] = []
            idx = 0
            while idx < len(self.waiting):
                row = self._free_row()
                if row < 0:
                    break
                req = self.waiting[idx]
                # One tree walk serves both the defer check and acquisition
                # (match_and_load also restores host-tier KV, so a
                # restorable prefix never triggers a needless deferral).
                tr = req.trace
                t_match = time.monotonic() if tr is not None else 0.0
                if hasattr(self.tree, "match_and_load"):
                    match = None
                    if self.kv_transfer is not None:
                        match = self.tree.match_prefix(req.prompt)
                        if match.host_nodes or match.disk_nodes:
                            # Disk extensions ALWAYS park (extent reads
                            # are staged-only); host-only extensions
                            # park past the min-restore threshold.
                            if match.disk_nodes or (
                                match.host_length + match.disk_length
                                >= self._kv_min_restore
                            ):
                                if self._park_for_restore(req, match):
                                    self.waiting.pop(idx)
                                    continue  # parked; don't advance idx
                                # Park failed: begin_restore may have
                                # EVICTED for room, so the walked match
                                # can hold stale slots — re-walk.
                                match = None
                            else:
                                # Small restore: tree untouched since the
                                # walk — hand the match to the sync path
                                # (one walk total, not two).
                                match = self.tree.match_and_load(
                                    req.prompt, match=match
                                )
                    if match is None:
                        match = self.tree.match_and_load(req.prompt)
                else:
                    match = self.tree.match_prefix(req.prompt)
                if tr is not None:
                    tr.add(
                        "prefix_match",
                        t_match,
                        time.monotonic() - t_match,
                        cached_tokens=int(match.length),
                        prompt_tokens=len(req.prompt),
                    )
                if self._defer_for_prefix_wave(req, match.length, group):
                    # Admitting this request NOW would recompute a prefix a
                    # groupmate is about to publish; next wave it's a cache
                    # hit instead (the serial-admission sharing the batch
                    # path would otherwise lose).
                    idx += 1
                    continue
                acquired = self._acquire_prompt_slots(req, match)
                if acquired is None:
                    break  # pool exhausted even after evict: wait for finishes
                self.waiting.pop(idx)
                if tr is not None:
                    # Queue wait: preemption requeue, SLO dispatch, or
                    # submission — whichever happened LAST — up to the
                    # instant a batch row was secured (a preempted
                    # request's first life must not render as queueing).
                    t_start = max(
                        req.requeue_time, req.admit_time, req.submit_time
                    )
                    tr.add(
                        "admission_wait",
                        t_start,
                        time.monotonic() - t_start,
                        cat="queue",
                    )
                reuse, prefix_slots, own = acquired
                self._rows[row] = req  # reserve the row; re-set on install
                group.append((req, row, reuse, prefix_slots, own))
            for _, row, *_ in group:
                self._rows[row] = None
            made_progress = bool(group)
            if not group:
                break
            if mix:
                for req, row, reuse, prefix_slots, own in group:
                    total = len(req.prompt)
                    self._inline.append(
                        _InlineJob(
                            req=req,
                            row=row,
                            reuse=reuse,
                            own=own,
                            token_slots=np.concatenate(
                                [prefix_slots, own[: total - reuse]]
                            ),
                            pos=reuse,
                            total=total,
                        )
                    )
                    # Reserve the batch seat without a Request in it:
                    # the request stays QUEUED (state-machine-wise it is
                    # still waiting for its first token) until the final
                    # chunk installs it RUNNING.
                    self._inline_rows.add(row)
                continue
            # Sub-waves by prefill-size bucket, shortest first: a short
            # request must not ride as a padded row through a 32k
            # groupmate's chunks, nor wait for them to sample its first
            # token. Each sub-wave finalizes itself (one batched
            # sample + one device→host sync), so TTFT is bounded by the
            # request's own bucket.
            def bucket(member):
                # UNCAPPED size bucket: a 512-token prompt must not share a
                # sub-wave (and its finalize) with a 32k prompt's chunk
                # loop. (The chunk SHAPE inside _prefill_group stays capped
                # at prefill_chunk.)
                n_new = len(member[0].prompt) - member[2]
                return _pow2_at_least(n_new, floor=16)

            group.sort(key=bucket)
            subwaves: list[list[tuple]] = []
            start = 0
            for i in range(1, len(group) + 1):
                if i == len(group) or bucket(group[i]) != bucket(group[start]):
                    sub = group[start:i]
                    start = i
                    # Slice the bucket at the compute-saturating width
                    # (see ``prefill_wave_tokens``): slices finalize their
                    # first tokens as they complete instead of convoying
                    # behind the whole bucket.
                    per_chunk = min(bucket(sub[0]), self.prefill_chunk)
                    rows = max(1, self.prefill_wave_tokens // per_chunk)
                    subwaves.extend(
                        sub[j : j + rows] for j in range(0, len(sub), rows)
                    )
            for sub in subwaves:
                # Quantized pools always prefill through the chunked
                # paged path: it attends the already-quantized K/V
                # (see prefill_chunk_paged), so prefill-time logits
                # match every later read of the published prefix. The
                # dense/sp paths attend full-precision and only
                # quantize at pool.write — fine for bf16 pools, an
                # invariant break for int8.
                # pp engines prefill exclusively through the chunked
                # paged path: it is the pipeline-scheduled one (the
                # dense/sp paths would all-gather stage weights).
                traced = [m[0].trace for m in sub if m[0].trace is not None]
                acct = self.step_acct
                t_wave = time.monotonic() if traced or acct is not None else 0.0
                self._wave_padded = 0
                if (
                    self.pool.quant is None
                    and not self._pp
                    and (len(sub) == 1 and self._sp_capable(sub[0]))
                ):
                    pending = [self._prefill_sp(*sub[0])]
                elif (
                    self.pool.quant is None
                    and not self._pp
                    and len(sub) == 1
                    and len(sub[0][0].prompt) - sub[0][2]
                    <= self.long_prefill_threshold
                ):
                    pending = [self._prefill_dense(*sub[0])]
                else:
                    pending = self._prefill_group(sub)
                self._finalize_first_tokens(pending)
                if traced or acct is not None:
                    dur = time.monotonic() - t_wave
                    new_tok = sum(len(m[0].prompt) - m[2] for m in sub)
                    if acct is not None:
                        # Step attribution (obs/step_plane.py): the wave's
                        # real vs launched-shape tokens — each prefill
                        # path stamped its padded count (_wave_padded).
                        acct.note_wave(
                            "prefill",
                            new_tok,
                            self._wave_padded,
                            dur,
                            rows=len(sub),
                        )
                    # One prefill-wave span per traced member (covers the
                    # whole sub-wave through first-token finalize, so each
                    # request's lane shows the convoy it rode in).
                    for tr in traced:
                        tr.add(
                            "prefill_wave",
                            t_wave,
                            dur,
                            cat="prefill",
                            wave_rows=len(sub),
                            wave_new_tokens=int(new_tok),
                        )

    # ------------------------------------------------------------------
    # async KV-movement plane seams (cache/kv_transfer.py)
    # ------------------------------------------------------------------

    def _pump_kv_transfer(self) -> None:
        """Engine-thread service point for the plane, run at the top of
        every admission pass: apply staged restore scatters (the only
        place the plane touches the donated pool buffer), re-queue parked
        requests whose pages landed, and convert prefetch hints into
        no-request restores."""
        plane = self.kv_transfer
        if plane is None:
            return
        plane.pump(self.tree)
        for key in plane.take_hints():
            self._apply_prefetch_hint(key)
        if self._kv_tier is not None and not self.draining:
            # Write-behind destage (cache/kv_tier.py): past the arena
            # watermark, cold-ish host prefixes spill to disk extents
            # on the plane worker, so later arena pressure DEMOTES
            # (free) instead of DROPPING (data loss). In-memory
            # submission only — file I/O stays off this thread — and
            # cadence-throttled: the candidate walk is O(tree), not
            # per-step work.
            now = time.monotonic()
            if (
                now - self._kv_tier_last_destage
                >= self._kv_tier_destage_interval_s
            ):
                self._kv_tier_last_destage = now
                self.tree.destage_cold(
                    watermark=self._kv_tier_watermark,
                    min_heat=self._kv_tier_min_heat,
                    budget=self._kv_tier_destage_budget,
                )
        if not self._restoring:
            return
        still: list[tuple[Request, object]] = []
        for req, ticket in self._restoring:
            if not ticket.done:
                still.append((req, ticket))
                continue
            plane.finish_ticket(self.tree, ticket)
            req.state = RequestState.QUEUED
            self.waiting.insert(0, req)
            tr = req.trace
            if tr is not None:
                tr.add(
                    "kv_restore", ticket.t0,
                    time.monotonic() - ticket.t0, cat="kv",
                    tokens=int(ticket.tokens),
                )
        self._restoring = still

    def _restore_alloc(self, n_tokens: int) -> np.ndarray | None:
        """Device slots for a staged restore, evicting (plain drop, no
        write-back — see ``evict_no_writeback``) under pressure."""
        dev = self.pool.alloc(n_tokens)
        if dev is None:
            freed = self.tree.evict_no_writeback(
                n_tokens - self.pool.free_slots
            )
            if freed:
                self._m_evicted["capacity"].inc(freed)
            dev = self.pool.alloc(n_tokens)
        return dev

    def _park_for_restore(self, req: Request, match) -> bool:
        """Move ``req`` into the RESTORING state behind a staged-restore
        ticket. Returns False when nothing could be restored (pool
        exhausted even after eviction) — the caller falls back to the
        synchronous path, which degrades to a shorter hit."""
        ticket = self.kv_transfer.begin_restore(
            self.tree, match, alloc=self._restore_alloc
        )
        if ticket is None:
            return False
        req.state = RequestState.RESTORING
        self._restoring.append((req, ticket))
        return True

    def _apply_prefetch_hint(self, key: np.ndarray) -> None:
        """Start a no-request restore for a routed-ahead prefix. Hints
        are strictly weaker than admissions: read-only match (no node
        splits), allocation straight from the free list (never evicts),
        joined with any in-flight restore of the same nodes — so a
        duplicate, stale, or raced hint degrades to a no-op."""
        plane = self.kv_transfer
        if plane is None or not hasattr(self.tree, "match_and_load"):
            return
        if self.draining:
            # Drain races a router hint: the router stops hinting once
            # the DRAINING state gossips, but frames already in flight
            # land here — drop them (counted) instead of opening a
            # restore ticket nothing will ever be routed here to use.
            plane.count_hint("draining")
            return
        match = self.tree.match_prefix(key, split_partial=False)
        if not match.host_nodes and not match.disk_nodes:
            plane.count_hint("noop")
            return
        ticket = plane.begin_restore(
            self.tree, match, alloc=self.pool.alloc, auto_release=True
        )
        plane.count_hint("started" if ticket is not None else "noop")

    def _defer_for_prefix_wave(
        self, req: Request, cached: int, group: list[tuple]
    ) -> bool:
        """True if ``req`` shares ≥1 page of NOT-yet-cached prefix (beyond
        its ``cached`` match length) with a request already collected this
        wave — or parked in the inline backlog (mixed waves): either one
        will publish that span, so waiting turns recomputation into a
        hit."""
        peers = [g[0] for g in group] + [j.req for j in self._inline]
        if not peers:
            return False
        prompt = req.prompt
        span = cached - cached % self.page_size + self.page_size
        if len(prompt) < span:
            return False
        head = prompt[:span]
        return any(
            len(p.prompt) >= span and np.array_equal(p.prompt[:span], head)
            for p in peers
        )

    def _acquire_prompt_slots(
        self, req: Request, match=None
    ) -> tuple[int, np.ndarray, np.ndarray] | None:
        """Lock the longest cached prefix of ``req.prompt`` and allocate
        pages for the remainder. Returns ``(reuse, prefix_slots, own)``, or
        ``None`` after full rollback if the pool can't satisfy it. Reuse is
        page-aligned and always leaves ≥1 token uncached so prefill has
        logits to sample the first output token from. ``match`` may carry a
        just-computed match result to avoid a second tree walk."""
        prompt = req.prompt
        if match is None:
            # Hierarchical trees restore host-resident extensions into
            # device slots as part of the match (host→HBM copy beats a
            # recompute).
            if hasattr(self.tree, "match_and_load"):
                match = self.tree.match_and_load(prompt)
            else:
                match = self.tree.match_prefix(prompt)
        reuse = min(
            match.length, (len(prompt) - 1) // self.page_size * self.page_size
        )
        prefix_slots = match.indices()[:reuse]
        self.tree.inc_lock_ref(match.last_node)
        req.lock_node = match.last_node
        own = self._alloc_pages(-(-(len(prompt) - reuse) // self.page_size))
        if own is None:
            self.tree.dec_lock_ref(req.lock_node)
            req.lock_node = None
            return None
        return reuse, prefix_slots, own

    def _install_running(self, req: Request, row: int, reuse: int) -> None:
        """Shared tail of admission when the first token is ALREADY known
        (disaggregated handoff): install + record TTFT in one go."""
        self._install_prefilled(req, row, reuse)
        self._record_first_token(req)

    def _install_prefilled(
        self, req: Request, row: int, reuse: int, inline: bool = False
    ) -> None:
        """Mark RUNNING, record stats, publish the prompt
        (``cache_unfinished_req``, ``radix_cache.py:488-519``), and wire the
        decode row. ``req.kv_len``/``token_slots``/``own_slots`` must be
        set. The first token MAY still be in flight on device (collocated
        admission defers the sample sync until every wave of the admission
        round has been dispatched — one device→host round trip total);
        ``_finalize_first_tokens`` fills it in before decode runs."""
        req.prefix_len = reuse
        req.state = RequestState.RUNNING
        req.row = row

        self.stats.prefills += 1
        # Stall attribution (obs/token_timeline.py): a decode gap that
        # spans this instant is a prefill convoy, not a scheduler stall
        # — UNLESS the prefill was a budget-bounded inline chunk riding
        # the decode wave, which gets its own (non-convoy) cause so the
        # mitigation cannot masquerade as the disease it cures.
        if inline:
            self._last_inline_prefill_t = time.monotonic()
        else:
            self._last_prefill_t = time.monotonic()
        self.stats.prompt_tokens += len(req.prompt)
        self.stats.cached_tokens += reuse
        self._m_prompt.inc(len(req.prompt))
        self._m_cached.inc(reuse)
        self._m_hit_len.observe(reuse)
        if req.resume_offset:
            # Resurrection hit accounting: the whole resumed prompt
            # (original prompt + delivered tokens) is replay; the cache
            # served ``reuse`` of it. The chaos gate pins the fleet-wide
            # ratio ≥ 0.8 — replay must be a hit, not a recompute.
            self.stats.replayed_cached_tokens += reuse

        self._publish(req, len(req.prompt))

        self._rows[row] = req
        if req.output_tokens:
            self._tokens[row] = req.output_tokens[-1]
        self._temps[row] = req.sampling.temperature
        self._top_ps[row] = req.sampling.top_p
        self._top_ks[row] = req.sampling.top_k
        self._page_table[row] = self._scratch_page
        n_pages = -(-req.kv_len // self.page_size)
        self._page_table[row, :n_pages] = (
            req.token_slots[:: self.page_size] // self.page_size
        )

    @staticmethod
    def _seeded_launch(rows: Iterable[Request]) -> bool:
        """True when EVERY row is seeded — the replay-determinism
        contract's scope. A mixed batch samples from the global stream
        (documented best-effort): determinism is a per-launch contract,
        never a cross-request entanglement."""
        rows = [r for r in rows if r is not None]
        return bool(rows) and all(
            r.sampling.seed is not None for r in rows
        )

    def _seed_key(self, req: Request) -> jax.Array:
        """Canonical per-row sampling key: a pure function of (seed,
        absolute token position). ``req.num_tokens`` IS the position of
        the token about to be drawn — and for a resumed request
        (``resume_offset``) the delivered tokens ride in the prompt, so
        positions line up exactly across lives."""
        # Mix the seed BEFORE combining with the position: a shift-then-
        # mask would throw away the seed's top bits, silently colliding
        # distinct user-supplied seeds.
        acc = _mix64(_mix64(int(req.sampling.seed) & _M64) ^ req.num_tokens)
        # A raw uint32[2] array IS a legacy threefry key — no jax
        # dispatch on the host path to build it.
        return jnp.asarray(
            np.array(
                [(acc >> 32) & 0xFFFFFFFF, acc & 0xFFFFFFFF],
                dtype=np.uint32,
            )
        )

    def _sample_seeded_row(self, req: Request, logit_row) -> int:
        """THE canonical seeded draw: one [1, V] ``sample_tokens`` call
        keyed by (seed, position). Every seeded sampling site — first
        token after prefill, every decode step, on any node — uses this
        exact shape and key schedule, so a request resurrected on
        another node redraws the same continuation its first life would
        have drawn (the categorical draw depends on the batch SHAPE, so
        shape-stability here is what makes cross-life replay exact)."""
        tok = sample_tokens(
            logit_row[None, :],
            self._seed_key(req),
            temperature=jnp.asarray(
                [req.sampling.temperature], jnp.float32
            ),
            top_p=jnp.asarray([req.sampling.top_p], jnp.float32),
            top_k=jnp.asarray([req.sampling.top_k], jnp.int32),
        )
        return int(np.asarray(tok)[0])

    def _record_first_token(self, req: Request) -> None:
        self.stats.ttft_s.append(req.first_token_time - req.submit_time)
        self._m_ttft.observe(req.first_token_time - req.submit_time)
        self._m_req_ttft.labels(tenant=req.tenant).observe(
            req.first_token_time - req.submit_time,
            trace_id=getattr(req.trace, "trace_id", None),
        )
        tr = req.trace
        if tr is not None:
            tr.add(
                "first_token", req.first_token_time, 0.0, cat="scheduler",
                ttft_s=round(req.first_token_time - req.submit_time, 6),
            )
        if self.on_first_token is not None:
            self.on_first_token(req)

    def _finalize_first_tokens(self, pending: list[tuple]) -> None:
        """ONE batched sample + ONE device→host copy for every request
        admitted this round (each copy costs a full RPC round trip on
        remote-tunneled devices — per-request syncs made TTFT scale with
        queue depth)."""
        if self._seeded_launch(r for r, _ in pending):
            # Seeded replay: each row draws through the canonical
            # shape-stable (seed, position) path instead of the batched
            # sample — a resumed request's first token is exactly the
            # token its first life drew at that position.
            now = time.monotonic()
            for req, logit in pending:
                tok = self._sample_seeded_row(req, logit)
                req.first_token_time = now
                req.output_tokens = [tok]
                self._tokens[req.row] = tok
                self._record_first_token(req)
                req.note_progress()
            return
        self._rng, key = jax.random.split(self._rng)
        # Pad to a power-of-two batch (repeating row 0) so serving queue
        # depths don't each compile a fresh sample_tokens variant.
        n = len(pending)
        n_b = _pow2_at_least(n, floor=1)
        logits = [logit for _, logit in pending]
        temps = [r.sampling.temperature for r, _ in pending]
        tops = [r.sampling.top_p for r, _ in pending]
        topks = [r.sampling.top_k for r, _ in pending]
        pad = n_b - n
        sampled = np.asarray(
            sample_tokens(
                jnp.stack(logits + [logits[0]] * pad),
                key,
                temperature=jnp.asarray(temps + [0.0] * pad, jnp.float32),
                top_p=jnp.asarray(tops + [1.0] * pad, jnp.float32),
                top_k=jnp.asarray(topks + [0] * pad, jnp.int32),
            )
        )[:n]
        now = time.monotonic()
        for (req, _), tok in zip(pending, sampled):
            req.first_token_time = now
            req.output_tokens = [int(tok)]
            self._tokens[req.row] = int(tok)
            # The ITL clock starts HERE: the first token's latency is
            # TTFT, so the timeline's first gap is token 1 → token 2 —
            # but the first token is still useful output.
            if self.goodput is not None:
                req.last_token_time = now
                self.goodput.note_token(req.tenant)
            self._record_first_token(req)
            # Wake streamers parked on the request condition: this is
            # THE first-token site, and the next _consume_token notify
            # may be a whole decode wave away.
            req.note_progress()

    def _prefill_dense(
        self,
        req: Request,
        row: int,
        reuse: int,
        prefix_slots: np.ndarray,
        own: np.ndarray,
    ) -> tuple:
        """Single-request dense prefill (gathered right-aligned prefix).
        Returns ``(req, final-logit device slice)`` for
        :meth:`_finalize_first_tokens`."""
        prompt = req.prompt
        n_new = len(prompt) - reuse
        s_b = _pow2_at_least(n_new)
        self._wave_padded = s_b  # launch shape (step attribution)
        p_b = _pow2_at_least(reuse, floor=self.page_size) if reuse else 0
        tokens = np.zeros((1, s_b), dtype=np.int32)
        tokens[0, :n_new] = prompt[reuse:]
        positions = (reuse + np.arange(s_b, dtype=np.int32))[None]
        kv_shape = (self.cfg.n_layers, 1, p_b, self.cfg.n_kv_heads, self.cfg.head_dim)
        cached_k = jnp.zeros(kv_shape, dtype=self.cfg.dtype)
        cached_v = jnp.zeros(kv_shape, dtype=self.cfg.dtype)
        if reuse:
            g = self.pool.gather(prefix_slots)  # [2, L, n, Hkv, D]
            cached_k = cached_k.at[:, 0, p_b - reuse :].set(g[0])
            cached_v = cached_v.at[:, 0, p_b - reuse :].set(g[1])
        logits, new_k, new_v = prefill_forward(
            self.params,
            self.cfg,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            cached_k,
            cached_v,
            jnp.full((1,), reuse, dtype=jnp.int32),
        )
        self.pool.write(own[:n_new], new_k[:, 0, :n_new], new_v[:, 0, :n_new])

        req.output_tokens = []
        req.kv_len = len(prompt)
        req.token_slots = np.concatenate([prefix_slots, own[:n_new]])
        req.own_slots = own
        self._install_prefilled(req, row, reuse)
        return (req, logits[0, n_new - 1])

    def _pp_n_micro(self, batch: int) -> int:
        """GPipe microbatch count for a pp launch: fill every stage when
        the batch divides, otherwise fall back to one wave (correct, just
        bubble-bound — batches are pow2-padded so pp=2/4 always divides)."""
        pp = self.device_mesh.shape["pp"]
        return pp if batch % pp == 0 else 1

    def _forward_chunk(
        self, toks, poss, sl, pt, kvlen, kv_block: int
    ):
        """One chunk forward through the right backend: the pipeline
        schedule under pp, ``prefill_chunk_paged`` otherwise. Shared by
        group prefill and the speculative verify pass so the dispatch
        cannot drift between them; quantized pools thread their scales
        through either path."""
        if self._pp:
            from radixmesh_tpu.parallel.pp_serving import pp_forward_chunk

            return pp_forward_chunk(
                self.params,
                self.cfg,
                toks,
                poss,
                self.pool.kv,
                sl,
                pt,
                kvlen,
                page_size=self.page_size,
                kv_block_pages=kv_block,
                mesh=self.device_mesh,
                n_micro=self._pp_n_micro(toks.shape[0]),
                kv_scale=self.pool.kv_scale,
            )
        return prefill_chunk_paged(
            self.params,
            self.cfg,
            toks,
            poss,
            self.pool.kv,
            sl,
            pt,
            kvlen,
            page_size=self.page_size,
            kv_block_pages=kv_block,
            kv_scale=self.pool.kv_scale,
            mesh=self.device_mesh,
        )

    def _sp_capable(self, member: tuple) -> bool:
        """A fresh (no cached prefix) long prompt on a mesh with an sp
        axis prefills sequence-sharded — ring attention over ICI."""
        req, _, reuse, *_ = member
        return (
            self.device_mesh is not None
            and self.device_mesh.shape.get("sp", 1) > 1
            and reuse == 0
            and len(req.prompt) >= self.sp_prefill_threshold
        )

    def _prefill_sp(
        self,
        req: Request,
        row: int,
        reuse: int,
        prefix_slots: np.ndarray,
        own: np.ndarray,
    ) -> tuple:
        """Sequence-parallel prefill of one fresh prompt: the whole span in
        ONE sharded call (``prefill_forward_sp``), sequence split over the
        sp mesh axis, ring attention over ICI. KV lands in the paged pool
        via a sharded scatter."""
        from radixmesh_tpu.models.llama import prefill_forward_sp

        prompt = req.prompt
        n = len(prompt)
        sp = self.device_mesh.shape["sp"]
        s_b = _pow2_at_least(n, floor=max(16, sp))
        s_b = -(-s_b // sp) * sp  # shard_map needs S divisible by sp
        self._wave_padded = s_b  # launch shape (step attribution)
        tokens = np.zeros((1, s_b), dtype=np.int32)
        tokens[0, :n] = prompt
        positions = np.arange(s_b, dtype=np.int32)[None]
        logits, new_k, new_v = prefill_forward_sp(
            self.params,
            self.cfg,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            self.device_mesh,
            logits_at=jnp.asarray([n - 1], dtype=jnp.int32),
        )
        self.pool.write(own[:n], new_k[:, 0, :n], new_v[:, 0, :n])
        req.output_tokens = []
        req.kv_len = n
        req.token_slots = own[:n].copy()
        req.own_slots = own
        self._install_prefilled(req, row, reuse)
        return (req, logits[0, 0])

    def _prefill_group(self, group: list[tuple]) -> list[tuple]:
        """Batched chunked-paged prefill for ``group`` of acquired
        requests: all rows advance through ``prefill_chunk_paged`` in
        lockstep (shapes bucketed to powers of two), each chunk writing
        K/V into the pool in place and attending blockwise via the page
        table — no host ``pool.gather`` round-trip, peak memory
        O(batch · chunk · block) regardless of prompt length. Ragged
        offsets are exact: every row carries its own positions and
        kv-length; exhausted/padded rows ride the scratch slot. One
        batched sample at the end → one host sync for the whole group."""
        N = len(group)
        ps = self.page_size
        kv_block = _KV_BLOCK_PAGES
        prompts = [g[0].prompt for g in group]
        reuses = [g[2] for g in group]
        totals = [len(p) for p in prompts]
        token_slots_all = [
            np.concatenate([g[3], g[4][: totals[i] - reuses[i]]])
            for i, g in enumerate(group)
        ]
        n_new_max = max(t - r for t, r in zip(totals, reuses))
        C = _pow2_at_least(min(n_new_max, self.prefill_chunk), floor=16)
        B = _pow2_at_least(N, floor=1)
        maxp = _pow2_at_least(
            max(-(-t // ps) for t in totals), floor=kv_block
        )
        pt = np.full((B, maxp), self._scratch_page, dtype=np.int32)
        for i, ts in enumerate(token_slots_all):
            n_pages = -(-totals[i] // ps)
            pt[i, :n_pages] = ts[::ps] // ps
        pt_dev = jnp.asarray(pt)

        final_logits: list = [None] * N
        n_chunks = -(-(n_new_max) // C)
        self._wave_padded = B * C * n_chunks  # launch shape (step attribution)
        for ci in range(n_chunks):
            toks = np.zeros((B, C), dtype=np.int32)
            sl = np.full((B, C), self._scratch_slot, dtype=np.int32)
            poss = np.zeros((B, C), dtype=np.int32)
            kvlen = np.zeros((B,), dtype=np.int32)
            lastpos = np.full((N,), -1, dtype=np.int32)
            for i in range(N):
                start = reuses[i] + ci * C
                nv = min(max(totals[i] - start, 0), C)
                poss[i] = np.clip(
                    start + np.arange(C, dtype=np.int32), 0, self.max_seq_len - 1
                )
                if nv > 0:
                    toks[i, :nv] = prompts[i][start : start + nv]
                    sl[i, :nv] = token_slots_all[i][start : start + nv]
                    kvlen[i] = start + nv
                    if start + nv == totals[i]:
                        lastpos[i] = nv - 1  # this chunk holds the last token
                else:
                    kvlen[i] = totals[i]
            res = self._forward_chunk(
                jnp.asarray(toks),
                jnp.asarray(poss),
                jnp.asarray(sl),
                pt_dev,
                jnp.asarray(kvlen),
                kv_block,
            )
            logits = self._commit_pool_update(res)
            for i in range(N):
                if lastpos[i] >= 0:
                    final_logits[i] = logits[i, lastpos[i]]

        out = []
        for i, (req, row, reuse, prefix_slots, own) in enumerate(group):
            req.output_tokens = []
            req.kv_len = totals[i]
            req.token_slots = token_slots_all[i]
            req.own_slots = own
            self._install_prefilled(req, row, reuse)
            out.append((req, final_logits[i]))
        return out

    # ------------------------------------------------------------------
    # publish / release (the cache_*_req contract)
    # ------------------------------------------------------------------

    def _sequence_key(self, req: Request, key_len: int) -> np.ndarray:
        if key_len <= len(req.prompt):
            return req.prompt[:key_len]
        return np.concatenate(
            [
                req.prompt,
                np.asarray(
                    req.output_tokens[: key_len - len(req.prompt)], dtype=np.int32
                ),
            ]
        )

    def _publish(self, req: Request, key_len: int) -> None:
        """Insert the first ``key_len`` tokens (whose KV is in the pool)
        into the tree; canonicalize shared prefixes; move the lock to the
        deepest published node."""
        tr = req.trace
        t_pub = time.monotonic() if tr is not None else 0.0
        key = self._sequence_key(req, key_len)
        matched = self.tree.insert(key, req.token_slots[:key_len].copy())
        m2 = self.tree.match_prefix(key)
        new_lock = m2.last_node
        if matched > req.prefix_len:
            # Over [prefix_len, matched) the tree kept already-present
            # slots. Where they're ours (this request published them
            # earlier) nothing changes; where another request published the
            # same tokens first, ours are duplicates — point our page table
            # at the canonical slots and free only the differing ones.
            canon = m2.indices()
            old = req.token_slots[: len(canon)].copy()
            dup = old[old != canon]
            if dup.size:
                req.token_slots[: len(canon)] = canon
                req.own_slots = np.setdiff1d(req.own_slots, dup)
                self.pool.free(dup)
        # Slots now referenced by tree nodes are tree-owned: drop them from
        # own_slots so release() never double-frees them.
        aligned = key_len - key_len % self.page_size
        tree_owned = req.token_slots[matched:aligned]
        if tree_owned.size:
            req.own_slots = np.setdiff1d(req.own_slots, tree_owned)
        if new_lock is not req.lock_node:
            self.tree.inc_lock_ref(new_lock)
            if req.lock_node is not None:
                self.tree.dec_lock_ref(req.lock_node)
            req.lock_node = new_lock
        if self.mesh is not None and aligned > 0:
            # Advertise the (canonical) published prefix around the ring
            # (radix_mesh.py:193-201). Only the page-ALIGNED prefix: the
            # local tree truncates inserts to page multiples, so residue
            # slots [aligned, key_len) are freed at release — advertising
            # them would map tokens to recycled slots ring-wide, and the
            # router would promise hits the node cannot serve. A traced
            # request's trace id rides the frames (old-wire-tolerant
            # trailer) so replicas stitch their apply/lag spans under it.
            self.mesh.insert(
                key[:aligned],
                req.token_slots[:aligned],
                trace_id=tr.trace_id if tr is not None else 0,
            )
        if tr is not None:
            tr.add(
                "publish",
                t_pub,
                time.monotonic() - t_pub,
                cat="cache",
                tokens=int(key_len),
                ring_advertised=bool(self.mesh is not None and aligned > 0),
            )

    def _release(self, req: Request) -> None:
        """cache_finished_req (radix_cache.py:439-486): publish the full
        sequence, free unpublished residue, release the lock, free the row."""
        self._publish(req, req.kv_len)
        if req.own_slots.size:
            self.pool.free(req.own_slots)
            req.own_slots = np.empty(0, dtype=np.int32)
        if req.lock_node is not None:
            self.tree.dec_lock_ref(req.lock_node)
            req.lock_node = None
        if req.row >= 0:
            self._rows[req.row] = None
            self._page_table[req.row] = self._scratch_page
            self._tokens[req.row] = 0
            req.row = -1

    # ------------------------------------------------------------------
    # decode
    # ------------------------------------------------------------------

    def _commit_pool_update(self, res):
        """Unpack a model call's ``(out, kv_pool[, kv_scale])`` result:
        store the updated pool buffers and return the leading value (the
        quantized pool threads its scale array through every call)."""
        if self.pool.quant is not None:
            out, self.pool.kv, self.pool.kv_scale = res
        else:
            out, self.pool.kv = res
        return out

    def _decode_pt_bucket(
        self, headroom: int, floor: int = 4
    ) -> np.ndarray:
        """Length-bucketed page-table slice for one decode launch: wide
        enough for every active row's context plus ``headroom`` tokens,
        bucketed to a power of two. A mixed batch must not pay the
        ``max_seq_len``-wide table on every step — short rows were
        attending (masked) over the full 8k-table width, which is THE
        wide-workload TTFT collapse (VERDICT round-3 weak #2 / next-step
        #6). Each bucket is one extra jit variant, bounded by log2(max
        pages). ``floor`` must be ``_KV_BLOCK_PAGES`` for launches that
        go through blockwise chunk attention (its page blocks must divide
        the table width)."""
        need = 1
        for req in self._rows:
            if req is not None:
                need = max(
                    need, (req.kv_len + headroom - 1) // self.page_size + 1
                )
        maxp = min(
            _pow2_at_least(need, floor=floor),
            self._page_table_padded.shape[1],
        )
        # Sliced from the PADDED buffer: columns past max_pages hold the
        # scratch page (a real pool page), so a bucket that overshoots
        # max_pages gathers junk that attention masks — never an OOB id.
        return self._page_table_padded[:, :maxp]

    def _decode_once(self, inline_allot: list[int] | None = None) -> None:
        g = self.spec_decode_tokens
        spec = g > 0 and self._spec_ok(g)
        if spec or inline_allot is not None:
            # Draft BEFORE committing to the wide verify launch: when no
            # row's history repeats its tail there is nothing to verify,
            # and the plain/fused path emits the same tokens cheaper.
            # A mixed wave (inline_allot) ALWAYS takes the fused chunk
            # launch — drafted rows verify, undrafted rows ride as
            # width-1 windows, and the inline chunks fill the rest of
            # the chunk width — so speculation and inline prefill
            # compose in one device call.
            drafts: dict[int, np.ndarray] = {}
            sources: dict[int, str] = {}
            for row, req in enumerate(self._rows):
                if req is None:
                    continue
                if spec and self._spec_row_ok(req, g):
                    drafts[row], sources[row] = self._draft_for(req)
                else:
                    drafts[row], sources[row] = req.prompt[:0], "none"
            if inline_allot is not None or any(
                len(d) for d in drafts.values()
            ):
                self._decode_spec_once(
                    g if spec else 0, drafts, sources, inline=inline_allot
                )
                return
        k = self.decode_steps_per_launch
        if k > 1:
            k_eff = self._multi_step_k(k)
            if k_eff > 1:
                self._decode_multi_once(k_eff)
                return
        seeded = self._seeded_launch(self._rows)
        n_rows = sum(1 for r in self._rows if r is not None)
        use_paged = select_paged(
            n_rows,
            self.cfg.head_dim,
            min_batch=self.paged_min_batch,
            max_len=max(
                (r.kv_len for r in self._rows if r is not None), default=0
            ),
        )
        self._last_dispatch = last_dispatch()
        if not self._pp and not use_paged and not seeded:
            # Dense single step (small-batch paged fast path,
            # ops/attention.py::select_paged): either no paged kernel on
            # this backend, or the batch sits below --paged-min-batch —
            # where the paged launch's whole-pool donation-copy and
            # block bookkeeping lose to the compact gathered working
            # set. Seeded launches skip it: its device-side draw is
            # batch-shaped, and replay needs the canonical per-row
            # (seed, position) draw below.
            self._decode_multi_once(1, force_compact=True)
            return
        slots = np.full(self.max_batch, self._scratch_slot, dtype=np.int32)
        lengths = np.ones(self.max_batch, dtype=np.int32)
        preempted: list[Request] = []
        for row, req in enumerate(self._rows):
            if req is None:
                continue
            page_idx, offset = divmod(req.kv_len, self.page_size)
            if offset == 0:  # crossing into a fresh page
                new = self._alloc_pages(1, cause="preempt")
                if new is None:
                    preempted.append(req)
                    continue
                req.own_slots = np.concatenate([req.own_slots, new])
                self._page_table[row, page_idx] = new[0] // self.page_size
                slot = int(new[0])
            else:
                slot = int(
                    self._page_table[row, page_idx] * self.page_size + offset
                )
            slots[row] = slot
            lengths[row] = req.kv_len + 1
        for req in preempted:
            self._preempt(req)

        active = [(row, r) for row, r in enumerate(self._rows) if r is not None]
        if not active:
            return
        step_t0 = time.monotonic()
        if self._pp or seeded:
            # A decode step is a C=1 chunk through the layer pipeline
            # (parallel/pp_serving.py) — same page-table attention, same
            # pool scatter, stage weights never move. The chunk path's
            # blockwise attention needs a KV-block-multiple table width,
            # which the bucket keeps (floor = block). Seeded launches
            # ride it on every backend: it returns LOGITS, and the
            # replay contract needs the canonical host-side draw.
            res = self._forward_chunk(
                jnp.asarray(self._tokens)[:, None],
                jnp.asarray(lengths - 1)[:, None],
                jnp.asarray(slots)[:, None],
                jnp.asarray(self._decode_pt_bucket(1, floor=_KV_BLOCK_PAGES)),
                jnp.asarray(lengths),
                _KV_BLOCK_PAGES,
            )
            logits = self._commit_pool_update(res)[:, 0]
        else:
            res = decode_step(
                self.params,
                self.cfg,
                jnp.asarray(self._tokens),
                self.pool.kv,
                jnp.asarray(slots),
                jnp.asarray(self._decode_pt_bucket(1)),
                jnp.asarray(lengths),
                self.page_size,
                mesh=self.device_mesh,
                kv_scale=self.pool.kv_scale,
            )
            logits = self._commit_pool_update(res)
        if seeded:
            sampled = np.zeros(self.max_batch, dtype=np.int64)
            for row, req in active:
                sampled[row] = self._sample_seeded_row(req, logits[row])
        else:
            self._rng, key = jax.random.split(self._rng)
            sampled = np.asarray(
                sample_tokens(
                    logits, key, temperature=jnp.asarray(self._temps),
                    top_p=jnp.asarray(self._top_ps),
                    top_k=jnp.asarray(self._top_ks),
                )
            )
        self.stats.decode_steps += 1
        # sample_tokens materialized on host above, so this spans the full
        # dispatch+device time of the step — the per-token latency (TPOT)
        # seen by every active request.
        elapsed = time.monotonic() - step_t0
        self._note_decode_time(elapsed)
        if self.step_acct is not None:
            self.step_acct.note_wave(
                "decode", len(active), self.max_batch, elapsed,
                rows=len(active),
            )
        for _, req in active:
            tr = req.trace
            if tr is not None:
                tr.add(
                    "decode_chunk", step_t0, elapsed, cat="decode",
                    k_steps=1, batch_rows=len(active),
                )

        for row, req in active:
            self._consume_token(req, row, int(slots[row]), int(sampled[row]))

    def _multi_step_k(self, k: int) -> int:
        """The largest fusable step count ≤ k this launch: bounded by
        every active row's sequence/page headroom and remaining output
        budget. Fusing is preferred whenever no WAITING request could
        actually admit (admission happens between launches, and k steps
        per launch is k× fewer pool donation-copies + host syncs — the
        wide-workload convoy, VERDICT round-3 next-step #6). Staggered
        admission leaves rows at DIFFERENT budget remainders, and
        refusing to fuse whenever any row was near its budget degraded
        mixed batches to single-stepping for most of their lifetime —
        shrink k to the binding row instead. Returns ≤ 1 when fusing is
        pointless."""
        if self.waiting and self._free_row() >= 0:
            return 1
        if self._pp and self.max_batch % self.device_mesh.shape["pp"]:
            return 1
        for req in self._rows:
            if req is None:
                continue
            if req.sampling.seed is not None:
                # Seeded replay (crash recovery): the fused launch draws
                # its intermediate tokens from one in-scan key schedule,
                # which would tie each draw to the LAUNCH rather than
                # the (seed, position) pair — single-step so every draw
                # goes through the position-keyed path.
                return 1
            k = min(k, self.max_seq_len - req.kv_len)
            k = min(k, self.max_pages * self.page_size - req.kv_len)
            # A row past its output budget would discard the tail of the
            # fused launch — bubble compute without a latency win.
            k = min(
                k, req.sampling.max_new_tokens - len(req.output_tokens)
            )
            if k <= 1:
                return 1
        return k

    def _compact_decode_tables(
        self, active: list[tuple], k: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Compact working-set mapping for ``decode_multi_compact``:
        the unique live pages of every active row (with ``k`` tokens of
        headroom) plus the scratch page, pow2-padded by DUPLICATING the
        scratch page (the one page where duplicate scatter-back targets
        are harmless — its contents are never read unmasked), and the
        bucketed page table rewritten into compact indices."""
        ps = self.page_size
        need = [
            (row, (req.kv_len + k - 1) // ps + 1) for row, req in active
        ]
        uniq = np.unique(np.concatenate(
            [self._page_table[row, :n] for row, n in need]
            + [np.asarray([self._scratch_page], dtype=np.int32)]
        )).astype(np.int32)
        n_c = _pow2_at_least(len(uniq), floor=8)
        compact = np.full(n_c, self._scratch_page, dtype=np.int32)
        compact[: len(uniq)] = uniq
        scratch_idx = int(np.searchsorted(uniq, self._scratch_page))
        maxp = self._decode_pt_bucket(k).shape[1]
        pt_c = np.full(
            (self.max_batch, maxp), scratch_idx, dtype=np.int32
        )
        for row, n in need:
            pt_c[row, :n] = np.searchsorted(
                uniq, self._page_table[row, :n]
            )
        return compact, pt_c

    def _decode_multi_once(self, k: int, force_compact: bool = False) -> None:
        """One ``decode_multi`` launch: k tokens per active request with a
        single host round trip (device-side sampling feeds each step). See
        ``models/llama.py::decode_multi`` for the latency rationale.
        ``force_compact`` pins the gathered compact-working-set variant
        even where the paged kernel exists — the small-batch crossover
        (``select_paged``) chose dense for this wave."""
        lengths = np.ones(self.max_batch, dtype=np.int32)
        active = self._provision_rows(k - 1)
        if not active:
            return
        for row, req in active:
            lengths[row] = req.kv_len + 1
        step_t0 = time.monotonic()
        self._rng, key = jax.random.split(self._rng)
        if self._pp:
            from radixmesh_tpu.parallel.pp_serving import pp_decode_multi

            res = pp_decode_multi(
                self.params,
                self.cfg,
                jnp.asarray(self._tokens),
                self.pool.kv,
                jnp.asarray(self._decode_pt_bucket(k)),
                jnp.asarray(lengths),
                key,
                jnp.asarray(self._temps),
                jnp.asarray(self._top_ps),
                jnp.asarray(self._top_ks),
                page_size=self.page_size,
                k_steps=k,
                mesh=self.device_mesh,
                kv_scale=self.pool.kv_scale,
                scratch_slot=self._scratch_slot,
            )
        elif force_compact or not default_use_kernel(self.cfg.head_dim):
            # No aliased kernel on this backend (or the crossover chose
            # dense): decode over a gathered compact working set so each
            # launch pays ONE pool gather + ONE scatter-back instead of
            # k·L pool-sized scatter copies
            # (see models/llama.py::decode_multi_compact).
            compact, pt_c = self._compact_decode_tables(active, k)
            res = decode_multi_compact(
                self.params,
                self.cfg,
                jnp.asarray(self._tokens),
                self.pool.kv,
                jnp.asarray(compact),
                jnp.asarray(pt_c),
                jnp.asarray(lengths),
                key,
                jnp.asarray(self._temps),
                jnp.asarray(self._top_ps),
                self.page_size,
                k_steps=k,
                mesh=self.device_mesh,
                kv_scale=self.pool.kv_scale,
                top_ks=jnp.asarray(self._top_ks),
            )
        else:
            res = decode_multi(
                self.params,
                self.cfg,
                jnp.asarray(self._tokens),
                self.pool.kv,
                jnp.asarray(self._decode_pt_bucket(k)),
                jnp.asarray(lengths),
                key,
                jnp.asarray(self._temps),
                jnp.asarray(self._top_ps),
                self.page_size,
                k_steps=k,
                mesh=self.device_mesh,
                kv_scale=self.pool.kv_scale,
                top_ks=jnp.asarray(self._top_ks),
            )
        sampled = self._commit_pool_update(res)
        sampled = np.asarray(sampled)  # [k, B] — the ONE round trip
        self.stats.decode_steps += k
        elapsed = time.monotonic() - step_t0
        for _ in range(k):
            self._note_decode_time(elapsed / k)
        if self.step_acct is not None:
            self.step_acct.note_wave(
                "decode", k * len(active), k * self.max_batch, elapsed,
                rows=len(active),
            )
        for _, req in active:
            tr = req.trace
            if tr is not None:
                tr.add(
                    "decode_chunk", step_t0, elapsed, cat="decode",
                    k_steps=k, batch_rows=len(active),
                )

        ps = self.page_size
        for row, req in active:
            base = req.kv_len
            for i in range(k):
                pos = base + i
                slot = int(
                    self._page_table[row, pos // ps] * ps + pos % ps
                )
                if self._consume_token(req, row, slot, int(sampled[i, row])):
                    break  # finished mid-launch: surplus tokens discarded

    def _spec_ok(self, g: int) -> bool:
        """Speculative decoding is considered whenever rows are active and
        no request waits for admission (admission happens between
        launches, so a wide verify launch would delay a queued prefill).
        Stochastic rows verify by exact rejection sampling
        (``ops/sampling.py::spec_verify_sample``), so temperature does not
        disable the path. Budget and headroom limits are per-row
        (``_spec_row_ok``): a nearly-finished request rides the launch
        with an empty draft — exactly a plain step for that row — instead
        of switching speculation off for the whole batch. Under pp the
        verify chunk rides the pipeline schedule for ANY batch size
        (``_pp_n_micro`` falls back to one wave when the batch doesn't
        split into pp microbatches — single-stream serving, speculation's
        prime latency case, must not lose it)."""
        if self.waiting:
            return False
        if any(
            r is not None and r.sampling.seed is not None
            for r in self._rows
        ):
            # Seeded replay (crash recovery): the spec verify resample
            # draws from the launch-wide key, which would decouple a
            # seeded row's tokens from its (seed, position) schedule —
            # seeded batches take the position-keyed single-step path.
            return False
        return any(req is not None for req in self._rows)

    def _spec_row_ok(self, req: Request, g: int) -> bool:
        """Per-row speculation gate: the verify window needs γ+1 positions
        of sequence and page-table headroom, and a row within one token of
        its output budget gains nothing from a draft (the surplus would be
        discarded — the same bubble ``_multi_step_k`` avoids). Failing
        rows decode normally inside the launch via an empty draft."""
        if req.kv_len + g + 1 > self.max_seq_len:
            return False
        if (req.kv_len + g) // self.page_size >= self.max_pages:
            return False
        if req.sampling.max_new_tokens - len(req.output_tokens) < 2:
            return False
        return True

    # Draft lookup scans at most this many trailing history tokens: the
    # match quality of prompt lookup lives in the recent context, and an
    # unbounded scan would put O(total-context) host work on the
    # inter-launch critical path of a 32k-token generation.
    _SPEC_WINDOW = 1024

    def _draft_for(self, req: Request) -> tuple[np.ndarray, str]:
        """Returns ``(draft, source)`` — source ∈ DRAFT_SOURCES, the
        speculation ledger's per-class key (tree drafts and n-gram
        drafts have very different acceptance profiles, and tuning γ on
        their blend hides which drafter is actually paying)."""
        # γ for this request's class: the configured window, shrunk or
        # regrown per (tenant, shape) by the acceptance-adaptive
        # controller when --spec-adaptive is on (clamped to [1, base];
        # base 0 — including the SLO tier-1 spec-off — always wins).
        gamma = self.spec_ledger.gamma_for(
            req.tenant, shape_bucket(len(req.prompt)),
            self.spec_decode_tokens,
        )
        hist = self._sequence_key(req, req.kv_len + 1)
        # Best drafter first: the radix tree itself. A replayed
        # conversation (same prompt served before) finds the PREVIOUS
        # generation's published tokens cached beyond its history — a
        # near-perfect draft for greedy replays, and the mechanism that
        # makes speculation a property of the prefix cache rather than of
        # the request's own text. The walk is O(context), so it only runs
        # for requests that admitted as near-full prefix hits (replay
        # candidates) and stops the first time it comes back empty —
        # novel generations never pay it per launch (_SPEC_WINDOW bounds
        # their n-gram scan instead).
        # Draft-ahead from the mesh (ROADMAP 1a′): a PREFETCH fill or a
        # disk promotion may have attached a continuation AFTER this
        # request's last peek latched tree drafting off — the tree's
        # draft_ready_epoch (bumped by kv_transfer's apply site) says so
        # without a walk. Re-arm and peek again, so a remote/disk-
        # resident hit drafts exactly like a natively-published one.
        epoch = getattr(self.tree, "draft_ready_epoch", 0)
        promoted = epoch > req.draft_epoch
        if promoted:
            req.tree_draft_ok = True
            req.draft_epoch = epoch
        if req.tree_draft_ok and (
            promoted
            or req.prefix_len >= max(0, len(req.prompt) - self.page_size)
        ):
            cont = self.tree.peek_continuation(hist, gamma)
            if len(cont):
                return cont, "tree"
            req.tree_draft_ok = False
        draft = self._ngram_draft(
            hist[-self._SPEC_WINDOW :], gamma, self.spec_ngram
        )
        return draft, ("ngram" if len(draft) else "none")

    @staticmethod
    def _ngram_draft(hist: np.ndarray, gamma: int, n: int) -> np.ndarray:
        """Prompt-lookup draft: the ``gamma`` tokens that followed the most
        recent PREVIOUS occurrence of the current tail n-gram (falling back
        to bigrams). Empty when the history never repeats its tail."""
        L = len(hist)
        for nn in range(n, 1, -1):
            if L <= nn:
                continue
            tail = hist[L - nn:]
            win = np.lib.stride_tricks.sliding_window_view(hist, nn)
            hits = np.nonzero((win[: L - nn] == tail).all(axis=1))[0]
            if hits.size:
                j = int(hits[-1]) + nn  # continuation of the match
                return hist[j : j + gamma]
        return hist[:0]

    def _provision_rows(
        self, extra: int, extras: dict[int, int] | None = None
    ) -> list[tuple[int, "Request"]]:
        """Ensure every active row's page table covers positions
        ``kv_len .. kv_len+extra``; preempt rows the pool can't cover.
        Returns the surviving (row, request) pairs. Shared by the fused
        multi-step and speculative paths (their only difference was the
        bound). ``extras`` overrides the bound per row — the speculative
        path provisions only each row's actual draft window, so a row that
        opted out (empty draft) cannot be preempted for headroom it will
        never write."""
        ps = self.page_size
        preempted: list[Request] = []
        for row, req in enumerate(self._rows):
            if req is None:
                continue
            row_extra = extra if extras is None else extras.get(row, extra)
            for p_idx in range(req.kv_len // ps, (req.kv_len + row_extra) // ps + 1):
                if self._page_table[row, p_idx] != self._scratch_page:
                    continue  # page already provisioned
                new = self._alloc_pages(1, cause="preempt")
                if new is None:
                    preempted.append(req)
                    break
                req.own_slots = np.concatenate([req.own_slots, new])
                self._page_table[row, p_idx] = new[0] // ps
        for req in preempted:
            self._preempt(req)
        return [(row, r) for row, r in enumerate(self._rows) if r is not None]

    def _decode_spec_once(
        self,
        g: int,
        drafts: dict[int, np.ndarray],
        sources: dict[int, str] | None = None,
        inline: list[int] | None = None,
        decode: bool = True,
    ) -> None:
        """One fused chunk launch: decode rows verify [fed_token, draft…]
        (w=draft+1 live positions per row; w=1 = a plain step) and —
        mixed compute waves — inline prefill jobs ride the SAME call as
        rows whose live window is their allotted slice of prompt tokens
        (``inline`` = tokens per backlog job, from WaveScheduler.plan).
        Acceptance per decode row via ``spec_verify_sample`` — greedy
        rows take the longest argmax-matching draft prefix, stochastic
        rows accept each draft token with its target probability (exact
        rejection sampling) — and emit one bonus token. Fed positions'
        K/V is written by the pass itself, so accepted tokens cost no
        extra work; rejected positions hold stale K/V that the next
        launch overwrites (slots are purely positional) and that
        attention never reads (masked by length). An inline job whose
        final chunk lands here installs + finalizes its first token in
        the same wave. ``decode=False`` (prefill/boost waves, the
        all-seeded fallback's second launch) advances the backlog alone."""
        ps = self.page_size
        jobs = (
            [
                (job, w)
                for job, w in zip(self._inline, inline)
                if w > 0 and job.pos < job.total
            ]
            if inline is not None
            else []
        )
        if inline is None:
            C = g + 1  # legacy speculative shape, untouched
        else:
            # Chunk width covers the widest live window this wave —
            # pow2-bucketed so varying allotments reuse compiled
            # variants (floor matches _prefill_group's chunk floor).
            C = _pow2_at_least(
                max([g + 1] + [w for _, w in jobs]), floor=16
            )
        # Provision only each row's actual verify window (draft + bonus):
        # an opted-out row (empty draft) needs exactly the one position a
        # plain step would, so γ positions of headroom it lacks must not
        # preempt it. Inline jobs never provision — their pages were all
        # acquired up front at admission.
        active = (
            self._provision_rows(
                g, extras={row: len(d) for row, d in drafts.items()}
            )
            if decode
            else []
        )
        if not active and not jobs:
            return
        step_t0 = time.monotonic()

        B = self.max_batch
        kv_block = _KV_BLOCK_PAGES
        maxp = _pow2_at_least(
            max(
                [
                    (r.kv_len + len(drafts.get(row, r.prompt[:0]))) // ps
                    + 1
                    for row, r in active
                ]
                + [(job.pos + w) // ps + 1 for job, w in jobs]
            ),
            floor=kv_block,
        )
        toks = np.zeros((B, C), dtype=np.int32)
        draft_len = np.zeros((B,), dtype=np.int32)
        sl = np.full((B, C), self._scratch_slot, dtype=np.int32)
        poss = np.zeros((B, C), dtype=np.int32)
        kvlen = np.zeros((B,), dtype=np.int32)
        pt = np.full((B, maxp), self._scratch_page, dtype=np.int32)
        for row, req in active:
            draft = drafts.get(row, req.prompt[:0])
            drafts[row] = draft
            w = len(draft) + 1  # this row's live verify window
            toks[row, 0] = self._tokens[row]
            toks[row, 1 : 1 + len(draft)] = draft
            pos = req.kv_len + np.arange(C, dtype=np.int32)
            poss[row] = np.minimum(pos, self.max_seq_len - 1)
            n_pages = min((req.kv_len + len(draft)) // ps + 1, self.max_pages)
            pt[row, :n_pages] = self._page_table[row, :n_pages]
            # Positions past the row's window write their (garbage) K/V to
            # the scratch slot; causal masking keeps them out of every
            # logit the verify actually uses.
            sl[row, :w] = pt[row, pos[:w] // ps] * ps + pos[:w] % ps
            kvlen[row] = req.kv_len + w
            draft_len[row] = len(draft)
            # Conservation contract: a draft counts as PROPOSED here iff
            # its row survives to the verify below — every proposed
            # token is then accounted accepted or rejected in the
            # accept loop, so proposed == accepted + rejected holds on
            # every path (drafts of rows preempted by _provision_rows
            # above were never proposed). The per-class ledger rides the
            # same two sites, so it cannot undercount either.
            self.stats.spec_proposed += len(draft)
            self._m_spec_proposed.inc(len(draft))
            if len(draft):
                cell = self._spec_shape.setdefault(
                    shape_bucket(len(req.prompt)), [0, 0]
                )
                cell[0] += len(draft)

        for job, w in jobs:
            # Inline prefill rows: the live window is the job's allotted
            # prompt slice [pos, pos+w) — exactly a _prefill_group chunk
            # for one row, riding the decode launch. draft_len stays 0,
            # so the verify below treats the row as undrafted and its
            # (meaningless mid-prompt) bonus sample is never consumed.
            row, pos, prompt = job.row, job.pos, job.req.prompt
            toks[row, :w] = prompt[pos : pos + w]
            p = pos + np.arange(C, dtype=np.int32)
            poss[row] = np.minimum(p, self.max_seq_len - 1)
            sl[row, :w] = job.token_slots[pos : pos + w]
            kvlen[row] = pos + w
            npg = min(-(-job.total // ps), maxp)
            pt[row, :npg] = job.token_slots[::ps][:npg] // ps

        # The verify pass is just a C-wide chunk; _forward_chunk picks
        # the pipeline schedule under pp (parallel/pp_serving.py).
        res = self._forward_chunk(
            jnp.asarray(toks),
            jnp.asarray(poss),
            jnp.asarray(sl),
            jnp.asarray(pt),
            jnp.asarray(kvlen),
            kv_block,
        )
        logits = self._commit_pool_update(res)
        if active:
            self._rng, key = jax.random.split(self._rng)
            accept_len, bonus = spec_verify_sample(
                logits,
                jnp.asarray(toks[:, 1:]),
                jnp.asarray(draft_len),
                key,
                jnp.asarray(self._temps),
                jnp.asarray(self._top_ps),
                jnp.asarray(self._top_ks),
            )
            accept_len = np.asarray(accept_len)  # [B] one sync
            bonus = np.asarray(bonus)
            self.stats.decode_steps += 1

        emitted_total = 0
        for row, req in active:
            draft = drafts[row]
            a = int(accept_len[row])
            rejected = len(draft) - a
            self.stats.spec_accepted += a
            self.stats.spec_rejected += rejected
            self._m_spec_accepted.inc(a)
            if rejected:
                self._m_spec_rejected.inc(rejected)
            # Rejected tail: the gap before this row's NEXT token
            # includes re-decoding it — the spec_verify_miss stall
            # attribution (consumed by _stall_cause).
            req.spec_miss = rejected
            if a:
                cell = self._spec_shape.setdefault(
                    shape_bucket(len(req.prompt)), [0, 0]
                )
                cell[1] += a
            if len(draft):
                self.spec_ledger.note_wave(
                    req.tenant,
                    shape_bucket(len(req.prompt)),
                    sources.get(row, "ngram") if sources else "ngram",
                    len(draft),
                    a,
                    len(draft),
                )
            base = req.kv_len
            for i in range(a + 1):  # a accepted drafts + 1 bonus token
                pos = base + i
                slot = int(self._page_table[row, pos // ps] * ps + pos % ps)
                token = int(draft[i]) if i < a else int(bonus[row])
                emitted_total += 1
                if self._consume_token(req, row, slot, token):
                    break
        inline_tok = 0
        pending: list[tuple] = []
        for job, w in jobs:
            start = job.pos
            job.pos += w  # exact chunk resume offset for the next wave
            inline_tok += w
            tr = job.req.trace
            if tr is not None:
                tr.add(
                    "prefill_inline", step_t0,
                    time.monotonic() - step_t0, cat="prefill",
                    chunk_tokens=int(w), resume_offset=int(start),
                )
            if job.pos >= job.total:
                # Final chunk: install + hand the last prompt position's
                # logits to the shared first-token finalizer (one
                # batched sample for every job finishing this wave).
                req = job.req
                req.output_tokens = []
                req.kv_len = job.total
                req.token_slots = job.token_slots
                req.own_slots = job.own
                self._inline_rows.discard(job.row)
                self._install_prefilled(
                    req, job.row, job.reuse, inline=True
                )
                pending.append((req, logits[job.row, w - 1]))
        if jobs:
            # Stall attribution: inline chunks advanced inside this wave
            # (finished or not) — a decode gap spanning this instant is
            # prefill_inline, never scheduler_wait (and not a convoy).
            self._last_inline_prefill_t = time.monotonic()
            self._inline = [j for j in self._inline if j.pos < j.total]
        if pending:
            self._finalize_first_tokens(pending)
        elapsed = time.monotonic() - step_t0
        if active:
            for _ in range(max(emitted_total, 1)):
                self._note_decode_time(elapsed / max(emitted_total, 1))
        if self.step_acct is not None:
            # The launch processes B·C positions; the USEFUL work is the
            # accepted+bonus decode tokens actually emitted plus the
            # inline prefill tokens advanced.
            self.step_acct.note_wave(
                "decode" if active else "prefill",
                emitted_total + inline_tok,
                B * C,
                elapsed,
                rows=len(active) + len(jobs),
            )
        for row, req in active:
            tr = req.trace
            if tr is not None:
                tr.add(
                    "decode_chunk", step_t0, elapsed, cat="decode",
                    k_steps=1, batch_rows=len(active), speculative=True,
                    draft_tokens=int(draft_len[row]),
                    accepted_tokens=int(accept_len[row]),
                )

    def hint_stall(self, cause: str) -> None:
        """One-shot stall-cause latch for external planes: the next
        over-threshold inter-token gap is attributed to ``cause``
        instead of the engine's own inference. The rebalance executor
        latches ``rebalance_handoff`` here while an ownership move
        drains this node's shard."""
        from radixmesh_tpu.obs.token_timeline import STALL_CAUSES

        if cause not in STALL_CAUSES:
            raise ValueError(f"unknown stall cause {cause!r}")
        self._stall_hint = cause

    def _stall_cause(self, req: Request, now: float, gap_s: float) -> str:
        """Attribute one over-threshold inter-token gap to the single
        most likely cause, in the taxonomy's priority order (see
        obs/token_timeline.py::STALL_CAUSES)."""
        hint = self._stall_hint
        if hint is not None:
            self._stall_hint = None
            return hint
        if self._restoring:
            return "restore_park"
        if now - self._last_prefill_t <= gap_s:
            # A prefill wave launched inside the gap: the decode convoy.
            return "prefill_convoy"
        if now - self._last_inline_prefill_t <= gap_s:
            # An inline prefill chunk (mixed compute wave) launched
            # inside the gap: budget-bounded by design, so it is NOT a
            # convoy — before this branch existed, a gap spanning a
            # wave boundary with an inline chunk in it fell through to
            # scheduler_wait, hiding the interleave's (bounded) cost.
            return "prefill_inline"
        if req.spec_miss:
            req.spec_miss = 0
            return "spec_verify_miss"
        return "scheduler_wait"

    def _note_token_time(self, req: Request) -> None:
        """Per-emitted-token timeline/goodput accounting. The FIRST
        token of a request only stamps the clock (its latency is TTFT,
        not ITL); every later token records its inter-token gap, with
        over-threshold gaps attributed to a stall cause."""
        now = time.monotonic()
        prev = req.last_token_time
        req.last_token_time = now
        self.goodput.note_token(req.tenant)
        if not prev:
            return
        gap = now - prev
        cause = None
        if gap >= self.timeline.stall_threshold_s:
            cause = self._stall_cause(req, now, gap)
            self.goodput.note_stall(req.tenant, gap)
        self.timeline.note_token(
            req.rid, req.tenant, gap, cause,
            trace_id=getattr(req.trace, "trace_id", None), now=now,
        )

    def _consume_token(self, req: Request, row: int, slot: int, token: int) -> bool:
        """Account one decode iteration for ``req``: the fed token's KV
        landed at ``slot``, ``token`` was sampled. Returns True when the
        request finished (stop token / length cap) and was released —
        shared by single-step and fused multi-step decode so the subtle
        stop/stats bookkeeping cannot drift between them."""
        req.token_slots = np.append(req.token_slots, slot)
        req.kv_len += 1
        req.output_tokens.append(token)
        self.stats.generated_tokens += 1
        if self.timeline is not None:  # one branch when off (PR 2 contract)
            self._note_token_time(req)
        if req.is_finished_by(token) or req.num_tokens >= self.max_seq_len:
            # Don't count the terminal token as output if it's a stop.
            if token in req.sampling.stop_token_ids:
                req.output_tokens.pop()
                self.stats.generated_tokens -= 1
            else:
                self._m_generated.inc()
            if req.submit_time:
                self._m_req_e2e.labels(tenant=req.tenant).observe(
                    time.monotonic() - req.submit_time,
                    trace_id=getattr(req.trace, "trace_id", None),
                )
            req.state = RequestState.FINISHED
            self.stats.finished += 1
            self._release(req)
            self._pressure = False  # freed memory: resume admission
            return True
        self._m_generated.inc()
        self._tokens[row] = token
        if (
            self.stream_publish_tokens > 0
            and len(req.output_tokens) % self.stream_publish_tokens == 0
        ):
            # Mid-decode publish (crash recovery): the grown prefix
            # (prompt + generated-so-far) lands in the tree AND
            # replicates around the ring every N tokens, so a node death
            # loses at most N tokens of resurrection cache hit — the
            # re-prefill on a surviving replica is near-pure hit. Same
            # call _preempt makes; idempotent for already-published
            # prefixes.
            self._publish(req, req.kv_len)
        # Streaming waiters block on the request condition instead of
        # polling (server/http_frontend.py) — wake them per token so
        # first-token latency isn't quantized by a poll interval.
        req.note_progress()
        return False

    def _preempt(self, req: Request) -> None:
        """Pool exhausted mid-decode even after eviction: publish what we
        have, free the row, and requeue from scratch (the generated tokens
        are discarded; the published KV makes the retry a long prefix hit)."""
        self.stats.preemptions += 1
        self._m_preempt.inc()
        self._pressure = True
        req.requeue_time = time.monotonic()
        tr = req.trace
        if tr is not None:
            tr.add(
                "preempt", req.requeue_time, 0.0, cat="scheduler",
                kv_len=int(req.kv_len),
                output_tokens=len(req.output_tokens),
            )
        self._release(req)
        req.state = RequestState.QUEUED
        req.output_tokens = []
        # Token-timeline clock resets with the life: the retry's first
        # token is TTFT again, not a giant inter-token gap.
        req.last_token_time = 0.0
        req.spec_miss = 0
        req.kv_len = 0
        req.prefix_len = 0
        req.token_slots = np.empty(0, dtype=np.int32)
        # The retry re-admits against its own just-published generation —
        # the ideal tree-draft replay — so re-enable tree drafting even if
        # the first life gave up on it.
        req.tree_draft_ok = True
        self.waiting.insert(0, req)
