"""The black box: crash-surviving flight-recorder dumps.

The telemetry history (``obs/timeseries.py``) gives a node a memory;
this module makes that memory survive the node. Three mechanisms, one
dump directory per node (``launch.py --blackbox-dir``):

- **Incremental segments.** Riding the history's ``on_sample`` hook,
  every ``segment_every`` samples the NEW ring points are written to a
  ``segment-NNNNNN.json`` file via write-to-temp + atomic rename — so a
  ``kill -9`` at any instant leaves every previously completed segment
  intact and loses at most one segment window of history. No partial
  JSON can ever be observed (rename is the commit point).
- **Flush triggers.** :meth:`BlackBox.flush` writes a ``final-N.json``
  artifact carrying the FULL retained history plus everything else a
  post-mortem needs: the phase attributor's recent-waterfall ring, the
  flight recorder's raw span export, the doctor's live findings at
  flush time, and the frontend's ``/debug/state`` snapshot. Wired
  triggers: SIGTERM (the launch exit path), graceful drain
  (``policy/lifecycle.py`` step 5c), ``POST /admin/blackbox``, and the
  **unclean-death watchdog** — a thread that watches the sampler's
  heartbeat and flushes once if sampling ever stalls past its timeout
  (a wedged process writes its own black box while it still can; a
  hard kill falls back to the segments).
- **Post-mortem loading.** :func:`load_blackbox` reads a dump directory
  back into one merged series map (segments + final, deduped by sample
  sequence), flags ``unclean`` dumps (segments but no final — the
  kill -9 signature), and hands the result to
  ``obs/doctor.py::postmortem_report`` / ``scripts/doctor.py
  --blackbox`` for offline diagnosis.

Every dump file is schema-versioned (:data:`BLACKBOX_SCHEMA_VERSION`);
the loader refuses files from a future schema rather than misreading
them. Import-light on purpose (stdlib only).
"""

from __future__ import annotations

import json
import os
import threading
import time

from radixmesh_tpu.obs.metrics import TRANSFER_SECONDS_BUCKETS, get_registry
from radixmesh_tpu.utils.logging import get_logger

__all__ = ["BLACKBOX_SCHEMA_VERSION", "BlackBox", "load_blackbox"]

BLACKBOX_SCHEMA_VERSION = 1


def _atomic_write_json(path: str, obj: dict) -> int:
    """Write-to-temp + rename: a hard kill mid-write leaves the old
    file (or nothing), never a truncated JSON. Returns bytes written."""
    tmp = f"{path}.tmp.{os.getpid()}"
    data = json.dumps(obj, sort_keys=True)
    with open(tmp, "w") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return len(data)


class BlackBox:
    """One node's dump writer. Seams (all optional, duck-typed):
    ``history`` (the segment source + watchdog heartbeat), ``doctor``
    (live findings in the final dump), ``recorder`` (span export;
    callable-or-instance, so ``get_recorder`` survives test swaps),
    ``attributor_fn`` (waterfall report), ``state_fn`` (the
    ``/debug/state`` snapshot)."""

    def __init__(
        self,
        out_dir: str,
        history=None,
        doctor=None,
        recorder=None,
        attributor_fn=None,
        state_fn=None,
        node: str = "node",
        segment_every: int = 30,
        watchdog_timeout_s: float = 0.0,
        max_segments: int = 240,
    ):
        # One subdirectory per node: a shared --blackbox-dir across a
        # local fleet must not interleave nodes' segment counters.
        safe_node = "".join(
            c if c.isalnum() or c in "-_.@" else "_" for c in node
        ) or "node"
        self.dir = os.path.join(out_dir, safe_node)
        os.makedirs(self.dir, exist_ok=True)
        self.node = node
        self.log = get_logger("obs.blackbox")
        self._rotate_prior_dump()
        self.history = history
        self.doctor = doctor
        self.recorder = recorder
        self.attributor_fn = attributor_fn
        self.state_fn = state_fn
        self.segment_every = max(1, int(segment_every))
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        if (
            self.watchdog_timeout_s > 0
            and history is not None
            and self.watchdog_timeout_s <= 2.0 * history.interval_s
        ):
            # A timeout a healthy inter-sample gap can reach would spend
            # the ONE-SHOT unclean-death flush on a false positive at
            # boot — and a genuine wedge months later would then leave
            # no watchdog final at all.
            clamped = 10.0 * history.interval_s
            self.log.warning(
                "blackbox watchdog %.1fs is within reach of the %.1fs "
                "sample interval; clamping to %.1fs",
                self.watchdog_timeout_s, history.interval_s, clamped,
            )
            self.watchdog_timeout_s = clamped
        self.max_segments = max(1, int(max_segments))
        self._lock = threading.Lock()
        self._samples_since_segment = 0
        self._pruned_segments = 0
        self._segments = 0
        self._last_segment_seq = -1
        self._flushes = 0
        self._flush_causes: list[str] = []
        self._watchdog_fired = False
        self._stop = threading.Event()
        self._watchdog: threading.Thread | None = None

        reg = get_registry()
        self._m_flushes = reg.counter(
            "radixmesh_blackbox_flushes_total",
            "black-box final dumps written, by trigger cause",
            ("cause",),
        )
        self._m_segments = reg.counter(
            "radixmesh_blackbox_segments_total",
            "incremental history segments committed (atomic rename)",
        )
        self._m_bytes = reg.counter(
            "radixmesh_blackbox_bytes_total",
            "bytes committed to the black-box dump directory",
        )
        self._m_flush_seconds = reg.histogram(
            "radixmesh_blackbox_flush_seconds",
            "wall cost of one black-box flush (history + spans + "
            "waterfalls + doctor + state)",
            buckets=TRANSFER_SECONDS_BUCKETS,
        )
        self._write_manifest()
        if history is not None:
            history.on_sample = self._on_sample
        if self.watchdog_timeout_s > 0 and history is not None:
            self._watchdog = threading.Thread(
                target=self._watch, daemon=True, name="blackbox-watchdog"
            )
            self._watchdog.start()

    # -- manifest ------------------------------------------------------

    def _rotate_prior_dump(self) -> None:
        """A restarted node must not clobber (or merge into) a previous
        boot's dump — that evidence is exactly what the directory exists
        to preserve, and re-using its numbering would overwrite the old
        segments while a fresh final would erase the kill -9 signature.
        Move any existing dump files into a ``prior-NNN`` subdirectory
        (itself a complete, loadable dump) and start this boot clean."""
        leftovers = [
            n for n in os.listdir(self.dir)
            if n == "MANIFEST.json"
            or (n.startswith(("segment-", "final-")) and n.endswith(".json"))
        ]
        if not leftovers:
            return
        i = 0
        while os.path.exists(os.path.join(self.dir, f"prior-{i:03d}")):
            i += 1
        prior = os.path.join(self.dir, f"prior-{i:03d}")
        os.makedirs(prior)
        for name in leftovers:
            os.replace(
                os.path.join(self.dir, name), os.path.join(prior, name)
            )
        self.log.warning(
            "black-box dir %s held a previous boot's dump (%d files); "
            "rotated to %s",
            self.dir, len(leftovers), prior,
        )

    def _write_manifest(self) -> None:
        n = _atomic_write_json(
            os.path.join(self.dir, "MANIFEST.json"),
            {
                "schema_version": BLACKBOX_SCHEMA_VERSION,
                "node": self.node,
                "created_wall": time.time(),
                "interval_s": (
                    self.history.interval_s
                    if self.history is not None
                    else None
                ),
                "segment_every": self.segment_every,
                "pid": os.getpid(),
            },
        )
        self._m_bytes.inc(n)

    # -- incremental segments ------------------------------------------

    def _on_sample(self, seq: int) -> None:
        """History post-sample hook (sampler thread): commit a segment
        every ``segment_every`` samples."""
        with self._lock:
            self._samples_since_segment += 1
            due = self._samples_since_segment >= self.segment_every
            if due:
                self._samples_since_segment = 0
        if due:
            try:
                self.write_segment()
            except OSError:
                self.log.exception("black-box segment write failed")

    def write_segment(self) -> dict | None:
        """Commit one incremental segment: every ring point newer than
        the last committed segment. Returns the segment summary (None
        when nothing new landed)."""
        if self.history is None:
            return None
        with self._lock:
            since = self._last_segment_seq
            seg_no = self._segments
        body = self.history.dump(since=since)
        if body["points"] == 0 and seg_no > 0:
            return None
        seg = {
            "schema_version": BLACKBOX_SCHEMA_VERSION,
            "kind": "segment",
            "node": self.node,
            "segment": seg_no,
            "seq_range": [since + 1, body["seq"]],
            "wall_offset": body["wall_offset"],
            "interval_s": body["interval_s"],
            "series": body["series"],
        }
        n = _atomic_write_json(
            os.path.join(self.dir, f"segment-{seg_no:06d}.json"), seg
        )
        with self._lock:
            self._segments = seg_no + 1
            self._last_segment_seq = body["seq"]
        self._m_segments.inc()
        self._m_bytes.inc(n)
        # Bounded retention: a long-lived node must not grow the dump
        # dir (and the loader's memory) without limit — slide a window
        # of max_segments, dropping the one that just fell off (its
        # span left the in-process ring long ago).
        drop = seg_no - self.max_segments
        if drop >= 0:
            try:
                os.remove(
                    os.path.join(self.dir, f"segment-{drop:06d}.json")
                )
                with self._lock:
                    self._pruned_segments += 1
            except OSError:
                pass
        return {"segment": seg_no, "seq_range": seg["seq_range"], "bytes": n}

    # -- the flush -----------------------------------------------------

    def flush(self, cause: str) -> dict:
        """Write one ``final-N.json`` artifact: full retained history +
        waterfall ring + span export + live doctor findings + state
        snapshot. Each trigger writes its own numbered final (a drain
        followed by SIGTERM leaves both, each complete); the newest is
        the authoritative post-mortem. Crash-isolated per section — a
        broken seam loses its section, never the dump."""
        t0 = time.monotonic()
        with self._lock:
            n_final = self._flushes
            self._flushes = n_final + 1
            self._flush_causes.append(cause)
        dump: dict = {
            "schema_version": BLACKBOX_SCHEMA_VERSION,
            "kind": "final",
            "node": self.node,
            "cause": cause,
            "final": n_final,
            "wall": time.time(),
        }
        if self.history is not None:
            try:
                dump["history"] = self.history.dump()
                dump["history_stats"] = self.history.stats()
            except Exception:  # noqa: BLE001 — a seam bug must not lose the dump
                self.log.exception("black-box history section failed")
        if self.attributor_fn is not None:
            try:
                attr = self.attributor_fn()
                if attr is not None:
                    dump["waterfall"] = attr.report()
            except Exception:  # noqa: BLE001 — section isolation
                self.log.exception("black-box waterfall section failed")
        if self.recorder is not None:
            try:
                rec = (
                    self.recorder()
                    if callable(self.recorder)
                    else self.recorder
                )
                if rec is not None:
                    dump["spans"] = rec.export_spans()
            except Exception:  # noqa: BLE001 — section isolation
                self.log.exception("black-box span section failed")
        if self.doctor is not None:
            try:
                dump["doctor"] = self.doctor.diagnose()
            except Exception:  # noqa: BLE001 — section isolation
                self.log.exception("black-box doctor section failed")
        if self.state_fn is not None:
            try:
                dump["state"] = self.state_fn()
            except Exception:  # noqa: BLE001 — section isolation
                self.log.exception("black-box state section failed")
        path = os.path.join(self.dir, f"final-{n_final:03d}.json")
        n = _atomic_write_json(path, dump)
        self._m_flushes.labels(cause=cause).inc()
        self._m_bytes.inc(n)
        self._m_flush_seconds.observe(time.monotonic() - t0)
        self.log.info(
            "black box flushed (%s): %d bytes to %s", cause, n, path
        )
        return {"path": path, "cause": cause, "bytes": n, "final": n_final}

    # -- the unclean-death watchdog ------------------------------------

    def _watch(self) -> None:
        """Flush ONCE if the history sampler ever stalls past the
        timeout: a process wedged hard enough to stop its 1 s sampler
        is dying — write the black box while a thread still runs.
        (A SIGKILL outruns any watchdog; the segments are that case's
        artifact.)"""
        while not self._stop.wait(self.watchdog_timeout_s / 2.0):
            if self.history.last_sample_age_s() <= self.watchdog_timeout_s:
                continue
            with self._lock:
                if self._watchdog_fired:
                    return
                self._watchdog_fired = True
            try:
                self.flush("watchdog")
            except Exception:  # noqa: BLE001 — the watchdog must not raise on a dying node
                self.log.exception("watchdog flush failed")
            return

    # -- lifecycle -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "dir": self.dir,
                "segments": self._segments,
                "flushes": self._flushes,
                "flush_causes": list(self._flush_causes),
                "segment_every": self.segment_every,
                "max_segments": self.max_segments,
                "pruned_segments": self._pruned_segments,
                "watchdog_timeout_s": self.watchdog_timeout_s,
            }

    def close(self, flush_cause: str | None = None) -> None:
        """Detach from the history and stop the watchdog; with
        ``flush_cause`` set, write one last final artifact first (the
        SIGTERM path passes "sigterm"; a simulated hard kill passes
        None and leaves segments only)."""
        if flush_cause is not None:
            try:
                self.flush(flush_cause)
            except Exception:  # noqa: BLE001 — exit path
                self.log.exception("close flush failed")
        self._stop.set()
        if self.history is not None and self.history.on_sample == self._on_sample:
            self.history.on_sample = None
        if self._watchdog is not None:
            self._watchdog.join(timeout=2)


# ---------------------------------------------------------------------------
# post-mortem loading
# ---------------------------------------------------------------------------


def load_blackbox(path: str) -> dict:
    """Read one node's dump directory (or a multi-node ``--blackbox-dir``
    root holding exactly one node subdirectory) back into a post-mortem
    input:

    - ``series``: every ring point from every complete segment PLUS the
      newest final dump, merged and deduped by sample sequence.
    - ``unclean``: True when segments exist but no final does — the
      hard-kill signature (the process never reached a flush trigger).
    - ``last_t`` / ``last_seq``: where the recorded history ends (the
      crash-window anchor for unclean dumps).

    Raises ``ValueError`` on an empty directory or a future schema
    version (refuse rather than misread)."""
    if os.path.isfile(os.path.join(path, "MANIFEST.json")):
        node_dir = path
    else:
        subs = sorted(
            d for d in os.listdir(path)
            if os.path.isfile(os.path.join(path, d, "MANIFEST.json"))
        ) if os.path.isdir(path) else []
        if len(subs) != 1:
            raise ValueError(
                f"{path}: not a black-box dump (want a MANIFEST.json or "
                f"exactly one node subdirectory; found {subs})"
            )
        node_dir = os.path.join(path, subs[0])
    with open(os.path.join(node_dir, "MANIFEST.json")) as fh:
        manifest = json.load(fh)
    if manifest.get("schema_version", 0) > BLACKBOX_SCHEMA_VERSION:
        raise ValueError(
            f"black-box schema {manifest.get('schema_version')} is newer "
            f"than this reader ({BLACKBOX_SCHEMA_VERSION})"
        )
    segments: list[dict] = []
    finals: list[dict] = []
    for name in sorted(os.listdir(node_dir)):
        full = os.path.join(node_dir, name)
        if name.startswith("segment-") and name.endswith(".json"):
            with open(full) as fh:
                segments.append(json.load(fh))
        elif name.startswith("final-") and name.endswith(".json"):
            with open(full) as fh:
                finals.append(json.load(fh))
    # Merge: seq-keyed dedupe per series; finals carry the full ring so
    # the newest final wins ties (identical points either way).
    merged: dict[str, dict[int, tuple[float, float]]] = {}

    def fold(series: dict) -> None:
        for name, body in series.items():
            dst = merged.setdefault(name, {})
            for seq, t, v in body.get("points", ()):
                dst[int(seq)] = (float(t), float(v))

    for seg in segments:
        fold(seg.get("series", {}))
    final = finals[-1] if finals else None
    if final is not None and "history" in final:
        fold(final["history"].get("series", {}))
    series = {
        name: [[seq, t, v] for seq, (t, v) in sorted(pts.items())]
        for name, pts in sorted(merged.items())
    }
    last_seq = -1
    last_t = None
    for pts in series.values():
        if pts and pts[-1][0] > last_seq:
            last_seq, last_t = pts[-1][0], pts[-1][1]
    return {
        "node": manifest.get("node", "node"),
        "manifest": manifest,
        "schema_version": manifest.get("schema_version"),
        "segments": len(segments),
        "finals": len(finals),
        "final": final,
        "causes": [f.get("cause") for f in finals],
        # No final = unclean: every graceful exit path (shutdown,
        # drain, SIGTERM, watchdog) writes one, so even a manifest-only
        # dir — a node that died before its first segment commit — is
        # the unclean-death signature, not a clean dump.
        "unclean": not finals,
        "interval_s": manifest.get("interval_s"),
        "wall_offset": (
            segments[0].get("wall_offset")
            if segments
            else (final or {}).get("history", {}).get("wall_offset", 0.0)
        ),
        "series": series,
        "last_seq": last_seq,
        "last_t": last_t,
    }
