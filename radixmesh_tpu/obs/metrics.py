"""Prometheus-style metrics: counters, gauges, histograms + text exposition.

The reference has no metrics at all — its ``TreeNode.hit_count`` is never
incremented and its benchmark emits no timings (SURVEY §5 "observability";
``radix_cache.py:47``, ``benchmark.py:24-31``). This module supplies the
rebuild's observability spine: hit-rate / hit-length, oplog traffic + lag,
GC reclamation, TTFT/TPOT — exposed programmatically (:meth:`Registry.snapshot`)
and in Prometheus text exposition format (:meth:`Registry.render`) for
scraping by the serving frontend.

Design notes: metric families are registered once per (name, type); calling
a registry factory again returns the existing family, so modules can grab
their metrics at construction time without coordinating. Label sets
materialize child series on first use. All mutation is lock-guarded —
series are updated from transport reader threads, the engine loop, and GC
threads concurrently.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Iterable, Sequence

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "set_registry",
    "DEFAULT_BUCKETS",
    "TOKEN_LEN_BUCKETS",
    "TRANSFER_SECONDS_BUCKETS",
    "REPAIR_SECONDS_BUCKETS",
    "RECOVERY_SECONDS_BUCKETS",
    "PHASE_SECONDS_BUCKETS",
]

# Latency-oriented default buckets (seconds): 1ms .. 60s.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# KV-movement buckets (seconds): the async transfer plane's per-op
# blocking costs (arena memcopies, staged chunk reads, handoff packs —
# cache/kv_transfer.py) live in the 10µs–10ms band, below
# DEFAULT_BUCKETS' 1ms floor; a histogram on those buckets would read
# as all-zeros. Shared so every kv_transfer lane bins identically.
TRANSFER_SECONDS_BUCKETS: tuple[float, ...] = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 0.001,
    0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 2.0,
)

# Token-count buckets (powers of two through the 32k long-context config,
# BASELINE.json "configs") — shared by every hit-length/match-length
# histogram so dashboards can compare them bucket-for-bucket.
TOKEN_LEN_BUCKETS: tuple[float, ...] = tuple(float(1 << i) for i in range(16))

# Anti-entropy repair buckets (seconds): a repair round spans probe →
# summary exchange → ring re-publication, so its latency rides the ring
# (ms on inproc/loopback) plus the peer's backoff schedule (seconds to
# a minute) — a wider band than DEFAULT_BUCKETS resolves at the top end
# and than TRANSFER_SECONDS_BUCKETS covers at all. Shared by
# cache/repair_plane.py so every node bins rounds identically.
REPAIR_SECONDS_BUCKETS: tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

# Request-recovery buckets (seconds): a recovery episode spans hop
# timeout (tens of ms to seconds) + jittered backoff + re-route +
# re-prefill — the death-to-first-resumed-token blip the recovery plane
# (server/recovery.py) exists to keep small. Shared so every edge bins
# resurrection latency identically.
RECOVERY_SECONDS_BUCKETS: tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.0, 5.0, 10.0, 30.0, 60.0,
)

# Critical-path phase buckets (seconds): one request's end-to-end time
# decomposes into EXCLUSIVE per-phase slices (obs/attribution.py), and
# those slices span five orders of magnitude — a publish is tens of µs,
# a convoyed prefill wait is seconds, an SLO queue stall under overload
# is tens of seconds. DEFAULT_BUCKETS' 1 ms floor would flatten the fast
# phases to zeros and TRANSFER_SECONDS_BUCKETS tops out at 2 s, below a
# convoy. Shared by every phase of radixmesh_request_phase_seconds so
# p50/p99 phase breakdowns compare bucket-for-bucket.
PHASE_SECONDS_BUCKETS: tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _label_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _escape(v: str) -> str:
    """Label-value escaping per the Prometheus exposition spec — an
    unescaped quote/backslash/newline would make the whole scrape
    unparseable, not just this series."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Family:
    """One named metric family; holds labeled child series."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: dict[tuple[tuple[str, str], ...], "_Family"] = {}
        self._labels: tuple[tuple[str, str], ...] = ()

    def labels(self, **labels: str):
        """Child series for a concrete label assignment."""
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {tuple(labels)}"
            )
        key = _label_key({k: str(v) for k, v in labels.items()})
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                child._labels = key
                self._children[key] = child
            return child

    def _new_child(self) -> "_Family":
        return type(self)(self.name, self.help)

    def _series(self) -> Iterable["_Family"]:
        if self.label_names:
            with self._lock:
                return list(self._children.values())
        return [self]

    # subclasses: _render_lines(self) and snapshot value accessors


class Counter(_Family):
    """Monotonically increasing count."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", label_names: Sequence[str] = ()):
        super().__init__(name, help, label_names)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render_lines(self) -> list[str]:
        return [
            f"{self.name}{_fmt_labels(s._labels)} {_fmt_value(s._value)}"
            for s in self._series()
        ]


class Gauge(_Family):
    """Value that can go up and down."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", label_names: Sequence[str] = ()):
        super().__init__(name, help, label_names)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render_lines(self) -> list[str]:
        return [
            f"{self.name}{_fmt_labels(s._labels)} {_fmt_value(s._value)}"
            for s in self._series()
        ]


class _HistTimer:
    def __init__(self, hist: "Histogram"):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.monotonic() - self._t0)
        return False


class Histogram(_Family):
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations ≤ its upper bound; ``+Inf`` bucket == count)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        # Trace exemplars: bucket index -> (trace_id, value, wall time),
        # the LAST traced observation to land in that bucket. Lazily
        # allocated — an untraced histogram never pays the dict.
        self._exemplars: dict[int, tuple[int, float, float]] | None = None

    def _new_child(self) -> "Histogram":
        return Histogram(self.name, self.help, buckets=self.buckets)

    def observe(self, value: float, trace_id: int | None = None) -> None:
        # bisect_left finds the first bound >= value — the bucket whose
        # "<= upper bound" predicate the value satisfies; past the last
        # bound it lands on the +Inf slot. O(log buckets) instead of the
        # linear scan: observe() sits on per-token serving hot paths
        # (TPOT, oplog lag) where the common sample lands in the upper
        # buckets the scan visited last.
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._sum += value
            self._counts[i if i < len(self.buckets) else -1] += 1
            # One `is not None` test when tracing is off (the step-
            # accounting hot-path contract); a traced observation pins
            # itself as the bucket's exemplar so a percentile that
            # resolves into this bucket links to a concrete request
            # flight (obs/trace_plane.py stitching).
            if trace_id is not None:
                if self._exemplars is None:
                    self._exemplars = {}
                self._exemplars[min(i, len(self.buckets))] = (
                    int(trace_id), float(value), time.time()
                )

    def time(self) -> _HistTimer:
        """``with hist.time(): ...`` observes the block's wall time."""
        return _HistTimer(self)

    def _le_str(self, i: int) -> str:
        return (
            _fmt_value(self.buckets[i]) if i < len(self.buckets) else "+Inf"
        )

    def exemplars(self) -> dict[str, dict]:
        """Per-bucket trace exemplars of THIS series: ``le`` string →
        ``{trace_id, value, wall_time}`` with the trace id rendered the
        way span exports carry it (``trace_plane.export_spans``), so a
        reader can join straight into a stitched trace. {} when no
        traced observation ever landed."""
        with self._lock:
            ex = dict(self._exemplars) if self._exemplars else {}
        return {
            self._le_str(i): {
                "trace_id": f"{tid:#018x}",
                "value": v,
                "wall_time": round(t, 6),
            }
            for i, (tid, v, t) in sorted(ex.items())
        }

    def bucket_counts(self) -> list[int]:
        """Cumulative per-bucket counts (Prometheus ``le`` semantics:
        entry i counts observations <= buckets[i]; the final entry is
        +Inf == count), read under one lock so the vector is a
        consistent snapshot — the cross-node merge sums these."""
        with self._lock:
            out = []
            cum = 0
            for c in self._counts:
                cum += c
                out.append(cum)
            return out

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Approximate quantile with linear interpolation inside the
        selected bucket (Prometheus ``histogram_quantile`` semantics).
        Returning the bucket's upper bound snapped every estimate to a
        bucket edge — a 1.1 ms median read as 2.5 ms — wherever a
        histogram-derived quantile surfaces (``/debug/state`` latency
        estimates; bench/workload medians come from raw samples and were
        never affected). Still approximate (uniform-within-bucket
        assumption); exact values need the raw samples."""
        with self._lock:
            total = sum(self._counts)
            if total == 0:
                return 0.0
            target = q * total
            acc = 0
            for i, ub in enumerate(self.buckets):
                in_bucket = self._counts[i]
                if acc + in_bucket >= target and in_bucket > 0:
                    # Lower edge: the previous bound, or 0 for the first
                    # bucket of a positive-bounded histogram (latencies/
                    # token counts — every histogram in this repo).
                    lo = self.buckets[i - 1] if i > 0 else min(0.0, ub)
                    return lo + (ub - lo) * (target - acc) / in_bucket
                acc += in_bucket
            # Target falls in the +Inf bucket: no finite upper edge to
            # interpolate toward — report the largest finite bound
            # (what PromQL does) rather than inf.
            return self.buckets[-1] if self.buckets else float("inf")

    def _render_lines(self) -> list[str]:
        lines: list[str] = []
        for s in self._series():
            with s._lock:
                ex = dict(s._exemplars) if s._exemplars else {}
                cum = 0
                for i, ub in enumerate(s.buckets):
                    cum += s._counts[i]
                    lbl = dict(s._labels)
                    lbl["le"] = _fmt_value(ub)
                    lines.append(
                        f"{self.name}_bucket{_fmt_labels(_label_key(lbl))} {cum}"
                    )
                    if i in ex:
                        tid, v, t = ex[i]
                        lines.append(
                            f"# EXEMPLAR {self.name}_bucket"
                            f"{_fmt_labels(_label_key(lbl))} "
                            f"trace_id={tid:#018x} value={_fmt_value(v)} "
                            f"wall_time={t:.6f}"
                        )
                cum += s._counts[-1]
                lbl = dict(s._labels)
                lbl["le"] = "+Inf"
                lines.append(f"{self.name}_bucket{_fmt_labels(_label_key(lbl))} {cum}")
                inf_key = len(s.buckets)
                if inf_key in ex:
                    tid, v, t = ex[inf_key]
                    lines.append(
                        f"# EXEMPLAR {self.name}_bucket"
                        f"{_fmt_labels(_label_key(lbl))} "
                        f"trace_id={tid:#018x} value={_fmt_value(v)} "
                        f"wall_time={t:.6f}"
                    )
                lines.append(f"{self.name}_sum{_fmt_labels(s._labels)} {_fmt_value(s._sum)}")
                lines.append(f"{self.name}_count{_fmt_labels(s._labels)} {cum}")
        return lines


class Registry:
    """Named metric families; idempotent registration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get_or_create(self, cls, name: str, help: str, label_names, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}"
                    )
                # A silent mismatch here would corrupt telemetry far from
                # the bad registration — fail at registration time instead.
                if tuple(label_names) != fam.label_names:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.label_names}, not {tuple(label_names)}"
                    )
                buckets = kw.get("buckets")
                if buckets is not None and tuple(sorted(buckets)) != fam.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with buckets "
                        f"{fam.buckets}"
                    )
                return fam
            fam = cls(name, help, label_names, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", label_names: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "", label_names: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(
        self,
        name: str,
        help: str = "",
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, label_names, buckets=buckets)

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: list[str] = []
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
        for f in families:
            if f.help:
                out.append(f"# HELP {f.name} {f.help}")
            out.append(f"# TYPE {f.name} {f.kind}")
            out.extend(f._render_lines())
        return "\n".join(out) + "\n"

    def snapshot(
        self, bucket_families: Sequence[str] = ()
    ) -> dict[str, float]:
        """Flat programmatic view: scalar series by rendered name.

        Histograms flatten to ``_count``/``_sum`` scalars; families
        named in ``bucket_families`` ADDITIONALLY emit their cumulative
        per-bucket counts as ``name_bucket{...,le="x"}`` series — the
        transport for cross-node percentile merging (a fleet collector
        sums bucket counts across nodes; averaging per-node quantiles
        is statistically wrong). Opt-in per family on purpose: buckets
        multiply series count ~16x, and only families a fleet view
        merges (the per-tenant request-latency histograms) earn that."""
        bucket_families = set(bucket_families)
        snap: dict[str, float] = {}
        with self._lock:
            families = list(self._families.values())
        for f in families:
            for s in f._series():
                # Rendered key cached per series: labels are fixed at
                # child creation, and the history sampler calls this for
                # every series at every tick — re-formatting hundreds of
                # label strings per sweep was the sampler's top cost.
                key = getattr(s, "_snap_key", None)
                if key is None:
                    key = s._snap_key = f"{f.name}{_fmt_labels(s._labels)}"
                if isinstance(s, Histogram):
                    snap[key + "_count"] = s.count
                    snap[key + "_sum"] = s.sum
                    if f.name in bucket_families:
                        for i, cum in enumerate(s.bucket_counts()):
                            lbl = dict(s._labels)
                            lbl["le"] = s._le_str(i)
                            snap[
                                f"{f.name}_bucket"
                                f"{_fmt_labels(_label_key(lbl))}"
                            ] = float(cum)
                else:
                    snap[key] = s.value
        return snap

    def exemplars(self) -> dict[str, dict[str, dict]]:
        """Every histogram series' trace exemplars, keyed the way
        :meth:`snapshot` keys series (``family{labels}``): the
        ``/debug/state`` exemplar section and the in-proc source a
        fleet collector joins against merged bucket counts. Series
        with no traced observations are omitted."""
        out: dict[str, dict[str, dict]] = {}
        with self._lock:
            families = list(self._families.values())
        for f in families:
            if not isinstance(f, Histogram):
                continue
            for s in f._series():
                ex = s.exemplars()
                if ex:
                    out[f"{f.name}{_fmt_labels(s._labels)}"] = ex
        return out


_default = Registry()
_default_lock = threading.Lock()


def get_registry() -> Registry:
    """Process-wide default registry."""
    return _default


def set_registry(reg: Registry) -> Registry:
    """Swap the process-wide default (tests use this for isolation)."""
    global _default
    with _default_lock:
        _default = reg
    return reg
