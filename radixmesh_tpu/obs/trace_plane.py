"""Request-flight tracing plane: span trees + bounded flight recorder.

``obs/metrics.py`` answers "how is the fleet doing" in aggregate;
nothing in the repo could answer "where did THIS request's 400 ms go?".
This module supplies the per-request causal timeline:

- A :class:`TraceContext` is created per request at the serving edge
  (``Engine.make_request`` / the HTTP frontend) and carried on
  ``Request.trace``; every layer the request crosses — SLO admission,
  routing, prefill waves, decode launches, publish, disagg handoff —
  records completed :class:`Span`\\ s against it.
- Spans land in a :class:`FlightRecorder`: a bounded in-memory ring
  (drop-oldest under pressure, counted) that costs one lock + one deque
  append per span, and exactly ONE branch per call site when tracing is
  off (``trace()``/``event()`` return before touching any span state).
- :meth:`FlightRecorder.chrome_trace` exports Chrome trace-event JSON
  ("traceEvents") loadable in Perfetto / ``chrome://tracing``; lanes map
  to Perfetto threads (one per request, per ring node, per engine), so
  a request's admission wait / prefill wave / decode chunks / publish
  read as one horizontal story, with ring replication-lag spans on the
  mesh lanes below it.
- The async KV-movement plane (``cache/kv_transfer.py``) records its
  lanes here too: ``kv_restore`` (on the request's lane when a parked
  restore completes, and per-node on the plane's ``kv:`` lane),
  ``kv_writeback`` (fused eviction-sweep copies on the worker), and
  ``kv_handoff_stage`` (disagg placement staged off the reader thread)
  — so a KV copy that DOES stall something shows up next to the decode
  chunks it delayed.
- The anti-entropy repair plane (``cache/repair_plane.py``) records one
  ``repair_round`` span per completed session on its ``repair:<node>``
  lane (cat ``repair``: probe → answering summary, with the peer rank,
  bucket count, and keys pushed as args) — so a repair storm, if one
  ever got past the backoff limits, would be visible interleaved with
  the request timelines it competes with.

Ring replication lag carries NO trace id across the wire (no wire-format
change): lag spans are derived receiver-side from the oplog's existing
origin wall-clock timestamp and recorded on per-node lanes; correlation
with a request is by time overlap, which is what a timeline viewer shows
anyway.

Overhead model: sampling off (the default) short-circuits at the first
``if`` in :meth:`FlightRecorder.trace` — no allocation, no lock, no
clock read at any instrumentation site (call sites are all shaped
``tr = req.trace; if tr is not None: ...``). Sampling on costs ~one
dict + one deque append per span under a short lock; the recorder is
bounded, so a trace storm degrades to dropped-oldest spans, never to
unbounded heap growth.

This module is import-light on purpose (stdlib only — no jax): router
nodes and artifact tests use it without pulling in a backend.
"""

from __future__ import annotations

import itertools
import json
import random
import threading
import time
from collections import deque
from dataclasses import dataclass

__all__ = [
    "Span",
    "TraceContext",
    "FlightRecorder",
    "get_recorder",
    "set_recorder",
    "configure",
    "write_trace",
]


@dataclass
class Span:
    """One completed span: monotonic start + duration, on a named lane."""

    name: str
    lane: str  # Perfetto thread lane, e.g. "req:17", "ring:prefill@0"
    t0: float  # time.monotonic() seconds at span start
    dur: float  # seconds
    trace_id: int  # 0 = not tied to a request trace (node-scope events)
    cat: str = "serving"
    args: dict | None = None


class TraceContext:
    """Per-request handle: a trace id + the lane its spans land on.

    Intentionally tiny — it is carried on every ``Request`` and tested
    for ``None`` on hot paths; all recording funnels through the owning
    recorder so swap-for-isolation (tests) keeps working.
    """

    __slots__ = ("trace_id", "lane", "_rec")

    def __init__(self, trace_id: int, lane: str, rec: "FlightRecorder"):
        self.trace_id = trace_id
        self.lane = lane
        self._rec = rec

    def add(
        self,
        name: str,
        t0: float,
        dur: float,
        cat: str = "serving",
        **args,
    ) -> None:
        """Record a completed span from explicit timestamps (most engine
        spans derive from bookkeeping the scheduler already stamps —
        submit/admit/first-token — so no extra clock reads)."""
        self._rec._record(
            Span(name, self.lane, t0, max(0.0, dur), self.trace_id, cat,
                 args or None)
        )

    def span(self, name: str, cat: str = "serving", **args) -> "_SpanTimer":
        """``with ctx.span("publish"): ...`` — wall-times the block."""
        return _SpanTimer(self, name, cat, args)


class _SpanTimer:
    __slots__ = ("_ctx", "_name", "_cat", "_args", "_t0")

    def __init__(self, ctx: TraceContext, name: str, cat: str, args: dict):
        self._ctx = ctx
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> "_SpanTimer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        self._ctx.add(
            self._name,
            self._t0,
            time.monotonic() - self._t0,
            cat=self._cat,
            **self._args,
        )
        return False


class FlightRecorder:
    """Bounded in-memory span ring with drop-oldest semantics.

    ``sample`` gates everything: 0.0 (default) disables tracing with a
    one-branch fast path; 1.0 traces every request; in between, each
    request (or node-scope event) flips an independent coin. Capacity
    bounds post-mortem memory — a storm past it drops the OLDEST spans
    (the fresh ones are the ones a live debugger wants) and counts the
    drops.
    """

    def __init__(self, capacity: int = 8192, sample: float = 0.0):
        if capacity <= 0:
            raise ValueError("trace capacity must be positive")
        self.capacity = int(capacity)
        self.sample = float(sample)
        self._lock = threading.Lock()
        self._buf: deque[Span] = deque(maxlen=self.capacity)
        self._ids = itertools.count(1)
        self._rng = random.Random(0xF117)  # deterministic sampling sequence
        self.recorded = 0  # spans accepted (lifetime)
        self.dropped = 0  # spans evicted by the ring bound (lifetime)

    # -- the hot-path gates -------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0

    def trace(self, lane: str, force: bool = False) -> TraceContext | None:
        """New per-request trace context, or None when tracing is off /
        this request lost the sampling coin flip. THE no-op guard: the
        disabled path is one float compare + return. ``force`` skips the
        coin flip (NOT the off switch) — used when an upstream node
        already decided this request is traced (disagg handoff), so a
        fractional sample yields whole cross-node timelines, not halves."""
        if self.sample <= 0.0:
            return None
        if (
            not force
            and self.sample < 1.0
            and self._rng.random() >= self.sample
        ):
            return None
        return TraceContext(next(self._ids), lane, self)

    def event(
        self,
        lane: str,
        name: str,
        t0: float,
        dur: float,
        cat: str = "serving",
        **args,
    ) -> None:
        """Node-scope span not tied to a request trace (ring replication
        lag, eviction sweeps, route decisions). Same one-branch guard."""
        if self.sample <= 0.0:
            return
        if self.sample < 1.0 and self._rng.random() >= self.sample:
            return
        self._record(Span(name, lane, t0, max(0.0, dur), 0, cat, args or None))

    # -- storage -------------------------------------------------------

    def _record(self, span: Span) -> None:
        with self._lock:
            if len(self._buf) == self.capacity:
                self.dropped += 1  # deque(maxlen) evicts the oldest
            self._buf.append(span)
            self.recorded += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self._buf)

    def drain(self) -> list[Span]:
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
            return out

    # -- export --------------------------------------------------------

    def chrome_trace(self, spans: list[Span] | None = None, drain: bool = False) -> dict:
        """Chrome trace-event JSON (the ``traceEvents`` array format) —
        load in Perfetto (ui.perfetto.dev) or ``chrome://tracing``.

        Lanes become threads of one process, named via ``thread_name``
        metadata events; complete-event (``ph: "X"``) timestamps are
        microseconds from the earliest span, emitted non-decreasing
        within each lane."""
        if spans is None:
            spans = self.drain() if drain else self.snapshot()
        base = min((s.t0 for s in spans), default=0.0)
        lanes: dict[str, int] = {}
        events: list[dict] = []
        # Sort by (lane, t0): within-lane ts monotonicity is part of the
        # artifact contract (bench.validate_trace checks it).
        for s in sorted(spans, key=lambda s: (s.lane, s.t0)):
            tid = lanes.setdefault(s.lane, len(lanes) + 1)
            ev = {
                "name": s.name,
                "cat": s.cat,
                "ph": "X",
                "ts": round((s.t0 - base) * 1e6, 3),
                "dur": round(s.dur * 1e6, 3),
                "pid": 1,
                "tid": tid,
            }
            args = dict(s.args or {})
            if s.trace_id:
                args["trace_id"] = s.trace_id
            if args:
                ev["args"] = args
            events.append(ev)
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": lane},
            }
            for lane, tid in lanes.items()
        ]
        return {
            "displayTimeUnit": "ms",
            "traceEvents": meta + events,
            "otherData": {
                "recorder": {
                    "capacity": self.capacity,
                    "sample": self.sample,
                    "recorded": self.recorded,
                    "dropped": self.dropped,
                },
            },
        }

    def stats(self) -> dict:
        """Programmatic recorder state for ``/debug/state``."""
        with self._lock:
            buffered = len(self._buf)
        return {
            "capacity": self.capacity,
            "sample": self.sample,
            "enabled": self.enabled,
            "buffered_spans": buffered,
            "recorded_spans": self.recorded,
            "dropped_spans": self.dropped,
        }


_default = FlightRecorder()
_default_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """Process-wide default recorder (disabled until configured)."""
    return _default


def set_recorder(rec: FlightRecorder) -> FlightRecorder:
    """Swap the process-wide default (tests use this for isolation)."""
    global _default
    with _default_lock:
        _default = rec
    return rec


def configure(capacity: int = 8192, sample: float = 1.0) -> FlightRecorder:
    """Enable tracing process-wide: install a fresh recorder with the
    given bound + sampling rate (``launch.py --trace-capacity/-sample``)."""
    return set_recorder(FlightRecorder(capacity=capacity, sample=sample))


def write_trace(path: str, drain: bool = True) -> int:
    """Dump the default recorder as a Chrome trace-event artifact.
    Returns the number of spans written."""
    rec = get_recorder()
    spans = rec.drain() if drain else rec.snapshot()
    obj = rec.chrome_trace(spans=spans)
    with open(path, "w") as fh:
        json.dump(obj, fh)
    return len(spans)
