"""Request-flight tracing plane: span trees + bounded flight recorder.

``obs/metrics.py`` answers "how is the fleet doing" in aggregate;
nothing in the repo could answer "where did THIS request's 400 ms go?".
This module supplies the per-request causal timeline:

- A :class:`TraceContext` is created per request at the serving edge
  (``Engine.make_request`` / the HTTP frontend) and carried on
  ``Request.trace``; every layer the request crosses — SLO admission,
  routing, prefill waves, decode launches, publish, disagg handoff —
  records completed :class:`Span`\\ s against it.
- Spans land in a :class:`FlightRecorder`: a bounded in-memory ring
  (drop-oldest under pressure, counted) that costs one lock + one deque
  append per span, and exactly ONE branch per call site when tracing is
  off (``trace()``/``event()`` return before touching any span state).
- :meth:`FlightRecorder.chrome_trace` exports Chrome trace-event JSON
  ("traceEvents") loadable in Perfetto / ``chrome://tracing``; lanes map
  to Perfetto threads (one per request, per ring node, per engine), so
  a request's admission wait / prefill wave / decode chunks / publish
  read as one horizontal story, with ring replication-lag spans on the
  mesh lanes below it.
- The async KV-movement plane (``cache/kv_transfer.py``) records its
  lanes here too: ``kv_restore`` (on the request's lane when a parked
  restore completes, and per-node on the plane's ``kv:`` lane),
  ``kv_writeback`` (fused eviction-sweep copies on the worker), and
  ``kv_handoff_stage`` (disagg placement staged off the reader thread)
  — so a KV copy that DOES stall something shows up next to the decode
  chunks it delayed.
- The anti-entropy repair plane (``cache/repair_plane.py``) records one
  ``repair_round`` span per completed session on its ``repair:<node>``
  lane (cat ``repair``: probe → answering summary, with the peer rank,
  bucket count, and keys pushed as args) — so a repair storm, if one
  ever got past the backoff limits, would be visible interleaved with
  the request timelines it competes with.

Cross-node stitching (PR 9): trace ids are 64-bit and globally unique
(splitmix64 over a process-scoped counter mixed with the pid), so the id
itself can cross the wire. Every inter-node hop now carries it — the
``/generate`` body (resume/hedge re-routes), the disagg handoff packet
header, and data-kind oplog frames (an optional, old-wire-tolerant
trailer — ``cache/oplog.py``) — and receivers open their spans under the
ORIGINATING id instead of minting a new one. Each span additionally
carries the ``node`` label of the process/role that recorded it, and
:meth:`FlightRecorder.merge` folds many nodes' span exports
(``export_spans`` / ``GET /debug/trace?format=spans``) into ONE Perfetto
document with one process-track per node, correcting clock offsets from
each export's wall-vs-monotonic base (plus optional per-node skew
estimates from the fleet plane's digest timestamps) — a resurrected
request's router → prefill → handoff → decode → resurrection journey
reads as a single flame view. Ring replication-lag spans are still
derived receiver-side from the oplog's origin wall-clock timestamp, but
when the frame carries a trace id the lag span lands UNDER it — the
replication edge is part of the request's timeline, not just time
overlap.

Overhead model: sampling off (the default) short-circuits at the first
``if`` in :meth:`FlightRecorder.trace` — no allocation, no lock, no
clock read at any instrumentation site (call sites are all shaped
``tr = req.trace; if tr is not None: ...``). Sampling on costs ~one
dict + one deque append per span under a short lock; the recorder is
bounded, so a trace storm degrades to dropped-oldest spans, never to
unbounded heap growth.

This module is import-light on purpose (stdlib only — no jax): router
nodes and artifact tests use it without pulling in a backend.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass

__all__ = [
    "Span",
    "TraceContext",
    "FlightRecorder",
    "get_recorder",
    "set_recorder",
    "configure",
    "write_trace",
    "new_trace_id",
    "stitch_traces",
    "dropped_spans_counter",
]

_M64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer (the tree-fingerprint mixing family)."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


_id_counter = itertools.count(1)


def dropped_spans_counter():
    """The trace-loss counter family,
    ``radixmesh_trace_dropped_spans_total{node}`` — resolved from the
    CURRENT registry at the drop site (not cached at recorder
    construction) so registry swaps in tests never strand increments on
    a stale registry. A bounded drop-oldest ring is correct storm
    behavior, but a SILENT one lies: every evicted span now lands on a
    scrapeable counter, every export declares its ``dropped`` total, and
    the attributor refuses holed traces (see ``FlightRecorder``)."""
    from radixmesh_tpu.obs.metrics import get_registry

    return get_registry().counter(
        "radixmesh_trace_dropped_spans_total",
        "flight-recorder spans evicted by the ring bound before export "
        "(trace-loss visibility: a stitched artifact from a node with "
        "drops has declared, not silent, coverage gaps)",
        ("node",),
    )


def new_trace_id() -> int:
    """A fresh 64-bit trace id, unique within the process (global
    counter) and collision-resistant across processes (pid mixed in) —
    the id that crosses the wire so receivers stitch their spans under
    the originating request instead of minting node-local ids that
    collide at merge time. Never 0 (0 = "no trace" on every wire)."""
    tid = _mix64(((os.getpid() & 0xFFFFF) << 40) ^ next(_id_counter))
    return tid or 1


@dataclass
class Span:
    """One completed span: monotonic start + duration, on a named lane."""

    name: str
    lane: str  # Perfetto thread lane, e.g. "req:17", "ring:prefill@0"
    t0: float  # time.monotonic() seconds at span start
    dur: float  # seconds
    trace_id: int  # 0 = not tied to a request trace (node-scope events)
    cat: str = "serving"
    args: dict | None = None
    # Node that recorded the span ("" = the recorder's default). The
    # stitched export groups spans into one Perfetto process-track per
    # node — in-process multi-node harnesses share ONE recorder, so the
    # node must ride the span, not the recorder.
    node: str = ""


class TraceContext:
    """Per-request handle: a trace id + the lane its spans land on.

    Intentionally tiny — it is carried on every ``Request`` and tested
    for ``None`` on hot paths; all recording funnels through the owning
    recorder so swap-for-isolation (tests) keeps working.
    """

    __slots__ = ("trace_id", "lane", "node", "_rec")

    def __init__(
        self,
        trace_id: int,
        lane: str,
        rec: "FlightRecorder",
        node: str = "",
    ):
        self.trace_id = trace_id
        self.lane = lane
        self.node = node
        self._rec = rec

    def add(
        self,
        name: str,
        t0: float,
        dur: float,
        cat: str = "serving",
        **args,
    ) -> None:
        """Record a completed span from explicit timestamps (most engine
        spans derive from bookkeeping the scheduler already stamps —
        submit/admit/first-token — so no extra clock reads)."""
        self._rec._record(
            Span(name, self.lane, t0, max(0.0, dur), self.trace_id, cat,
                 args or None, self.node)
        )

    def span(self, name: str, cat: str = "serving", **args) -> "_SpanTimer":
        """``with ctx.span("publish"): ...`` — wall-times the block."""
        return _SpanTimer(self, name, cat, args)


class _SpanTimer:
    __slots__ = ("_ctx", "_name", "_cat", "_args", "_t0")

    def __init__(self, ctx: TraceContext, name: str, cat: str, args: dict):
        self._ctx = ctx
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> "_SpanTimer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        self._ctx.add(
            self._name,
            self._t0,
            time.monotonic() - self._t0,
            cat=self._cat,
            **self._args,
        )
        return False


class FlightRecorder:
    """Bounded in-memory span ring with drop-oldest semantics.

    ``sample`` gates everything: 0.0 (default) disables tracing with a
    one-branch fast path; 1.0 traces every request; in between, each
    request (or node-scope event) flips an independent coin. Capacity
    bounds post-mortem memory — a storm past it drops the OLDEST spans
    (the fresh ones are the ones a live debugger wants) and counts the
    drops.

    Drops are never silent (PR 12): every eviction increments
    ``radixmesh_trace_dropped_spans_total{node}``, every export carries
    the lifetime ``dropped`` count (so a stitched artifact declares its
    coverage), and evicting a trace-id-bearing span marks that trace id
    as HOLED — the phase attributor (``obs/attribution.py``) refuses to
    decompose a holed trace into a waterfall instead of publishing a
    breakdown with interior gaps, and counts the refusal.
    """

    # Bound on the holed-trace-id memory: past it the set stops growing
    # and ``drops_untracked`` flips — attribution then refuses EVERY
    # trace conservatively (a storm that evicted 4k distinct traces has
    # destroyed any per-request story worth telling anyway).
    DROPPED_TRACE_CAP = 4096

    def __init__(
        self, capacity: int = 8192, sample: float = 0.0, node: str = ""
    ):
        if capacity <= 0:
            raise ValueError("trace capacity must be positive")
        self.capacity = int(capacity)
        self.sample = float(sample)
        # Default node label for contexts/events that don't name one
        # (single-node processes set it once via configure(node=...)).
        self.node = node
        # This process's monotonic→wall conversion, captured once: the
        # stitcher shifts every export into a shared wall-clock base
        # with it (per-node clock skew is corrected separately).
        self.wall_offset = time.time() - time.monotonic()
        self._lock = threading.Lock()
        self._buf: deque[Span] = deque(maxlen=self.capacity)
        self._rng = random.Random(0xF117)  # deterministic sampling sequence
        self.recorded = 0  # spans accepted (lifetime)
        self.dropped = 0  # spans evicted by the ring bound (lifetime)
        # Live per-trace span index: every buffered span with a nonzero
        # trace id sits in exactly one list (evictions remove it), so a
        # retire-time waterfall is one dict lookup, not a ring scan.
        self._by_tid: dict[int, list[Span]] = {}
        # Trace ids that LOST at least one span to the ring bound.
        self._dropped_tids: set[int] = set()
        self.drops_untracked = False  # dropped-tid set hit its cap
        # Span-retire hook (obs/attribution.py installs it): called with
        # (retire_span, recorder) AFTER the span landed, outside the
        # buffer lock, whenever a span named in ``retire_spans`` records.
        # None (the default) keeps _record one append — the PR 2
        # one-branch contract extends here: sampling off records nothing,
        # so the hook costs zero when tracing is off.
        self.retire_hook = None
        self.retire_spans: frozenset[str] = frozenset()
        # The installed PhaseAttributor (obs/attribution.py), if any —
        # carried on the recorder so a registry/recorder swap in tests
        # gets a fresh one via ensure_attributor().
        self.attributor = None

    # -- the hot-path gates -------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0

    def trace(
        self,
        lane: str,
        force: bool = False,
        trace_id: int | None = None,
        node: str | None = None,
    ) -> TraceContext | None:
        """New per-request trace context, or None when tracing is off /
        this request lost the sampling coin flip. THE no-op guard: the
        disabled path is one float compare + return. ``force`` skips the
        coin flip (NOT the off switch) — used when an upstream node
        already decided this request is traced (disagg handoff), so a
        fractional sample yields whole cross-node timelines, not halves.
        ``trace_id`` ADOPTS an upstream node's 64-bit id (implies
        ``force`` — the id's existence IS the upstream decision), so the
        receiver's spans stitch under the originating request; None
        mints a fresh globally-unique id (``new_trace_id``)."""
        if self.sample <= 0.0:
            return None
        if (
            not force
            and trace_id is None
            and self.sample < 1.0
            and self._rng.random() >= self.sample
        ):
            return None
        return TraceContext(
            new_trace_id() if not trace_id else int(trace_id) & _M64,
            lane,
            self,
            self.node if node is None else node,
        )

    def event(
        self,
        lane: str,
        name: str,
        t0: float,
        dur: float,
        cat: str = "serving",
        trace_id: int = 0,
        node: str | None = None,
        **args,
    ) -> None:
        """Node-scope span (ring replication lag, eviction sweeps, route
        decisions). Same one-branch guard. A nonzero ``trace_id`` ties
        the span to an (upstream-originated) request trace AND skips the
        sampling coin flip — the sender already decided this request is
        traced, and a receiver flipping its own coin would shear
        cross-node timelines apart at fractional sampling rates."""
        if self.sample <= 0.0:
            return
        if (
            not trace_id
            and self.sample < 1.0
            and self._rng.random() >= self.sample
        ):
            return
        self._record(
            Span(
                name, lane, t0, max(0.0, dur), int(trace_id) & _M64, cat,
                args or None, self.node if node is None else node,
            )
        )

    # -- storage -------------------------------------------------------

    def _record(self, span: Span) -> None:
        evicted: Span | None = None
        with self._lock:
            if len(self._buf) == self.capacity:
                # Peek the victim BEFORE deque(maxlen) evicts it: the
                # drop must be attributed (metric + holed-trace mark),
                # not just counted.
                evicted = self._buf[0]
                self.dropped += 1
                if evicted.trace_id:
                    lst = self._by_tid.get(evicted.trace_id)
                    if lst is not None:
                        # Global FIFO order implies per-trace FIFO order:
                        # the victim is the oldest span of its trace.
                        if lst and lst[0] is evicted:
                            lst.pop(0)
                        if not lst:
                            del self._by_tid[evicted.trace_id]
                    if len(self._dropped_tids) < self.DROPPED_TRACE_CAP:
                        self._dropped_tids.add(evicted.trace_id)
                    elif evicted.trace_id not in self._dropped_tids:
                        self.drops_untracked = True
            self._buf.append(span)
            self.recorded += 1
            if span.trace_id:
                self._by_tid.setdefault(span.trace_id, []).append(span)
        if evicted is not None:
            dropped_spans_counter().labels(
                node=evicted.node or self.node or "node"
            ).inc()
        hook = self.retire_hook
        if hook is not None and span.name in self.retire_spans:
            hook(span, self)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self._buf)

    def drain(self) -> list[Span]:
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
            self._by_tid.clear()
            return out

    def spans_for_trace(self, trace_id: int) -> list[Span]:
        """Every buffered span recorded under ``trace_id`` (insertion
        order) — the attributor's per-request input, O(trace spans)."""
        with self._lock:
            return list(self._by_tid.get(int(trace_id) & _M64, ()))

    def trace_has_drops(self, trace_id: int) -> bool:
        """True when ``trace_id`` lost at least one span to the ring
        bound (or the holed-trace set itself overflowed, in which case
        EVERY trace answers True — coverage can no longer be proven).
        The attributor's refusal predicate: a waterfall computed from a
        holed trace would silently misattribute the missing intervals
        to the residual phase."""
        with self._lock:
            return (
                self.drops_untracked
                or (int(trace_id) & _M64) in self._dropped_tids
            )

    # -- export --------------------------------------------------------

    def chrome_trace(self, spans: list[Span] | None = None, drain: bool = False) -> dict:
        """Chrome trace-event JSON (the ``traceEvents`` array format) —
        load in Perfetto (ui.perfetto.dev) or ``chrome://tracing``.

        Lanes become threads of one process, named via ``thread_name``
        metadata events; complete-event (``ph: "X"``) timestamps are
        microseconds from the earliest span, emitted non-decreasing
        within each lane."""
        if spans is None:
            spans = self.drain() if drain else self.snapshot()
        base = min((s.t0 for s in spans), default=0.0)
        lanes: dict[str, int] = {}
        events: list[dict] = []
        # Sort by (lane, t0): within-lane ts monotonicity is part of the
        # artifact contract (bench.validate_trace checks it).
        for s in sorted(spans, key=lambda s: (s.lane, s.t0)):
            tid = lanes.setdefault(s.lane, len(lanes) + 1)
            ev = {
                "name": s.name,
                "cat": s.cat,
                "ph": "X",
                "ts": round((s.t0 - base) * 1e6, 3),
                "dur": round(s.dur * 1e6, 3),
                "pid": 1,
                "tid": tid,
            }
            args = dict(s.args or {})
            if s.trace_id:
                # Hex string: 64-bit ids exceed the 2^53 integer range a
                # JS-based viewer (Perfetto) reads losslessly.
                args["trace_id"] = f"{s.trace_id:#018x}"
            if s.node:
                args["node"] = s.node
            if args:
                ev["args"] = args
            events.append(ev)
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": lane},
            }
            for lane, tid in lanes.items()
        ]
        return {
            "displayTimeUnit": "ms",
            "traceEvents": meta + events,
            "otherData": {
                "recorder": {
                    "capacity": self.capacity,
                    "sample": self.sample,
                    "recorded": self.recorded,
                    "dropped": self.dropped,
                },
            },
        }

    def export_spans(self, drain: bool = False) -> dict:
        """Raw-span export for the cross-node stitcher: the recorder's
        spans as plain dicts plus this process's node label and
        monotonic→wall offset (``GET /debug/trace?format=spans`` serves
        exactly this body; a collector pulls one per node and hands the
        set to :meth:`merge`)."""
        spans = self.drain() if drain else self.snapshot()
        return {
            "node": self.node,
            "wall_offset": self.wall_offset,
            # Coverage declaration: spans this recorder evicted before
            # the export. A collector stitching multiple nodes folds
            # these into the artifact's per-node dropped map — no
            # silent caps.
            "dropped": self.dropped,
            "spans": [
                {
                    "name": s.name,
                    "lane": s.lane,
                    "t0": s.t0,
                    "dur": s.dur,
                    "trace_id": f"{s.trace_id:#018x}" if s.trace_id else "",
                    "cat": s.cat,
                    "args": s.args or {},
                    "node": s.node or self.node,
                }
                for s in spans
            ],
        }

    @staticmethod
    def merge(
        exports: list[dict], clock_offsets: dict[str, float] | None = None
    ) -> dict:
        """Stitch many nodes' span exports into ONE Perfetto document:
        one process-track (pid) per node, one thread per (node, lane),
        every timestamp shifted into a shared wall-clock base.

        Per-export correction: ``t_wall = t0 + wall_offset`` (the
        export's own monotonic→wall conversion). Per-NODE correction:
        ``clock_offsets[node]`` seconds are subtracted — the caller's
        estimate of that node's wall-clock skew vs the collector, e.g.
        ``FleetView.clock_offsets()`` derived from the digest timestamps
        every node already gossips. Skew bends telemetry, never
        correctness — exactly the oplog-lag contract.

        In-process multi-node harnesses produce ONE export whose spans
        carry distinct ``node`` labels; the grouping below handles both
        shapes identically."""
        offsets = clock_offsets or {}
        rows: list[tuple[str, str, float, dict]] = []
        dropped_by_node: dict[str, int] = {}
        for ex in exports:
            base_node = ex.get("node") or "node"
            wall = float(ex.get("wall_offset", 0.0))
            if ex.get("dropped"):
                dropped_by_node[base_node] = (
                    dropped_by_node.get(base_node, 0) + int(ex["dropped"])
                )
            for s in ex.get("spans", ()):
                node = s.get("node") or base_node
                t_wall = (
                    float(s["t0"]) + wall - float(offsets.get(node, 0.0))
                )
                rows.append((node, s.get("lane", "lane"), t_wall, s))
        base = min((t for _, _, t, _ in rows), default=0.0)
        pids: dict[str, int] = {}
        tids: dict[tuple[str, str], int] = {}
        events: list[dict] = []
        for node, lane, t_wall, s in sorted(
            rows, key=lambda r: (r[0], r[1], r[2])
        ):
            pid = pids.setdefault(node, len(pids) + 1)
            tid = tids.setdefault((node, lane), len(tids) + 1)
            ev = {
                "name": s.get("name", "span"),
                "cat": s.get("cat", "serving"),
                "ph": "X",
                "ts": round((t_wall - base) * 1e6, 3),
                "dur": round(float(s.get("dur", 0.0)) * 1e6, 3),
                "pid": pid,
                "tid": tid,
            }
            args = dict(s.get("args") or {})
            if s.get("trace_id"):
                args["trace_id"] = s["trace_id"]
            if args:
                ev["args"] = args
            events.append(ev)
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": node},
            }
            for node, pid in pids.items()
        ] + [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pids[node],
                "tid": tid,
                "args": {"name": lane},
            }
            for (node, lane), tid in tids.items()
        ]
        return {
            "displayTimeUnit": "ms",
            "traceEvents": meta + events,
            "otherData": {
                "stitched": True,
                "nodes": sorted(pids),
                "clock_offsets": {k: round(v, 6) for k, v in offsets.items()},
                # Coverage: spans each contributing node evicted before
                # exporting. A reader of the stitched doc knows exactly
                # which nodes' timelines may have holes.
                "dropped": dropped_by_node,
                "dropped_total": sum(dropped_by_node.values()),
            },
        }

    def stats(self) -> dict:
        """Programmatic recorder state for ``/debug/state``."""
        with self._lock:
            buffered = len(self._buf)
            holed = len(self._dropped_tids)
            drops_untracked = self.drops_untracked
        return {
            "capacity": self.capacity,
            "sample": self.sample,
            "enabled": self.enabled,
            "buffered_spans": buffered,
            "recorded_spans": self.recorded,
            "dropped_spans": self.dropped,
            # Traces that lost spans to the ring bound: the attributor
            # refuses waterfalls for these (obs/attribution.py).
            "holed_traces": holed,
            "drops_untracked": drops_untracked,
        }


_default = FlightRecorder()
_default_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """Process-wide default recorder (disabled until configured)."""
    return _default


def set_recorder(rec: FlightRecorder) -> FlightRecorder:
    """Swap the process-wide default (tests use this for isolation)."""
    global _default
    with _default_lock:
        _default = rec
    return rec


def configure(
    capacity: int = 8192, sample: float = 1.0, node: str = ""
) -> FlightRecorder:
    """Enable tracing process-wide: install a fresh recorder with the
    given bound + sampling rate (``launch.py --trace-capacity/-sample``).
    ``node`` labels this process's spans for the cross-node stitcher."""
    # Materialize the trace-loss series at 0 from process start
    # (dashboards never see gaps — the eviction_counters convention).
    dropped_spans_counter().labels(node=node or "node")
    return set_recorder(
        FlightRecorder(capacity=capacity, sample=sample, node=node)
    )


def stitch_traces(
    exports: list[dict], clock_offsets: dict[str, float] | None = None
) -> dict:
    """Module-level alias of :meth:`FlightRecorder.merge` (collectors
    import the function without touching a recorder instance)."""
    return FlightRecorder.merge(exports, clock_offsets)


def write_trace(path: str, drain: bool = True) -> int:
    """Dump the default recorder as a Chrome trace-event artifact.
    Returns the number of spans written."""
    rec = get_recorder()
    spans = rec.drain() if drain else rec.snapshot()
    obj = rec.chrome_trace(spans=spans)
    with open(path, "w") as fh:
        json.dump(obj, fh)
    return len(spans)
