"""Token-level speed observability: where each decoded token's time went.

Every prior plane stops above the token: PR 12's waterfall is
per-request phases, PR 9's step accounting is per-launch. This module
is the layer below — three ledgers the engine feeds from its one
per-emitted-token funnel (``Engine._consume_token``):

- :class:`TokenTimeline` — a bounded, change-compressed ring of
  inter-token latencies (ITL). One append per emitted token, drop-oldest
  overwrite, and the same one-branch-when-off discipline as the flight
  recorder: the engine tests ``timeline is not None`` once per token and
  pays nothing when disabled. Gaps past ``stall_threshold_s`` become
  stall events attributed to a named cause (the taxonomy in
  :data:`STALL_CAUSES`). Served on ``GET /debug/tokens``.
- :class:`SpecLedger` — per-(tenant, request shape, draft source)
  speculation acceptance: proposed/accepted/rejected totals, a per-wave
  acceptance EWMA, and the γ actually used. Exported as
  ``radixmesh_spec_*`` families so it rides the telemetry history ring
  and the fleet aggregator unchanged. Also hosts the acceptance-adaptive
  γ controller (off by default; ``Engine(spec_adaptive=True)`` /
  ``--spec-adaptive``): per (tenant, shape) class, shrink γ by one when
  the acceptance EWMA sits below ``accept_floor``, grow by one when it
  clears ``accept_ceil``, always clamped to [1, base γ]. The SLO
  degradation ladder keeps priority: tier ≥ 1 zeroes the engine's base
  γ, which gates drafting off entirely — the controller never fights it,
  and :meth:`SpecLedger.note_tier` records the tier so the doctor's
  ``spec_misconfigured`` rule can tell "off by SLO" from "mistuned".
- :class:`GoodputLedger` — useful-output tokens per device-second per
  tenant, decomposed into padding waste (from step accounting),
  rejected-draft waste (from the spec ledger), and stall time (from the
  timeline): the ledger that says where the non-MFU fraction goes.

Hot-path contract (checked by the hot-path lint): the token-append path
takes no locks of its own and allocates nothing beyond the ring slot —
the ring is a preallocated list written only by the scheduler thread;
readers snapshot without locks (the same wedged-engine rationale as
``Engine.telemetry``).
"""

from __future__ import annotations

import time

from radixmesh_tpu.obs.metrics import get_registry

__all__ = [
    "TokenTimeline",
    "SpecLedger",
    "GoodputLedger",
    "STALL_CAUSES",
    "DRAFT_SOURCES",
    "ITL_SECONDS_BUCKETS",
]

# The stall-cause taxonomy, in attribution-priority order. A gap only
# ever gets ONE cause; the engine resolves it at emit time from what it
# knows was in flight during the gap (``Engine._stall_cause``):
#
# - ``restore_park``     — a KV-plane restore was in flight (requests
#                          parked in RESTORING while decode waited).
# - ``prefill_convoy``   — a WHOLE prefill wave ran inside the gap (the
#                          wide-shape TTFT collapse, seen from the token
#                          side).
# - ``prefill_inline``   — a budget-bounded inline prefill chunk rode the
#                          decode wave inside the gap (mixed compute
#                          waves, ``--prefill-inline-budget``). Distinct
#                          from the convoy on purpose: inline chunks are
#                          the MITIGATION, bounded by the budget, and a
#                          gap they stretch must not read as either a
#                          convoy regression or an unexplained
#                          ``scheduler_wait``.
# - ``rebalance_handoff``— an ownership move was draining this node
#                          (external planes latch it via
#                          ``Engine.hint_stall``).
# - ``spec_verify_miss`` — the previous speculative wave rejected this
#                          row's drafts, so the gap re-decoded them.
# - ``scheduler_wait``   — none of the above: the scheduler simply did
#                          not run this row (queueing, host work, GC).
STALL_CAUSES = (
    "restore_park",
    "prefill_convoy",
    "prefill_inline",
    "rebalance_handoff",
    "spec_verify_miss",
    "scheduler_wait",
)

# Where a draft came from: the radix tree's published continuation
# (replay hits), prompt n-gram lookup, or nothing (empty draft — the
# row rode the verify launch as a plain step).
DRAFT_SOURCES = ("tree", "ngram", "none")

# ITL distribution buckets: decode steps are sub-ms to tens of ms on
# real hardware; DEFAULT_BUCKETS' 1 ms floor would flatten the healthy
# band to zeros, and the tail must still resolve multi-second stalls.
ITL_SECONDS_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

# Change-compression tolerance: a token whose ITL is within this
# relative band of its request's previous ring entry (same cause) bumps
# that entry's repeat count instead of writing a new slot — steady-state
# decode (thousands of near-identical gaps) compresses to one slot per
# plateau, so the ring's wall coverage is workload-adaptive.
_REL_TOL = 0.25


class TokenTimeline:
    """Bounded per-token ITL ring + stall-cause accounting.

    Writer: the engine scheduler thread only (one ``note_token`` per
    emitted token). Readers (``/debug/tokens``, the doctor) snapshot
    lock-free — worst case they see a slot mid-overwrite, which the
    rid-stamp check discards.
    """

    def __init__(
        self,
        capacity: int = 4096,
        stall_threshold_s: float = 0.05,
        node: str = "",
    ):
        if capacity <= 0:
            raise ValueError("timeline capacity must be positive")
        self.capacity = int(capacity)
        self.stall_threshold_s = float(stall_threshold_s)
        self.node = node
        # Ring slots are [rid, tenant, t_mono, itl_s, repeats, cause].
        self._ring: list = [None] * self.capacity
        self._head = 0  # next slot to write
        self.appends = 0  # note_token calls (uncompressed token count)
        self.points = 0  # ring slots actually written
        # rid -> ring index of that request's latest entry (for repeat
        # compression); bounded by periodic clear, validated by rid
        # stamp before use so stale mappings can't corrupt a slot.
        self._last: dict[int, int] = {}
        # cause -> count / seconds, all-time (the ring forgets, the
        # histogram must not — the doctor reads deltas off the history
        # ring's copy of the counter families).
        self.stall_counts: dict[str, int] = dict.fromkeys(STALL_CAUSES, 0)
        self.stall_seconds: dict[str, float] = dict.fromkeys(
            STALL_CAUSES, 0.0
        )

        reg = get_registry()
        # Fleet-mergeable per-tenant ITL distribution (bucket counts ride
        # the history ring via BUCKET_FAMILIES, exemplars carry trace
        # ids — the PR 17 percentile pipeline, one level down).
        self._m_itl = reg.histogram(
            "radixmesh_token_itl_seconds",
            "inter-token latency per tenant (fleet-mergeable buckets; "
            "exemplars carry trace ids)",
            ("tenant",),
            buckets=ITL_SECONDS_BUCKETS,
        )
        self._m_stalls = reg.counter(
            "radixmesh_token_stalls_total",
            "decode gaps past the stall threshold, by attributed cause",
            ("cause",),
        )
        self._m_stall_children = {
            c: self._m_stalls.labels(cause=c) for c in STALL_CAUSES
        }
        self._itl_children: dict[str, object] = {}

    # -- write path (scheduler thread) ---------------------------------

    def note_token(
        self,
        rid: int,
        tenant: str,
        itl_s: float,
        cause: str | None = None,
        trace_id: int | None = None,
        now: float | None = None,
    ) -> None:
        """Account one emitted token's inter-token gap. ``cause`` is the
        engine's stall attribution (None below threshold)."""
        self.appends += 1
        child = self._itl_children.get(tenant)
        if child is None:
            child = self._m_itl.labels(tenant=tenant)
            self._itl_children[tenant] = child
        child.observe(itl_s, trace_id=trace_id)
        if cause is not None:
            self.stall_counts[cause] += 1
            self.stall_seconds[cause] += itl_s
            self._m_stall_children[cause].inc()
        # Repeat-compress against this request's previous entry: same
        # cause bucket and ITL within the relative band.
        idx = self._last.get(rid)
        if idx is not None:
            slot = self._ring[idx]
            if (
                slot is not None
                and slot[0] == rid
                and slot[5] == cause
                and abs(itl_s - slot[3]) <= _REL_TOL * max(slot[3], 1e-9)
            ):
                slot[4] += 1
                return
        if len(self._last) > 4 * self.capacity:
            # Bounded bookkeeping: stale rids accumulate across request
            # lifetimes; a rare clear only costs one lost compression
            # opportunity per live request.
            self._last.clear()
        t = time.monotonic() if now is None else now
        idx = self._head
        self._ring[idx] = [rid, tenant, t, itl_s, 1, cause]
        self._head = (idx + 1) % self.capacity
        self.points += 1
        self._last[rid] = idx

    # -- read path (any thread, lock-free) -----------------------------

    def snapshot(self, limit: int = 256) -> dict:
        """Point-in-time view for ``/debug/tokens``: ring stats, the
        stall-cause histogram, per-tenant ITL percentiles, and the most
        recent ``limit`` (change-compressed) entries, oldest first."""
        n = min(limit, self.capacity)
        head = self._head
        entries = []
        for off in range(self.capacity):
            slot = self._ring[(head + off) % self.capacity]
            if slot is None:
                continue
            entries.append(slot)
        entries = entries[-n:]
        quantiles = {}
        for tenant, child in list(self._itl_children.items()):
            try:
                quantiles[tenant] = {
                    "count": int(child.count),
                    "p50_s": child.quantile(0.5),
                    "p99_s": child.quantile(0.99),
                }
            except Exception:  # noqa: BLE001 — snapshot must not throw
                continue
        return {
            "capacity": self.capacity,
            "stall_threshold_s": self.stall_threshold_s,
            "appends": self.appends,
            "points": self.points,
            "compressed": self.appends - self.points,
            "dropped": max(0, self.points - self.capacity),
            "stalls": {
                c: n for c, n in self.stall_counts.items() if n
            },
            "stall_seconds": {
                c: round(s, 6)
                for c, s in self.stall_seconds.items()
                if s
            },
            "itl": quantiles,
            "recent": [
                {
                    "rid": e[0],
                    "tenant": e[1],
                    "t": e[2],
                    "itl_s": e[3],
                    "repeats": e[4],
                    "cause": e[5],
                }
                for e in entries
            ],
        }


class _SpecClass:
    """One (tenant, shape, source) acceptance cell."""

    __slots__ = (
        "proposed", "accepted", "rejected", "waves", "ewma",
        "gamma_used", "last_wave",
    )

    def __init__(self):
        self.proposed = 0
        self.accepted = 0
        self.rejected = 0
        self.waves = 0
        self.ewma: float | None = None  # cold until the first wave
        self.gamma_used = 0
        self.last_wave = 0


class SpecLedger:
    """Per-class speculation acceptance + the adaptive-γ controller.

    Written by the scheduler thread (one ``note_wave`` per row per
    verify launch); read lock-free. Classes are bounded: past
    ``max_classes`` the least-recently-waved cell is evicted (its
    registry counters keep their totals — only the EWMA state goes)."""

    def __init__(
        self,
        alpha: float = 0.25,
        max_classes: int = 128,
        adaptive: bool = False,
        accept_floor: float = 0.5,
        accept_ceil: float = 0.8,
        node: str = "",
    ):
        if not 0 < alpha <= 1:
            raise ValueError("alpha must be in (0, 1]")
        if not 0 <= accept_floor <= accept_ceil <= 1:
            raise ValueError("need 0 <= accept_floor <= accept_ceil <= 1")
        self.alpha = float(alpha)
        self.max_classes = int(max_classes)
        self.adaptive = bool(adaptive)
        self.accept_floor = float(accept_floor)
        self.accept_ceil = float(accept_ceil)
        self.node = node
        self._cells: dict[tuple[str, str, str], _SpecClass] = {}
        # (tenant, shape) -> current adaptive γ (absent = base).
        self._gamma: dict[tuple[str, str], int] = {}
        self._wave_seq = 0
        # Last SLO degradation tier seen (slo/runner.py notes it when it
        # applies a tier): tier >= 1 means speculation is OFF by policy,
        # and the spec_misconfigured doctor rule must stay silent.
        self.last_tier = 0

        reg = get_registry()
        labels = ("tenant", "shape", "source")
        self._m_proposed = reg.counter(
            "radixmesh_spec_proposed_tokens_total",
            "draft tokens offered to verification, by request class "
            "and draft source",
            labels,
        )
        self._m_accepted = reg.counter(
            "radixmesh_spec_accepted_tokens_total",
            "draft tokens accepted by verification, by request class "
            "and draft source",
            labels,
        )
        self._m_rejected = reg.counter(
            "radixmesh_spec_rejected_tokens_total",
            "draft tokens rejected by verification, by request class "
            "and draft source",
            labels,
        )
        self._m_ratio = reg.gauge(
            "radixmesh_spec_accept_ratio",
            "per-wave acceptance EWMA by request class and draft source",
            labels,
        )
        self._m_gamma = reg.gauge(
            "radixmesh_spec_gamma_used_tokens",
            "draft window actually used last wave, by request class "
            "and draft source",
            labels,
        )

    # -- write path (scheduler thread) ---------------------------------

    def note_wave(
        self,
        tenant: str,
        shape: str,
        source: str,
        proposed: int,
        accepted: int,
        gamma: int,
    ) -> None:
        """Account one row's verify outcome. ``gamma`` is the draft
        window actually used (≤ the engine's configured γ)."""
        if proposed <= 0:
            return
        rejected = proposed - accepted
        key = (tenant, shape, source)
        cell = self._cells.get(key)
        if cell is None:
            if len(self._cells) >= self.max_classes:
                self._evict_one()
            cell = self._cells[key] = _SpecClass()
        self._wave_seq += 1
        rate = accepted / proposed
        cell.proposed += proposed
        cell.accepted += accepted
        cell.rejected += rejected
        cell.waves += 1
        cell.gamma_used = gamma
        cell.last_wave = self._wave_seq
        # Cold start: the first wave seeds the EWMA directly instead of
        # decaying from an arbitrary prior.
        if cell.ewma is None:
            cell.ewma = rate
        else:
            cell.ewma += self.alpha * (rate - cell.ewma)
        lbl = {"tenant": tenant, "shape": shape, "source": source}
        self._m_proposed.labels(**lbl).inc(proposed)
        self._m_accepted.labels(**lbl).inc(accepted)
        if rejected:
            self._m_rejected.labels(**lbl).inc(rejected)
        self._m_ratio.labels(**lbl).set(cell.ewma)
        self._m_gamma.labels(**lbl).set(gamma)
        if self.adaptive:
            self._steer(tenant, shape, cell.ewma, gamma)

    def _steer(
        self, tenant: str, shape: str, ewma: float, gamma: int
    ) -> None:
        """The control law: one γ step per wave, toward acceptance."""
        key = (tenant, shape)
        g = self._gamma.get(key, gamma)
        if ewma < self.accept_floor:
            g = max(1, g - 1)
        elif ewma > self.accept_ceil:
            g = g + 1  # clamped to base at gamma_for()
        self._gamma[key] = g

    def _evict_one(self) -> None:
        oldest = min(self._cells, key=lambda k: self._cells[k].last_wave)
        del self._cells[oldest]

    def gamma_for(self, tenant: str, shape: str, base: int) -> int:
        """The γ the engine should draft with for this class: ``base``
        when the controller is off (or has no signal yet), else the
        steered value clamped to [1, base]. ``base`` ≤ 0 (speculation
        off — including by SLO tier) always wins."""
        if base <= 0 or not self.adaptive:
            return base
        g = self._gamma.get((tenant, shape))
        if g is None:
            return base
        return max(1, min(base, g))

    def note_tier(self, tier: int) -> None:
        """SLO runner seam: records the degradation tier in force."""
        self.last_tier = int(tier)

    # -- read path -----------------------------------------------------

    def report(self) -> dict:
        """Per-class acceptance snapshot (the ``/debug/tokens`` and
        doctor view). List-snapshot before iterating: the scheduler
        grows the dict concurrently."""
        cells = list(self._cells.items())
        out = {}
        for (tenant, shape, source), c in sorted(cells):
            out[f"{tenant}/{shape}/{source}"] = {
                "tenant": tenant,
                "shape": shape,
                "source": source,
                "proposed": c.proposed,
                "accepted": c.accepted,
                "rejected": c.rejected,
                "waves": c.waves,
                "accept_ewma": (
                    None if c.ewma is None else round(c.ewma, 4)
                ),
                "gamma_used": c.gamma_used,
            }
        return out

    def totals(self) -> dict:
        cells = list(self._cells.values())
        p = sum(c.proposed for c in cells)
        a = sum(c.accepted for c in cells)
        r = sum(c.rejected for c in cells)
        return {"proposed": p, "accepted": a, "rejected": r}


class GoodputLedger:
    """Useful-output tokens per device-second per tenant, with the waste
    decomposition. Fed per token by the engine (same branch as the
    timeline); ``report()`` refreshes the registry gauges, so every
    caller that reads it (``/debug/tokens``, the doctor, the history
    sampler's derived fold) also keeps the scrape plane fresh."""

    def __init__(self, node: str = "", now=time.monotonic):
        self.node = node
        self._now = now
        self._t0 = now()
        # tenant -> [useful_tokens, stall_seconds]
        self._tenants: dict[str, list] = {}

        reg = get_registry()
        self._m_tps = reg.gauge(
            "radixmesh_goodput_tokens_per_second",
            "useful output tokens per wall second, per tenant",
            ("tenant",),
        )
        self._m_waste = reg.gauge(
            "radixmesh_goodput_waste_fraction",
            "waste share of decode capacity by kind "
            "(padding / rejected_draft / stall)",
            ("kind",),
        )

    # -- write path (scheduler thread) ---------------------------------

    def note_token(self, tenant: str) -> None:
        cell = self._tenants.get(tenant)
        if cell is None:
            cell = self._tenants[tenant] = [0, 0.0]
        cell[0] += 1

    def note_stall(self, tenant: str, stall_s: float) -> None:
        cell = self._tenants.get(tenant)
        if cell is None:
            cell = self._tenants[tenant] = [0, 0.0]
        cell[1] += stall_s

    # -- read path -----------------------------------------------------

    def report(self, step_acct=None, spec: SpecLedger | None = None) -> dict:
        """The decomposition: per-tenant goodput plus where the rest of
        the capacity went. ``step_acct`` contributes padding waste (its
        padded-vs-real token accounting), ``spec`` rejected-draft waste;
        stall time comes from this ledger's own per-tenant sums."""
        now = self._now()
        elapsed = max(now - self._t0, 1e-9)
        tenants = {}
        useful_total = 0
        stall_total = 0.0
        for tenant, (tokens, stall_s) in sorted(self._tenants.items()):
            tps = tokens / elapsed
            tenants[tenant] = {
                "useful_tokens": tokens,
                "tokens_per_second": round(tps, 3),
                "stall_seconds": round(stall_s, 6),
            }
            useful_total += tokens
            stall_total += stall_s
            self._m_tps.labels(tenant=tenant).set(tps)
        padding = 0
        if step_acct is not None:
            try:
                rep = step_acct.report()
                for kind in ("prefill", "decode"):
                    k = rep.get(kind)
                    if isinstance(k, dict):
                        padding += int(
                            k.get("padded_tokens", 0)
                            - k.get("real_tokens", 0)
                        )
            except Exception:  # noqa: BLE001 — seam isolation
                pass
        rejected = 0
        if spec is not None:
            rejected = spec.totals()["rejected"]
        # Waste fractions against the total token positions the device
        # actually processed (useful + padding + rejected); stall is a
        # time share of the wall instead — stalled seconds process
        # nothing, so a token denominator would hide them.
        processed = max(useful_total + padding + rejected, 1)
        waste = {
            "padding": padding / processed,
            "rejected_draft": rejected / processed,
            "stall": min(stall_total / elapsed, 1.0),
        }
        for kind, frac in waste.items():
            self._m_waste.labels(kind=kind).set(frac)
        return {
            "elapsed_s": round(elapsed, 3),
            "useful_tokens": useful_total,
            "tokens_per_second": round(useful_total / elapsed, 3),
            "tenants": tenants,
            "waste": {k: round(v, 6) for k, v in waste.items()},
            "padding_tokens": padding,
            "rejected_draft_tokens": rejected,
            "stall_seconds": round(stall_total, 6),
        }
