"""TPU step attribution: per-wave token accounting, pad fraction, MFU.

BENCH_FULL_r04 says the TPU is ~2% utilized (mfu ≈ 0.021) and p99 TTFT
sits in the seconds — but nothing in the repo could say WHERE a prefill
or decode wave's wall-clock goes, or how much of each launch is padding.
This module is the measurement leg ROADMAP item 2 (tree-sourced
speculative decoding + chunked prefill) gates its before/after on:

- Every prefill sub-wave and decode launch reports ``(kind, real
  tokens, padded tokens, wall seconds)`` to a :class:`StepAccounting`
  instance owned by the engine.
- **Pad fraction** = 1 - real/padded: the share of the launch shape
  that was pow2/bucket padding — compute the MXU did that no request
  asked for.
- **MFU estimate** = achieved FLOP/s over the device's nominal peak,
  with achieved FLOPs from the standard matmul-dominant analytic model
  ``FLOPs/token ≈ 2 · n_params`` (one multiply-add per weight per
  token; attention's O(s·d) term and the embedding gather are inside
  the ~few-percent error band this estimate is honest to). Documented
  in ARCHITECTURE.md "Mesh-wide observability"; exact numbers need a
  profiler capture (``/debug/profile?seconds=N`` wraps
  ``jax.profiler`` for that).
- Emitted as ``radixmesh_step_mfu`` / ``radixmesh_wave_pad_fraction``
  gauges (labels: engine, kind) plus ``step_wave`` recorder spans on
  the ``step:<engine>`` lane, and aggregated into :meth:`report` for
  ``/debug/state`` and the OBS bench artifact.

Accounting is OFF by default (``Engine(step_accounting=True)`` /
``launch.py --step-accounting``): the wave hot paths keep the PR 2
one-branch-when-off contract — a single ``is not None`` test — which
``tests/test_trace_plane.py`` re-proves at these call sites.

Import-light (stdlib only at module scope): the peak-FLOPs lookup
imports jax lazily and degrades to a nominal figure off-accelerator.
"""

from __future__ import annotations

import time

from radixmesh_tpu.obs.metrics import get_registry
from radixmesh_tpu.obs.trace_plane import get_recorder

__all__ = [
    "PEAK_TFLOPS_BY_DEVICE",
    "DEFAULT_PEAK_TFLOPS",
    "detect_peak_tflops",
    "analytic_flops_per_token",
    "StepAccounting",
]

# Nominal dense bf16 matmul peak by accelerator generation (TFLOP/s per
# chip, vendor-published). MFU is an ESTIMATE: the point is trend lines
# (before/after a scheduling change on the same hardware), not absolute
# truth — a wrong peak scales every reading by one constant.
PEAK_TFLOPS_BY_DEVICE = {
    "tpu v4": 275.0,
    "tpu v5 lite": 197.0,
    "tpu v5e": 197.0,
    "tpu v5p": 459.0,
    "tpu v6e": 918.0,
}
# Off-accelerator (CPU tests, interpret mode): a nominal 1 TFLOP/s so
# MFU stays finite and comparable across runs on the same host — the
# value is labeled an estimate everywhere it surfaces.
DEFAULT_PEAK_TFLOPS = 1.0


def detect_peak_tflops() -> float:
    """Peak TFLOP/s of the default jax device, by device-kind lookup;
    the nominal default when jax is absent or the kind is unknown."""
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — no backend = nominal figure
        return DEFAULT_PEAK_TFLOPS
    for name, tflops in PEAK_TFLOPS_BY_DEVICE.items():
        if name in kind:
            return tflops
    return DEFAULT_PEAK_TFLOPS


def analytic_flops_per_token(n_params: int) -> float:
    """Forward-pass FLOPs per processed token, matmul-dominant model:
    2 FLOPs (multiply + add) per parameter per token."""
    return 2.0 * float(n_params)


class StepAccounting:
    """Per-engine wave accounting: tokens, padding, achieved-vs-peak.

    One instance per engine, driven from the single scheduler thread —
    no locking of its own (the metric gauges carry their own locks).
    """

    KINDS = ("prefill", "decode")

    def __init__(
        self,
        engine: str,
        n_params: int,
        peak_tflops: float | None = None,
    ):
        self.engine = engine
        self.n_params = int(n_params)
        self.flops_per_token = analytic_flops_per_token(n_params)
        self.peak_flops = (
            peak_tflops if peak_tflops is not None else detect_peak_tflops()
        ) * 1e12
        self._trace_lane = f"step:{engine}"
        self._agg: dict[str, dict[str, float]] = {
            k: {
                "waves": 0,
                "real_tokens": 0,
                "padded_tokens": 0,
                "busy_s": 0.0,
                "mfu_last": 0.0,
                "pad_fraction_last": 0.0,
            }
            for k in self.KINDS
        }
        reg = get_registry()
        mfu = reg.gauge(
            "radixmesh_step_mfu",
            "per-wave model FLOPs utilization estimate (analytic "
            "2*n_params FLOPs/token over the device's nominal peak)",
            ("engine", "kind"),
        )
        pad = reg.gauge(
            "radixmesh_wave_pad_fraction",
            "share of the last wave's launch shape that was padding "
            "(1 - real/padded tokens)",
            ("engine", "kind"),
        )
        waves = reg.counter(
            "radixmesh_step_waves_total",
            "prefill/decode device waves accounted",
            ("engine", "kind"),
        )
        # Eager children: the series exist at 0 from engine start.
        self._g_mfu = {k: mfu.labels(engine=engine, kind=k) for k in self.KINDS}
        self._g_pad = {k: pad.labels(engine=engine, kind=k) for k in self.KINDS}
        self._m_waves = {
            k: waves.labels(engine=engine, kind=k) for k in self.KINDS
        }

    def note_wave(
        self,
        kind: str,
        real_tokens: int,
        padded_tokens: int,
        dt_s: float,
        rows: int = 0,
    ) -> float:
        """Account one device wave; returns its MFU estimate. The MFU
        numerator counts REAL tokens only — padding is wasted peak, so
        it shows up as low MFU plus a high pad fraction, which is
        exactly the pair of signals a scheduling fix must move in
        opposite directions."""
        if kind not in self._agg:
            raise ValueError(f"unknown wave kind {kind!r}")
        real = max(0, int(real_tokens))
        padded = max(real, int(padded_tokens))
        dt = max(1e-9, float(dt_s))
        mfu = (self.flops_per_token * real) / (self.peak_flops * dt)
        pad_fraction = 1.0 - (real / padded) if padded else 0.0
        a = self._agg[kind]
        a["waves"] += 1
        a["real_tokens"] += real
        a["padded_tokens"] += padded
        a["busy_s"] += dt
        a["mfu_last"] = mfu
        a["pad_fraction_last"] = pad_fraction
        self._g_mfu[kind].set(mfu)
        self._g_pad[kind].set(pad_fraction)
        self._m_waves[kind].inc()
        rec = get_recorder()
        if rec.enabled:
            rec.event(
                self._trace_lane,
                "step_wave",
                time.monotonic() - dt,
                dt,
                cat="step",
                kind=kind,
                real_tokens=real,
                padded_tokens=padded,
                rows=int(rows),
                mfu=round(mfu, 6),
                pad_fraction=round(pad_fraction, 4),
            )
        return mfu

    def report(self) -> dict:
        """Aggregates for /debug/state and the OBS artifact. ``mfu`` is
        the busy-time-weighted mean (total real FLOPs over total busy
        peak-FLOP capacity), not a mean of per-wave ratios."""
        out: dict = {
            "n_params": self.n_params,
            "flops_per_token": self.flops_per_token,
            "peak_tflops": round(self.peak_flops / 1e12, 3),
        }
        for kind, a in self._agg.items():
            busy = a["busy_s"]
            mfu = (
                (self.flops_per_token * a["real_tokens"])
                / (self.peak_flops * busy)
                if busy > 0
                else 0.0
            )
            pad = (
                1.0 - a["real_tokens"] / a["padded_tokens"]
                if a["padded_tokens"]
                else 0.0
            )
            out[kind] = {
                "waves": int(a["waves"]),
                "real_tokens": int(a["real_tokens"]),
                "padded_tokens": int(a["padded_tokens"]),
                "busy_s": round(busy, 6),
                "mfu": mfu,
                "pad_fraction": round(pad, 6),
                "mfu_last": a["mfu_last"],
                "pad_fraction_last": round(a["pad_fraction_last"], 6),
            }
        return out
