"""The mesh doctor: a rule engine over the observability planes.

Every diagnosis since PR 9 was a human reading raw artifacts — the
prefill convoy (BENCH_FULL_r05's 1 756 ms wide-shape p50 TTFT), the
hot-shard skew (16.3, OBS_r09), the paged small-batch gap — all found
by eyeball. This module closes the loop: it CONSUMES the substrate
(FleetView health + shard heat, the phase attributor's per-shape
waterfall aggregates, step accounting, the SLO token-bucket plane,
engine spec counters) and emits **ranked findings, each carrying its
evidence** — metric values, shard ids, owner sets — in a file-format
the DOCTOR artifact schema pins (``bench.validate_doctor``), so "what
is wrong with the mesh" is a GET, not an afternoon.

Rules (each fires at most one finding; evidence fields are part of the
schema contract — see :data:`RULE_EVIDENCE_FIELDS`):

- ``hot_shard`` — fleet skew score over threshold: names the hot shard
  AND its owner set (the item-2 rebalancer's trigger input).
- ``prefill_convoy`` — one request shape's exclusive prefill-phase
  share of e2e over threshold while slower than the rest of the
  traffic: names the convoying shape (the BENCH_FULL_r05 pathology,
  now machine-detected).
- ``restore_park_stall`` — requests parked in RESTORING behind a slow
  restore lane (live parked count + queued restores, or the
  restore_park phase share): names the throttled lane.
- ``replication_lag`` — gossiped per-node oplog origin→apply lag over
  threshold: names the lagging ranks.
- ``slo_burn_rate`` — multi-window (5 m AND 1 h) error-budget burn per
  tenant over the token-bucket plane (the classic SRE pager rule:
  both windows hot ⇒ neither a blip nor stale news).
- ``spec_efficiency`` — per-shape speculative acceptance under the
  floor with enough proposals to matter: names the shape whose drafts
  miss (the item-1(a) adaptive-γ substrate).

A healthy mesh yields ZERO findings — the acceptance workload
(``workload.run_doctor_workload``) gates on that as hard as it gates on
the seeded pathologies being named.

Import-light on purpose (stdlib only): both frontends, the router, and
``scripts/doctor.py`` construct doctors without a backend; every input
is an optional duck-typed seam.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from dataclasses import dataclass, field

__all__ = [
    "DoctorConfig",
    "Finding",
    "BurnRateTracker",
    "MeshDoctor",
    "RULES",
    "RULE_EVIDENCE_FIELDS",
    "POSTMORTEM_RULES",
    "POSTMORTEM_EVIDENCE_FIELDS",
    "postmortem_report",
]

# Rule ids in severity-tiebreak order (ranking is by score first; this
# order breaks exact ties deterministically).
RULES = (
    "hot_shard",
    "prefill_convoy",
    "restore_park_stall",
    "replication_lag",
    "slo_burn_rate",
    "spec_efficiency",
    "rebalancer_asleep",
    "tier_thrash",
    # Fleet rules (PR 17): judged over the FleetAggregator's cross-node
    # store — no single node's seams can run them.
    "straggler_node",
    "fleet_burn_slope",
    "telemetry_gap",
    # Token-plane rules (PR 18): judged over the engine's per-token
    # timeline / speculation ledger and the history ring's goodput
    # series.
    "decode_stall",
    "spec_misconfigured",
    "goodput_regression",
)

# The pinned evidence vocabulary per rule: every finding MUST carry at
# least these keys (bench.validate_doctor checks artifacts against this
# map; tests/test_doctor.py checks live findings against it). Evidence
# without a contract rots into prose.
RULE_EVIDENCE_FIELDS = {
    "hot_shard": ("skew_score", "shard", "owners", "reporters"),
    "prefill_convoy": (
        "shape", "prefill_share", "mean_e2e_s", "fleet_mean_e2e_s",
        "requests",
    ),
    "restore_park_stall": (
        "lane", "parked", "restores_queued", "park_p99_s", "park_share",
    ),
    "replication_lag": ("ranks", "threshold_s", "worst_lag_s"),
    "slo_burn_rate": (
        "tenant", "burn_fast", "burn_slow", "fast_window_s",
        "slow_window_s", "budget", "tier",
    ),
    "spec_efficiency": ("shape", "proposed", "accepted", "acceptance"),
    "rebalancer_asleep": (
        "skew_peak", "sustained_s", "window_s", "moves_in_window",
        "hot_shard", "plane_armed",
    ),
    "tier_thrash": (
        "shard", "demotes", "promotes", "cycles", "window_s", "source",
    ),
    "straggler_node": (
        "rank", "signal", "value_s", "fleet_median_s", "ratio", "ranks",
    ),
    "fleet_burn_slope": (
        "tenant", "burn_fast", "burn_slow", "slope_per_s", "budget",
        "offered",
    ),
    "telemetry_gap": (
        "peer", "rank", "stalled_s", "peer_seq", "verdict",
    ),
    "decode_stall": (
        "cause", "stalls", "stall_seconds", "p99_itl_s", "threshold_s",
    ),
    "spec_misconfigured": (
        "tenant", "shape", "source", "gamma", "accept_ewma", "proposed",
    ),
    "goodput_regression": (
        "recent_tps", "baseline_tps", "drop_frac", "window_s",
    ),
}


@dataclass
class DoctorConfig:
    """Rule thresholds. Defaults are tuned so steady healthy serving —
    balanced heat, sub-threshold lag, drafts landing — yields zero
    findings (the acceptance workload's healthy-phase gate)."""

    # hot_shard: fleet skew (max/mean over reported shards) above this
    # with at least min_reporters heat reporters.
    hot_shard_skew: float = 4.0
    hot_shard_min_reporters: int = 1
    # prefill_convoy: a shape's exclusive prefill share of its e2e, with
    # at least min_requests audited waterfalls of that shape, while its
    # mean e2e exceeds the other shapes' mean by slowdown×.
    convoy_prefill_share: float = 0.55
    convoy_min_requests: int = 3
    convoy_slowdown: float = 1.5
    # restore_park_stall: live parked requests + a queued restore lane,
    # OR the audited restore_park share of e2e across requests.
    park_min_parked: int = 2
    park_share: float = 0.25
    # replication_lag: gossiped per-node lag EWMA above this.
    lag_threshold_s: float = 1.0
    # slo_burn_rate: shed-fraction burn multiple over budget, both
    # windows (SRE multi-window multi-burn: fast window catches the
    # fire, slow window proves it is not a blip).
    burn_budget: float = 0.01  # tolerable shed fraction (99% availability)
    burn_fast_window_s: float = 300.0
    burn_slow_window_s: float = 3600.0
    burn_fast_threshold: float = 14.4
    burn_slow_threshold: float = 6.0
    burn_min_requests: int = 20
    # spec_efficiency: acceptance floor with enough proposals to judge.
    spec_accept_floor: float = 0.3
    spec_min_proposed: int = 50
    # rebalancer_asleep: the fleet skew stayed above hot_shard_skew for
    # at least sustain seconds inside the trailing window while the
    # rebalance plane adopted ZERO moves in that window — the telemetry
    # sees a storm the mesh is not acting on (a missing/off plane fires
    # the same rule: that is the pre-rebalancer pathology by name).
    rebalance_window_s: float = 120.0
    rebalance_sustain_s: float = 10.0
    # Persistence bound for SELF-SAMPLED skew points (no history ring):
    # a diagnose-time sample only proves the skew at that instant, so
    # its value persists at most this long toward "sustained" — sparse
    # polling must not smear two momentary spikes into a storm (the
    # same discipline BurnRateTracker's staleness bound applies).
    # History-fed trajectories are change-compressed (a gap means NO
    # CHANGE), so their persistence is exact and uncapped.
    rebalance_max_sample_gap_s: float = 30.0
    # tier_thrash: the durable KV tier (cache/kv_tier.py) demoting AND
    # promoting the same subtree shard >= min_cycles times each inside
    # one hysteresis window — the working set straddles the host
    # watermark and every crossing pays a disk round trip. Cycles =
    # min(demotes, promotes) within the window.
    tier_thrash_window_s: float = 60.0
    tier_thrash_min_cycles: int = 3
    # straggler_node: one rank's decode-step (or replication-lag) EWMA
    # at least ratio× the fleet median across >= min_ranks ACTIVE ranks
    # (zeros are ranks not running that plane, not fast ranks), above
    # an absolute floor so uniform microsecond noise never fires.
    straggler_ratio: float = 3.0
    straggler_min_ranks: int = 2
    straggler_floor_s: float = 0.005
    # fleet_burn_slope: aggregated (fleet-summed) multi-window burn.
    # Deliberately LOWER thresholds than the per-node page rule: this
    # is the pre-scale signal (ROADMAP item 2) — it should fire, with
    # its slope, before anyone's pager does.
    fleet_burn_fast_threshold: float = 6.0
    fleet_burn_slow_threshold: float = 3.0
    fleet_burn_min_requests: int = 20
    # telemetry_gap: floor on how long a peer's ring may sit still
    # before it counts as stalled (the aggregator's per-peer
    # cadence-scaled threshold also applies — whichever is larger).
    telemetry_gap_s: float = 5.0
    # decode_stall: minimum attributed stall events before the token
    # timeline's dominant cause is worth a finding (a handful of gaps
    # is jitter, not a pathology).
    decode_stall_min_events: int = 10
    # spec_misconfigured: a (tenant, shape, draft-source) class whose
    # acceptance EWMA sits under spec_accept_floor while γ stays wide —
    # judged only with enough proposals, and only when γ was NOT zeroed
    # by the SLO degradation ladder (that is policy, not mistuning).
    spec_misconfig_min_proposed: int = 50
    # goodput_regression: recent-window mean of the history ring's
    # goodput:tokens_per_second at least regress_frac below the
    # preceding baseline window's mean (floored so an idle engine's
    # near-zero throughput never reads as a collapse).
    goodput_regress_frac: float = 0.3
    goodput_recent_window_s: float = 60.0
    goodput_baseline_window_s: float = 300.0
    goodput_min_tps: float = 1.0


@dataclass
class Finding:
    """One diagnosis: the rule that fired, a 0..1 severity score (1 =
    drop everything), a one-line summary, and the rule's pinned
    evidence dict."""

    rule: str
    score: float
    summary: str
    evidence: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "score": round(self.score, 4),
            "summary": self.summary,
            "evidence": self.evidence,
        }


class BurnRateTracker:
    """Windowed error-budget burn over cumulative (admitted, shed)
    request counters, backed by the telemetry-history feed.

    :meth:`sample` records one counter snapshot per tenant (the history
    sampler — ``obs/timeseries.py`` — feeds it every interval via
    ``bind_burn_tracker``, so the windows are dense regardless of how
    rarely anyone calls :meth:`burn`); :meth:`burn` answers the
    shed-fraction burn multiple over a trailing window by diffing the
    newest sample against the last sample AT OR BEFORE the window
    start — the exact window diff, accurate to one sample spacing.
    Retention is spacing-aware: samples closer together than
    ``min_spacing_s`` collapse (the bounded ring then spans the full
    1 h slow window even at a 1 s feed cadence). The clock is
    injectable (virtual-time unit tests). Burn = (shed / offered) /
    budget — 1.0 means exactly spending the budget, 14.4 over 5 m AND
    6 over 1 h is the classic page condition.

    With the history ring feeding every sample the base is always
    within one spacing of the window start, so the window diff is
    exact. A base more than ``max_base_lag_s`` older than the window
    start means the feed is sparse (a history-less doctor polled
    slower than the bound) — then the tracker degrades to the PR 12
    conservative base, the first sample INSIDE the window, which
    under-counts the window's head but never smears stale shed into
    it. Only a feed with no in-window sample at all (sampler dead)
    answers "can't judge".
    """

    MAX_SAMPLES = 720  # the 1 h slow window at min_spacing_s granularity

    def __init__(
        self,
        budget: float,
        now=time.monotonic,
        min_spacing_s: float = 5.0,
        max_base_lag_s: float = 30.0,
        max_samples: int | None = None,
    ):
        self.budget = max(1e-9, float(budget))
        self.min_spacing_s = float(min_spacing_s)
        self.max_base_lag_s = float(max_base_lag_s)
        # MAX_SAMPLES is sized for the live 5 s spacing; a replay over
        # a finer-grained recording must widen the ring or eviction
        # silently drops the pre-window base.
        self.max_samples = int(max_samples) if max_samples else self.MAX_SAMPLES
        self._now = now
        self._lock = threading.Lock()
        # tenant → deque[(t, admitted, shed)]
        self._samples: dict[str, deque] = {}

    def sample(self, counts: dict[str, dict[str, int]], t: float | None = None) -> None:
        t = self._now() if t is None else t
        with self._lock:
            for tenant, c in counts.items():
                dq = self._samples.setdefault(
                    tenant, deque(maxlen=self.max_samples)
                )
                if dq and t - dq[-1][0] < self.min_spacing_s:
                    # Spacing-aware retention: a 1 s history feed must
                    # not shrink the ring's span below the slow window —
                    # overwrite the newest slot instead of appending.
                    dq[-1] = (dq[-1][0], int(c.get("admitted", 0)),
                              int(c.get("shed", 0)))
                    continue
                dq.append((t, int(c.get("admitted", 0)), int(c.get("shed", 0))))

    def burn(
        self, tenant: str, window_s: float, t: float | None = None
    ) -> tuple[float, int]:
        """(burn multiple, offered requests) over the trailing window —
        offered lets callers gate on sample size."""
        t = self._now() if t is None else t
        start = t - window_s
        with self._lock:
            dq = self._samples.get(tenant)
            if not dq or len(dq) < 2:
                return 0.0, 0
            newest = dq[-1]
            # Last sample at or before the window start: the correct
            # window-diff base (bisect on the time column).
            times = [s[0] for s in dq]
            i = bisect.bisect_right(times, start) - 1
            if i < 0:
                # The ring is younger than the window: every retained
                # sample is in-window — judge over the actual span (a
                # freshly booted node's honest answer).
                base = dq[0]
            else:
                base = dq[i]
                if start - base[0] > self.max_base_lag_s:
                    # Feed gap: the nearest pre-window sample is too
                    # stale to localize the in-window shed. Fall back
                    # to the first IN-WINDOW sample (the conservative
                    # PR 12 base) — the diff then under-counts the
                    # window's head instead of smearing stale shed
                    # into it, so a history-less doctor polled slower
                    # than the lag bound still judges. A dead feed
                    # (newest itself pre-window) still refuses below.
                    j = bisect.bisect_left(times, start)
                    if j >= len(dq) - 1:
                        return 0.0, 0
                    base = dq[j]
            if base is newest:
                return 0.0, 0
        admitted = newest[1] - base[1]
        shed = newest[2] - base[2]
        offered = admitted + shed
        if offered <= 0:
            return 0.0, 0
        return (shed / offered) / self.budget, offered

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._samples)


def _parse_labels(name: str) -> dict[str, str]:
    """Label dict off a rendered series name
    (``family{k="v",k2="v2"}``); {} when unlabeled/malformed."""
    i = name.find("{")
    if i < 0 or not name.endswith("}"):
        return {}
    out: dict[str, str] = {}
    for part in name[i + 1 : -1].split(","):
        if "=" not in part:
            continue
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip().strip('"')
    return out


def _tier_move_events(series: dict) -> list[tuple[float, int, str]]:
    """(t, shard, dir) move events reconstructed from recorded
    ``radixmesh_kv_tier_moves_total`` counter series. Counters are
    change-compressed cumulative values, so each point's delta over the
    previous point is the number of moves landing at that sample."""
    events: list[tuple[float, int, str]] = []
    for name, body in series.items():
        if not name.startswith("radixmesh_kv_tier_moves_total{"):
            continue
        labels = _parse_labels(name)
        d = labels.get("dir")
        sh = labels.get("shard")
        if d not in ("demote", "promote") or sh is None:
            continue
        pts = body["points"] if isinstance(body, dict) else body
        prev = None
        for _, t, v in pts:
            if prev is None:
                # The first retained point of a change-compressed ring
                # carries the cumulative PRE-window count (late-started
                # history, pruned head): it is the baseline, not a
                # burst of moves at one instant. Deliberately
                # conservative — for a ring that recorded from counter
                # birth this swallows the first real move per series
                # (at most one cycle at the exact threshold), which is
                # the right trade against a pruned head replaying
                # hundreds of phantom moves as a guaranteed false
                # tier_thrash.
                prev = float(v)
                continue
            delta = int(round(float(v) - prev))
            prev = float(v)
            events.extend((float(t), int(sh), d) for _ in range(max(0, delta)))
    events.sort()
    return events


def _max_flap(
    events: list[tuple[float, int, str]], window_s: float
) -> tuple[int, int, int, int] | None:
    """Worst flapping shard over any sliding window of ``window_s``:
    (cycles, demotes, promotes, shard) where cycles = min(demotes,
    promotes) inside the window — one 'cycle' is a full host→disk→host
    round trip. None when no demote/promote events exist."""
    by_shard: dict[int, list[tuple[float, int, str]]] = {}
    for ev in events:
        by_shard.setdefault(ev[1], []).append(ev)
    best: tuple[int, int, int, int] | None = None
    for sh, evs in sorted(by_shard.items()):
        i = 0
        counts = {"demote": 0, "promote": 0}
        for j, (t, _, d) in enumerate(evs):
            counts[d] += 1
            while evs[i][0] < t - window_s:
                counts[evs[i][2]] -= 1
                i += 1
            cand = (
                min(counts["demote"], counts["promote"]),
                counts["demote"], counts["promote"], sh,
            )
            if best is None or cand > best:
                best = cand
    return best


class MeshDoctor:
    """The diagnosis engine. Every input is an optional seam:

    - ``mesh``: a MeshCache (FleetView health/heat + shard ownership).
    - ``engine``: an Engine (kv_transfer lane depths, parked requests,
      per-shape spec counters via ``telemetry()``).
    - ``slo``: an OverloadController (``burn_counts()`` + ``.tier``).
    - ``attributor``: a PhaseAttributor (per-shape phase aggregates).
    - ``history``: a TelemetryHistory (``obs/timeseries.py``) — when
      attached, its sampler feeds the burn tracker every interval, so
      the 5 m / 1 h windows are dense regardless of how rarely anyone
      GETs ``/cluster/doctor`` (the PR 12 can't-judge gap is closed by
      construction).

    Construct ONE per frontend and call :meth:`diagnose` per GET — the
    burn tracker needs continuity across calls (a fresh doctor has no
    windows). Absent seams silently skip their rules; ``rules_checked``
    in the report says which actually ran, so "no findings" can never
    be confused with "nothing was looked at".
    """

    def __init__(
        self,
        mesh=None,
        engine=None,
        slo=None,
        attributor=None,
        history=None,
        aggregator=None,
        cfg: DoctorConfig | None = None,
        now=time.monotonic,
    ):
        self.mesh = mesh
        self.engine = engine
        self.slo = slo
        self._attributor = attributor
        self.history = history
        # A FleetAggregator (obs/aggregator.py): the cross-node seam
        # behind the three fleet rules — straggler_node over per-rank
        # signal folds, fleet_burn_slope over fleet-summed burn
        # windows, telemetry_gap over per-peer pull bookkeeping.
        self.aggregator = aggregator
        self.cfg = cfg or DoctorConfig()
        self._now = now
        self.burn_tracker = BurnRateTracker(self.cfg.burn_budget, now=now)
        # The history ring becomes the burn windows' clock source: every
        # sampler tick forwards slo.burn_counts() into the tracker
        # (diagnose() then never needs to self-sample). Only a history
        # that itself holds an SLO seam ever feeds bound trackers — a
        # doctor bound to an slo-less history would starve forever, so
        # that shape keeps self-sampling instead.
        self._burn_fed_by_history = (
            history is not None
            and slo is not None
            and getattr(history, "slo", None) is not None
        )
        if self._burn_fed_by_history:
            history.bind_burn_tracker(self.burn_tracker)
        # Skew trajectory for the rebalancer_asleep rule when no
        # history ring is attached: diagnose-time samples, bounded to
        # the rule's window (the burn self-sampling pattern).
        self._skew_samples: deque = deque(maxlen=1024)

    # The attributor seam is callable-or-instance: frontends pass
    # obs.attribution.ensure_attributor so a test-swapped recorder
    # transparently resolves to its fresh attributor.
    @property
    def attributor(self):
        a = self._attributor
        return a() if callable(a) else a

    # -- rules ---------------------------------------------------------

    def _rule_hot_shard(self) -> Finding | None:
        if self.mesh is None or not getattr(self.mesh, "sharded", False):
            return None
        report = self.mesh.shard_heat_report()
        skew = float(report.get("skew_score") or 0.0)
        reporters = int(report.get("reporters") or 0)
        if (
            skew < self.cfg.hot_shard_skew
            or reporters < self.cfg.hot_shard_min_reporters
        ):
            return None
        shard = report.get("hot_shard")
        owners = sorted(report.get("hot_owners", []))
        return Finding(
            "hot_shard",
            min(1.0, 0.5 + skew / (8.0 * self.cfg.hot_shard_skew)),
            f"shard {shard} is soaking the fleet (skew {skew:.1f}, "
            f"owners {owners}) — rebalance or raise its RF",
            {
                "skew_score": round(skew, 4),
                "shard": shard,
                "owners": owners,
                "reporters": reporters,
            },
        )

    def _rule_prefill_convoy(self) -> Finding | None:
        attr = self.attributor
        if attr is None:
            return None
        shapes = {
            k: v
            for k, v in attr.by_shape().items()
            if v["count"] >= self.cfg.convoy_min_requests and v["e2e_s"] > 0
        }
        if not shapes:
            return None
        worst = None
        for shape, agg in shapes.items():
            share = agg["phases"].get("prefill", 0.0) / agg["e2e_s"]
            mean_e2e = agg["e2e_s"] / agg["count"]
            others = [
                (o["e2e_s"], o["count"])
                for k, o in shapes.items()
                if k != shape
            ]
            other_mean = (
                sum(e for e, _ in others) / max(1, sum(c for _, c in others))
                if others
                else 0.0
            )
            if share < self.cfg.convoy_prefill_share:
                continue
            if others and mean_e2e < other_mean * self.cfg.convoy_slowdown:
                # Prefill-heavy but not slower than the rest of the
                # traffic: batch-1-style workloads are prefill-dominant
                # by nature, not convoyed.
                continue
            cand = (share, shape, mean_e2e, other_mean, agg["count"])
            if worst is None or cand > worst:
                worst = cand
        if worst is None:
            return None
        share, shape, mean_e2e, other_mean, count = worst
        return Finding(
            "prefill_convoy",
            min(1.0, 0.4 + share / 2.0),
            f"shape {shape} spends {share:.0%} of its e2e in prefill "
            f"waves ({mean_e2e * 1e3:.0f} ms mean e2e vs "
            f"{other_mean * 1e3:.0f} ms fleet) — long prompts are "
            "convoying; interleave chunked prefill with decode",
            {
                "shape": shape,
                "prefill_share": round(share, 4),
                "mean_e2e_s": round(mean_e2e, 6),
                "fleet_mean_e2e_s": round(other_mean, 6),
                "requests": count,
            },
        )

    def _rule_restore_park_stall(self) -> Finding | None:
        eng = self.engine
        attr = self.attributor
        parked = restores_queued = 0
        if eng is not None:
            parked = len(getattr(eng, "_restoring", ()))
            plane = getattr(eng, "kv_transfer", None)
            if plane is not None:
                st = plane.stats()
                restores_queued = int(st.get("restores_queued", 0)) + int(
                    st.get("staged_chunks", 0)
                )
        park_p99 = park_share = 0.0
        if attr is not None:
            hist = attr.phase_hist("restore_park")
            total = sum(attr.phase_totals().values())
            if hist is not None and hist.count:
                park_p99 = hist.quantile(0.99)
                park_share = hist.sum / total if total > 0 else 0.0
        live_stall = (
            parked >= self.cfg.park_min_parked and restores_queued > 0
        )
        audited_stall = park_share > self.cfg.park_share
        if not (live_stall or audited_stall):
            return None
        return Finding(
            "restore_park_stall",
            min(1.0, 0.4 + 0.1 * parked + park_share),
            f"{parked} request(s) parked in RESTORING behind "
            f"{restores_queued} queued restore unit(s) "
            f"(park share {park_share:.0%}, p99 {park_p99 * 1e3:.0f} ms) "
            "— the restore lane is throttled; raise chunk size or lane "
            "concurrency",
            {
                "lane": "restore",
                "parked": parked,
                "restores_queued": restores_queued,
                "park_p99_s": round(park_p99, 6),
                "park_share": round(park_share, 4),
            },
        )

    def _rule_replication_lag(self) -> Finding | None:
        if self.mesh is None:
            return None
        fleet = getattr(self.mesh, "fleet", None)
        if fleet is None:
            return None
        lagging = {
            rank: round(d.replication_lag_s, 4)
            for rank, d in fleet.digests().items()
            if d.replication_lag_s > self.cfg.lag_threshold_s
        }
        if not lagging:
            return None
        worst = max(lagging.values())
        return Finding(
            "replication_lag",
            min(1.0, 0.4 + 0.1 * worst / self.cfg.lag_threshold_s),
            f"{len(lagging)} node(s) applying oplog frames "
            f"{worst:.1f}s after origin (threshold "
            f"{self.cfg.lag_threshold_s}s): {sorted(lagging)} — "
            "replicas are stale; failover there would lose prefix hits",
            {
                "ranks": {str(r): v for r, v in sorted(lagging.items())},
                "threshold_s": self.cfg.lag_threshold_s,
                "worst_lag_s": worst,
            },
        )

    def _rule_slo_burn_rate(self) -> Finding | None:
        slo = self.slo
        if slo is None:
            return None
        if not self._burn_fed_by_history:
            # Doctors whose tracker isn't fed by a sampler tick (no
            # history, or a history built without an SLO seam) still
            # self-sample at diagnose time; history-fed ones must not
            # double-sample.
            self.burn_tracker.sample(slo.burn_counts())
        cfg = self.cfg
        worst: Finding | None = None
        for tenant in self.burn_tracker.tenants():
            fast, offered = self.burn_tracker.burn(
                tenant, cfg.burn_fast_window_s
            )
            slow, _ = self.burn_tracker.burn(tenant, cfg.burn_slow_window_s)
            if offered < cfg.burn_min_requests:
                continue
            if (
                fast < cfg.burn_fast_threshold
                or slow < cfg.burn_slow_threshold
            ):
                continue
            f = Finding(
                "slo_burn_rate",
                min(1.0, 0.6 + fast / (10.0 * cfg.burn_fast_threshold)),
                f"tenant {tenant!r} burning error budget at "
                f"{fast:.1f}x over {cfg.burn_fast_window_s:.0f}s AND "
                f"{slow:.1f}x over {cfg.burn_slow_window_s:.0f}s "
                f"(budget {cfg.burn_budget:.2%} shed) — sustained "
                "overload, not a blip",
                {
                    "tenant": tenant,
                    "burn_fast": round(fast, 3),
                    "burn_slow": round(slow, 3),
                    "fast_window_s": cfg.burn_fast_window_s,
                    "slow_window_s": cfg.burn_slow_window_s,
                    "budget": cfg.burn_budget,
                    "tier": int(getattr(slo, "tier", 0)),
                },
            )
            if worst is None or f.score > worst.score:
                worst = f
        return worst

    def _rule_spec_efficiency(self) -> Finding | None:
        eng = self.engine
        if eng is None:
            return None
        spec = eng.spec_report()
        worst = None
        for shape, c in spec.items():
            if c["proposed"] < self.cfg.spec_min_proposed:
                continue
            if c["acceptance"] >= self.cfg.spec_accept_floor:
                continue
            cand = (
                self.cfg.spec_accept_floor - c["acceptance"], shape, c,
            )
            if worst is None or cand > worst:
                worst = cand
        if worst is None:
            return None
        _, shape, c = worst
        return Finding(
            "spec_efficiency",
            min(1.0, 0.3 + (self.cfg.spec_accept_floor - c["acceptance"])),
            f"shape {shape} accepts only {c['acceptance']:.0%} of "
            f"{c['proposed']} proposed draft tokens (floor "
            f"{self.cfg.spec_accept_floor:.0%}) — speculative verify "
            "waves are wasted compute there; shrink γ for that class",
            {
                "shape": shape,
                "proposed": c["proposed"],
                "accepted": c["accepted"],
                "acceptance": c["acceptance"],
            },
        )

    def _skew_trajectory(
        self, now: float
    ) -> tuple[list[tuple[float, float]], bool]:
        """((t, skew) points covering the trailing rule window, exact):
        the history ring's change-compressed ``shard:skew_ratio`` series
        when one is attached (dense regardless of diagnose cadence —
        a gap means the value did NOT change, so persistence is exact),
        else this doctor's own diagnose-time samples (a gap means
        nobody LOOKED — persistence must be capped)."""
        hist = self.history
        if hist is not None:
            try:
                q = hist.query(family="shard:skew_ratio", limit=100000)
                s = q["series"].get("shard:skew_ratio")
                if s is not None:
                    return [(p[1], float(p[2])) for p in s["points"]], True
            except Exception:  # noqa: BLE001 — a broken seam degrades to self-sampling
                pass
        mesh = self.mesh
        skew = 0.0
        if mesh is not None and getattr(mesh, "sharded", False):
            skew = float(mesh.fleet.shard_heat().get("skew_score") or 0.0)
        self._skew_samples.append((now, skew))
        return list(self._skew_samples), False

    @staticmethod
    def _sustained_above(
        pts,
        threshold: float,
        start: float,
        end: float,
        max_gap_s: float | None = None,
    ) -> tuple[float, float]:
        """(seconds above threshold, peak value) over [start, end].
        Each point's value persists until the next point — or at most
        ``max_gap_s`` when given (self-sampled trajectories: a sparse
        poll proves nothing about the time nobody looked, so two
        momentary spikes must not smear into a sustained storm)."""
        above_s = 0.0
        peak = 0.0
        for i, (t, v) in enumerate(pts):
            nxt = pts[i + 1][0] if i + 1 < len(pts) else end
            if max_gap_s is not None:
                nxt = min(nxt, t + max_gap_s)
            seg_start = max(t, start)
            seg_end = min(nxt, end)
            if seg_end <= seg_start:
                continue
            peak = max(peak, v)
            if v >= threshold:
                above_s += seg_end - seg_start
        return above_s, peak

    def _rule_rebalancer_asleep(self) -> Finding | None:
        mesh = self.mesh
        if mesh is None or not getattr(mesh, "sharded", False):
            return None
        cfg = self.cfg
        now = self._now()
        pts, exact = self._skew_trajectory(now)
        sustained, peak = self._sustained_above(
            pts, cfg.hot_shard_skew, now - cfg.rebalance_window_s, now,
            max_gap_s=None if exact else cfg.rebalance_max_sample_gap_s,
        )
        if sustained < cfg.rebalance_sustain_s:
            return None
        plane = getattr(mesh, "rebalance", None)
        moves = (
            plane.moves_in_window(cfg.rebalance_window_s)
            if plane is not None
            else 0
        )
        if moves > 0:
            return None
        hot = mesh.fleet.shard_heat().get("hot_shard")
        why = (
            "no rebalance plane is armed"
            if plane is None
            else "the rebalance plane adopted zero moves"
        )
        return Finding(
            "rebalancer_asleep",
            min(1.0, 0.5 + peak / (8.0 * cfg.hot_shard_skew)),
            f"skew held >= {cfg.hot_shard_skew:.1f} for {sustained:.0f}s "
            f"(peak {peak:.1f}, hot shard {hot}) while {why} in the "
            f"same {cfg.rebalance_window_s:.0f}s window — the heat map "
            "sees a storm nothing is acting on",
            {
                "skew_peak": round(peak, 4),
                "sustained_s": round(sustained, 3),
                "window_s": cfg.rebalance_window_s,
                "moves_in_window": int(moves),
                "hot_shard": hot,
                "plane_armed": plane is not None,
            },
        )

    def _rule_tier_thrash(self) -> Finding | None:
        cfg = self.cfg
        now = self._now()
        events: list[tuple[float, int, str]] = []
        source = None
        hist = self.history
        if hist is not None:
            try:
                q = hist.query(
                    family="radixmesh_kv_tier_moves_total", limit=100000
                )
                events = _tier_move_events(q["series"])
                if events:
                    source = "history"
            except Exception:  # noqa: BLE001 — a broken seam degrades to the live ring
                events = []
        if not events:
            tier = getattr(self.engine, "_kv_tier", None) \
                if self.engine is not None else None
            if tier is None:
                return None
            # list() is one C call over the deque (GIL-atomic snapshot,
            # the spec_report discipline).
            events = [
                (t, sh, d)
                for (t, sh, d) in list(tier.recent_moves)
                if d in ("demote", "promote")
            ]
            source = "live"
        events = [
            e for e in events if e[0] >= now - cfg.tier_thrash_window_s
        ]
        best = _max_flap(events, cfg.tier_thrash_window_s)
        if best is None or best[0] < cfg.tier_thrash_min_cycles:
            return None
        cycles, demotes, promotes, shard = best
        return Finding(
            "tier_thrash",
            min(1.0, 0.4 + 0.1 * cycles),
            f"subtree shard {shard} flapped host<->disk {cycles}x "
            f"({demotes} demotes / {promotes} promotes) inside the "
            f"{cfg.tier_thrash_window_s:.0f}s hysteresis window — the "
            "working set straddles the destage watermark; raise the "
            "watermark or the host arena",
            {
                "shard": int(shard),
                "demotes": int(demotes),
                "promotes": int(promotes),
                "cycles": int(cycles),
                "window_s": cfg.tier_thrash_window_s,
                "source": source,
            },
        )

    def _rule_straggler_node(self) -> Finding | None:
        agg = self.aggregator
        if agg is None:
            return None
        cfg = self.cfg
        worst = None
        for signal, family in (
            ("decode_ewma", "fleet:decode_ewma_seconds"),
            ("replication_lag", "fleet:replication_lag_seconds"),
        ):
            vals = {
                r: v for r, v in agg.rank_signal(family).items() if v > 0
            }
            if len(vals) < cfg.straggler_min_ranks:
                continue
            svals = sorted(vals.values())
            # Lower median: with two active ranks the baseline is the
            # FASTER one, so a 2-decode cell can still name its
            # straggler instead of comparing the slow rank to itself.
            median = svals[(len(svals) - 1) // 2]
            rank, v = max(vals.items(), key=lambda kv: kv[1])
            if v < cfg.straggler_floor_s:
                continue
            ratio = v / max(median, 1e-9)
            if ratio < cfg.straggler_ratio:
                continue
            cand = (ratio, signal, rank, v, median, len(vals))
            if worst is None or cand > worst:
                worst = cand
        if worst is None:
            return None
        ratio, signal, rank, v, median, n_ranks = worst
        return Finding(
            "straggler_node",
            min(1.0, 0.5 + ratio / (10.0 * cfg.straggler_ratio)),
            f"rank {rank} is a straggler: {signal} EWMA {v * 1e3:.1f} ms "
            f"vs fleet median {median * 1e3:.1f} ms ({ratio:.1f}x over "
            f"{n_ranks} active ranks) — drain or replace it before the "
            "mesh convoys behind it",
            {
                "rank": str(rank),
                "signal": signal,
                "value_s": round(v, 6),
                "fleet_median_s": round(median, 6),
                "ratio": round(ratio, 3),
                "ranks": n_ranks,
            },
        )

    def _rule_fleet_burn_slope(self) -> Finding | None:
        agg = self.aggregator
        if agg is None:
            return None
        cfg = self.cfg
        report = agg.fleet_burn_report(
            fast_window_s=cfg.burn_fast_window_s,
            slow_window_s=cfg.burn_slow_window_s,
        )
        worst: Finding | None = None
        for tenant, r in report.items():
            if r["offered"] < cfg.fleet_burn_min_requests:
                continue
            if (
                r["burn_fast"] < cfg.fleet_burn_fast_threshold
                or r["burn_slow"] < cfg.fleet_burn_slow_threshold
            ):
                continue
            slope = r["slope_per_s"]
            trend = (
                "and RISING" if slope > 0
                else ("and falling" if slope < 0 else "flat")
            )
            f = Finding(
                "fleet_burn_slope",
                min(
                    1.0,
                    0.5
                    + r["burn_fast"] / (10.0 * cfg.fleet_burn_fast_threshold)
                    + max(0.0, slope),
                ),
                f"tenant {tenant!r} burning error budget FLEET-WIDE at "
                f"{r['burn_fast']:.1f}x (fast) / {r['burn_slow']:.1f}x "
                f"(slow), slope {slope:+.4f}/s {trend} — the pre-scale "
                "signal: add capacity before the per-node pager trips",
                {
                    "tenant": tenant,
                    "burn_fast": r["burn_fast"],
                    "burn_slow": r["burn_slow"],
                    "slope_per_s": slope,
                    "budget": r["budget"],
                    "offered": r["offered"],
                },
            )
            if worst is None or f.score > worst.score:
                worst = f
        return worst

    def _rule_telemetry_gap(self) -> Finding | None:
        agg = self.aggregator
        if agg is None:
            return None
        cfg = self.cfg
        worst = None
        for name, st in agg.peer_status().items():
            stalled = st.get("stalled_s")
            if stalled is None:
                # Never pulled successfully: the aggregator cannot tell
                # a dead peer from one it has not reached yet.
                continue
            thresh = max(cfg.telemetry_gap_s, st.get("gap_threshold_s", 0.0))
            if stalled < thresh:
                continue
            # Disambiguate dead sampler vs dead node via the gossip
            # plane: a rank the FleetView still scores healthy has a
            # live process whose SAMPLER stopped; a rank gossip also
            # lost is simply dead.
            verdict = "unknown"
            rank = st.get("rank")
            if rank is not None and self.mesh is not None:
                try:
                    h = self.mesh.fleet.health().get(rank)
                    if h is not None and h["score"] >= 0.5:
                        verdict = "sampler_dead"
                    else:
                        verdict = "node_dead"
                except Exception:  # noqa: BLE001 — gossip seam optional for the verdict
                    pass
            cand = (stalled, name, rank, st, verdict)
            if worst is None or cand[0] > worst[0]:
                worst = cand
        if worst is None:
            return None
        stalled, name, rank, st, verdict = worst
        what = {
            "sampler_dead": "its process still gossips healthy — the "
            "SAMPLER died, not the node",
            "node_dead": "gossip lost it too — the node is dead",
            "unknown": "no gossip view to disambiguate",
        }[verdict]
        return Finding(
            "telemetry_gap",
            min(1.0, 0.5 + 0.05 * stalled),
            f"peer {name!r} ring stopped advancing {stalled:.1f}s ago "
            f"(last seq {st['seq']}); {what}",
            {
                "peer": name,
                "rank": None if rank is None else str(rank),
                "stalled_s": round(stalled, 3),
                "peer_seq": st["seq"],
                "verdict": verdict,
            },
        )

    def _rule_decode_stall(self) -> Finding | None:
        """Token-timeline stall histogram: enough attributed inter-token
        gaps over the stall threshold, with the DOMINANT cause named —
        this is the per-token refinement of restore_park_stall (which
        sees parks, not the gap each park put into someone's stream)."""
        eng = self.engine
        tl = getattr(eng, "timeline", None) if eng is not None else None
        if tl is None:
            return None
        snap = tl.snapshot(limit=0)
        stalls = snap.get("stalls") or {}
        total = sum(stalls.values())
        if total < self.cfg.decode_stall_min_events:
            return None
        cause = max(stalls, key=stalls.get)
        stall_s = float((snap.get("stall_seconds") or {}).get(cause, 0.0))
        p99 = max(
            (t.get("p99_s") or 0.0 for t in snap.get("itl", {}).values()),
            default=0.0,
        )
        return Finding(
            "decode_stall",
            min(1.0, 0.3 + 0.05 * stall_s + min(0.3, total / 200.0)),
            f"{total} decode stalls (>{snap['stall_threshold_s'] * 1e3:.0f}ms "
            f"inter-token gap), dominant cause {cause!r} "
            f"({stalls[cause]} events, {stall_s:.2f}s of stream time); "
            f"worst tenant p99 ITL {p99 * 1e3:.1f}ms",
            {
                "cause": cause,
                "stalls": total,
                "stall_seconds": round(stall_s, 3),
                "p99_itl_s": round(p99, 6),
                "threshold_s": snap["stall_threshold_s"],
            },
        )

    def _rule_spec_misconfigured(self) -> Finding | None:
        """γ and acceptance diverge: a (tenant, shape, draft-source)
        class keeps proposing wide waves whose EWMA acceptance sits
        under the floor. Distinct from spec_efficiency (raw per-shape
        counters): this judges the LEDGER's smoothed per-class view and
        stays silent when the SLO ladder zeroed γ on purpose."""
        eng = self.engine
        led = getattr(eng, "spec_ledger", None) if eng is not None else None
        if led is None:
            return None
        if getattr(eng, "spec_decode_tokens", 0) <= 0:
            return None  # speculation is off — nothing to mis-tune
        if getattr(led, "last_tier", 0) >= 1:
            return None  # γ zeroed by SLO policy, not by mistuning
        cfg = self.cfg
        worst = None
        for c in led.report().values():
            if c["proposed"] < cfg.spec_misconfig_min_proposed:
                continue
            ewma = c.get("accept_ewma")
            if ewma is None or ewma >= cfg.spec_accept_floor:
                continue
            if c.get("gamma_used", 0) <= 1:
                continue  # already at the narrowest useful γ
            cand = (cfg.spec_accept_floor - ewma, c)
            if worst is None or cand[0] > worst[0]:
                worst = cand
        if worst is None:
            return None
        gap, c = worst
        return Finding(
            "spec_misconfigured",
            min(1.0, 0.3 + gap),
            f"class {c['tenant']}/{c['shape']}/{c['source']} runs "
            f"γ={c['gamma_used']} while EWMA acceptance is "
            f"{c['accept_ewma']:.0%} (floor "
            f"{cfg.spec_accept_floor:.0%}, {c['proposed']} proposed) — "
            "shrink γ for that class or enable --spec-adaptive",
            {
                "tenant": c["tenant"],
                "shape": c["shape"],
                "source": c["source"],
                "gamma": c["gamma_used"],
                "accept_ewma": c["accept_ewma"],
                "proposed": c["proposed"],
            },
        )

    def _rule_goodput_regression(self) -> Finding | None:
        """The history ring's ``goodput:tokens_per_second`` series in
        the trailing recent window fell regress_frac below the preceding
        baseline window — useful throughput collapsed while the fleet is
        still up (the waste decomposition in /debug/tokens says where it
        went)."""
        hist = self.history
        if hist is None:
            return None
        try:
            q = hist.query(family="goodput:tokens_per_second", limit=100000)
            s = q["series"].get("goodput:tokens_per_second")
        except Exception:  # noqa: BLE001 — a broken seam is silence, not a crash
            return None
        if s is None:
            return None
        pts = [(p[1], float(p[2])) for p in s["points"]]
        if len(pts) < 2:
            return None
        cfg = self.cfg
        now = pts[-1][0]
        recent = [v for t, v in pts if t >= now - cfg.goodput_recent_window_s]
        base = [
            v
            for t, v in pts
            if now - cfg.goodput_baseline_window_s
            <= t
            < now - cfg.goodput_recent_window_s
        ]
        if not recent or not base:
            return None
        r = sum(recent) / len(recent)
        b = sum(base) / len(base)
        if b < cfg.goodput_min_tps:
            return None  # idle baseline: nothing to regress from
        drop = (b - r) / b
        if drop < cfg.goodput_regress_frac:
            return None
        return Finding(
            "goodput_regression",
            min(1.0, 0.3 + drop),
            f"useful throughput fell {drop:.0%}: {r:.1f} tok/s over the "
            f"last {cfg.goodput_recent_window_s:.0f}s vs {b:.1f} tok/s "
            "baseline — check the /debug/tokens waste decomposition "
            "(padding vs rejected drafts vs stalls)",
            {
                "recent_tps": round(r, 3),
                "baseline_tps": round(b, 3),
                "drop_frac": round(drop, 4),
                "window_s": cfg.goodput_recent_window_s,
            },
        )

    # -- the diagnosis -------------------------------------------------

    def diagnose(self) -> dict:
        """Run every rule whose inputs are attached; return the ranked
        findings report (the ``GET /cluster/doctor`` body)."""
        checks = {
            "hot_shard": self._rule_hot_shard,
            "prefill_convoy": self._rule_prefill_convoy,
            "restore_park_stall": self._rule_restore_park_stall,
            "replication_lag": self._rule_replication_lag,
            "slo_burn_rate": self._rule_slo_burn_rate,
            "spec_efficiency": self._rule_spec_efficiency,
            "rebalancer_asleep": self._rule_rebalancer_asleep,
            "tier_thrash": self._rule_tier_thrash,
            "straggler_node": self._rule_straggler_node,
            "fleet_burn_slope": self._rule_fleet_burn_slope,
            "telemetry_gap": self._rule_telemetry_gap,
            "decode_stall": self._rule_decode_stall,
            "spec_misconfigured": self._rule_spec_misconfigured,
            "goodput_regression": self._rule_goodput_regression,
        }
        # Seam presence per rule: a rule whose inputs are absent never
        # looked at anything, so it must NOT appear in rules_checked —
        # that list is the honesty field the module contract promises
        # ("no findings" vs "nothing was looked at"), and the healthy-
        # phase gate in bench.validate_doctor is vacuous without it.
        attr = self.attributor
        available = {
            "hot_shard": self.mesh is not None,
            "prefill_convoy": attr is not None,
            "restore_park_stall": self.engine is not None
            or attr is not None,
            "replication_lag": self.mesh is not None,
            "slo_burn_rate": self.slo is not None,
            "spec_efficiency": self.engine is not None,
            "rebalancer_asleep": self.mesh is not None,
            # The tier series ride the history ring even for an
            # engine-less doctor (a frontend sampling a remote
            # registry), so either seam arms the rule.
            "tier_thrash": self.engine is not None
            or self.history is not None,
            # The fleet rules judge the aggregator's cross-node store;
            # no single-node seam can substitute for it.
            "straggler_node": self.aggregator is not None,
            "fleet_burn_slope": self.aggregator is not None,
            "telemetry_gap": self.aggregator is not None,
            # Token-plane rules: the timeline/ledger hang off the
            # engine; the goodput series rides the history ring (so a
            # frontend sampling a remote registry can still run it).
            "decode_stall": self.engine is not None,
            "spec_misconfigured": self.engine is not None,
            "goodput_regression": self.history is not None,
        }
        findings: list[Finding] = []
        checked: list[str] = []
        for rule in RULES:
            if not available[rule]:
                continue
            try:
                f = checks[rule]()
            except Exception as e:  # noqa: BLE001 — a broken rule is a finding, not an outage
                f = Finding(
                    rule, 0.1,
                    f"rule crashed: {e!r} (diagnosis plane bug — file it)",
                    {"error": repr(e)},
                )
            checked.append(rule)
            if f is not None:
                missing = [
                    k
                    for k in RULE_EVIDENCE_FIELDS.get(rule, ())
                    if k not in f.evidence and "error" not in f.evidence
                ]
                if missing:  # pinned-evidence contract, enforced live
                    f.evidence["_missing_evidence"] = missing
                findings.append(f)
        findings.sort(key=lambda f: (-f.score, RULES.index(f.rule)))
        return {
            "findings": [f.as_dict() for f in findings],
            "healthy": not findings,
            "rules_checked": checked,
            "inputs": {
                "mesh": self.mesh is not None,
                "engine": self.engine is not None,
                "slo": self.slo is not None,
                "attribution": self.attributor is not None,
                "history": self.history is not None,
                "aggregator": self.aggregator is not None,
            },
        }


# ---------------------------------------------------------------------------
# post-mortem doctoring: the same judgment, over a black-box dump alone
# ---------------------------------------------------------------------------

# Post-mortem rule ids in severity-tiebreak order. These replay the
# live rules' judgment over RECORDED history series (a dump has no mesh
# or engine object to duck-type), plus the one rule only hindsight can
# run: node_crash.
POSTMORTEM_RULES = (
    "node_crash",
    "hot_shard",
    "replication_lag",
    "slo_burn_rate",
    "tier_thrash",
)

POSTMORTEM_EVIDENCE_FIELDS = {
    "node_crash": ("rank", "window", "detector"),
    "hot_shard": ("shard", "skew_peak", "t_peak"),
    "replication_lag": ("ranks", "threshold_s", "worst_lag_s"),
    "slo_burn_rate": ("tenant", "burn_fast", "burn_slow", "t_peak"),
    "tier_thrash": ("shard", "demotes", "promotes", "cycles", "window_s"),
}


def _labeled_series(series: dict, prefix: str) -> dict[str, list]:
    """label value → points, for series named ``prefix{label="X"}``."""
    out: dict[str, list] = {}
    head = prefix + "{"
    for name, pts in series.items():
        if name.startswith(head) and name.endswith('"}'):
            label = name[len(head):-2].split('="', 1)[-1]
            out[label] = pts
    return out


def _value_at(pts: list, t: float):
    """The change-compressed series' value at time ``t`` (last point at
    or before it); None before the first point."""
    times = [p[1] for p in pts]
    i = bisect.bisect_right(times, t) - 1
    return pts[i][2] if i >= 0 else None


def postmortem_report(dump: dict, cfg: DoctorConfig | None = None) -> dict:
    """Replay the doctor's judgment over a black-box dump
    (``obs/blackbox.py::load_blackbox``) — no live cluster required.
    ``scripts/doctor.py --blackbox`` is the CLI.

    Unlike the live rules, post-mortem rules judge the WHOLE recorded
    window (a pathology that peaked mid-flight and cooled before the
    dump still fired), and they can name the one thing no live rule
    can: the crash itself —

    - ``node_crash`` detector "health_drop": a fleet health score
      falling below 0.5; the window is anchored by the recorded digest
      age at the drop (the node was last heard from ``age`` seconds
      before the drop sample).
    - ``node_crash`` detector "history_truncated": the dump itself ends
      without any final flush (the kill -9 signature) — the crash
      window is the last recorded sample plus one segment of slack.
    """
    cfg = cfg or DoctorConfig()
    series: dict = dump.get("series", {})
    interval = float(dump.get("interval_s") or 1.0)
    findings: list[Finding] = []
    checked: list[str] = []

    # -- node_crash ----------------------------------------------------
    checked.append("node_crash")
    ages = _labeled_series(series, "fleet:health_age_seconds")
    for rank, pts in sorted(
        _labeled_series(series, "fleet:health_score").items()
    ):
        seen_good = False
        for seq, t, v in pts:
            if v >= 0.5:
                seen_good = True
                continue
            if not seen_good:
                # A drop only counts after the rank has been seen
                # healthy; leading sub-0.5 points (sampler started
                # while the digest was still converging) are skipped,
                # not terminal for the rank.
                continue
            age = _value_at(ages.get(rank, []), t) or 0.0
            findings.append(Finding(
                "node_crash",
                0.9,
                f"node rank {rank} went dark: health dropped to {v:.2f} "
                f"at t={t:.1f}, last heard {age:.1f}s earlier — crash "
                f"window [{t - age:.1f}, {t:.1f}]",
                {
                    "rank": rank,
                    "window": [round(t - age, 3), round(t, 3)],
                    "detector": "health_drop",
                    "score_at_drop": v,
                    "age_at_drop_s": round(age, 3),
                },
            ))
            break
    if dump.get("unclean") and dump.get("last_t") is None:
        # The box was armed (a manifest exists) but no history was ever
        # committed and no final flushed: the node died before its
        # first segment — unclean by construction, but with nothing
        # recorded there is no window to anchor.
        findings.append(Finding(
            "node_crash",
            1.0,
            f"node {dump.get('node', '?')}'s black box was armed but "
            "holds NO committed history and NO final flush — unclean "
            "death before the first segment; no crash window can be "
            "anchored",
            {
                "rank": dump.get("node", "?"),
                "window": [None, None],
                "detector": "history_truncated",
                "last_seq": None,
            },
        ))
    if dump.get("unclean") and dump.get("last_t") is not None:
        last_t = float(dump["last_t"])
        slack = interval * float(
            dump.get("manifest", {}).get("segment_every", 1) or 1
        )
        findings.append(Finding(
            "node_crash",
            1.0,
            f"node {dump.get('node', '?')}'s own history ends at "
            f"t={last_t:.1f} with NO final flush — unclean death; crash "
            f"window [{last_t:.1f}, {last_t + slack:.1f}] (one segment "
            "of slack past the last committed sample)",
            {
                "rank": dump.get("node", "?"),
                "window": [round(last_t, 3), round(last_t + slack, 3)],
                "detector": "history_truncated",
                "last_seq": dump.get("last_seq"),
            },
        ))

    # -- hot_shard (peak over the recorded window) ---------------------
    checked.append("hot_shard")
    skew_pts = series.get("shard:skew_ratio", [])
    if skew_pts:
        _, t_peak, skew_peak = max(skew_pts, key=lambda p: p[2])
        if skew_peak >= cfg.hot_shard_skew:
            heats = _labeled_series(series, "shard:heat")
            hot, hot_load = None, -1.0
            for sid, pts in heats.items():
                v = _value_at(pts, t_peak)
                if v is not None and v > hot_load:
                    hot, hot_load = int(sid), v
            if hot is None:
                # Skew peaked but no shard:heat series has a point at
                # or before the peak (heat rings pruned/capped, or the
                # first heat sample landed after the skew one) — a
                # "shard None peaked" finding would name nothing, so
                # record the anomaly as unresolvable instead.
                findings.append(Finding(
                    "hot_shard",
                    0.5,
                    f"skew peaked at {skew_peak:.1f} (t={t_peak:.1f}) "
                    "but the recorded heat series cannot resolve which "
                    "shard — heat rings pruned or absent at the peak",
                    {
                        "shard": None,
                        "skew_peak": round(skew_peak, 4),
                        "t_peak": round(t_peak, 3),
                        "hot_load": None,
                    },
                ))
            else:
                ev = {
                    "shard": hot,
                    "skew_peak": round(skew_peak, 4),
                    "t_peak": round(t_peak, 3),
                    "hot_load": round(hot_load, 4),
                }
                # The final dump's live findings can enrich the owner
                # set (owners are an ownership-map fact no recorded
                # series carries) — present only on dumps that reached
                # a flush.
                final = dump.get("final") or {}
                for f in (final.get("doctor") or {}).get(
                    "findings", ()
                ):
                    if f.get("rule") == "hot_shard" and f.get(
                        "evidence", {}
                    ).get("shard") == hot:
                        ev["owners"] = f["evidence"].get("owners")
                findings.append(Finding(
                    "hot_shard",
                    min(
                        1.0,
                        0.5 + skew_peak / (8.0 * cfg.hot_shard_skew),
                    ),
                    f"shard {hot} peaked at skew {skew_peak:.1f} "
                    f"(t={t_peak:.1f}) over the recorded window",
                    ev,
                ))

    # -- replication_lag (peak per rank) -------------------------------
    checked.append("replication_lag")
    lagging = {}
    for rank, pts in _labeled_series(
        series, "fleet:replication_lag_seconds"
    ).items():
        peak = max((p[2] for p in pts), default=0.0)
        if peak > cfg.lag_threshold_s:
            lagging[rank] = round(peak, 4)
    if lagging:
        findings.append(Finding(
            "replication_lag",
            min(1.0, 0.4 + 0.1 * max(lagging.values()) / cfg.lag_threshold_s),
            f"{len(lagging)} node(s) peaked past {cfg.lag_threshold_s}s "
            f"replication lag in the recorded window: {sorted(lagging)}",
            {
                "ranks": dict(sorted(lagging.items())),
                "threshold_s": cfg.lag_threshold_s,
                "worst_lag_s": max(lagging.values()),
            },
        ))

    # -- slo_burn_rate (worst multi-window point in the record) --------
    checked.append("slo_burn_rate")
    adm = _labeled_series(series, "slo:admitted")
    shed = _labeled_series(series, "slo:shed")
    for tenant in sorted(set(adm) & set(shed)):
        # Recorded series are change-compressed: a gap between points
        # means the counters did not move, so an arbitrarily stale
        # base is EXACT (the counter value at the window start), not a
        # smear risk — a storm that follows a long idle stretch must
        # still be named. No staleness refusal in replay.
        merged = sorted(
            {p[1] for p in adm[tenant]} | {p[1] for p in shed[tenant]}
        )
        tracker = BurnRateTracker(
            cfg.burn_budget, min_spacing_s=0.0,
            max_base_lag_s=float("inf"),
            max_samples=len(merged) + 1,
        )
        worst = None
        for t in merged:
            a = _value_at(adm[tenant], t) or 0.0
            s = _value_at(shed[tenant], t) or 0.0
            tracker.sample(
                {tenant: {"admitted": int(a), "shed": int(s)}}, t=t
            )
            fast, offered = tracker.burn(tenant, cfg.burn_fast_window_s, t=t)
            slow, _ = tracker.burn(tenant, cfg.burn_slow_window_s, t=t)
            if (
                offered >= cfg.burn_min_requests
                and fast >= cfg.burn_fast_threshold
                and slow >= cfg.burn_slow_threshold
                and (worst is None or fast > worst[0])
            ):
                worst = (fast, slow, t)
        if worst is not None:
            fast, slow, t = worst
            findings.append(Finding(
                "slo_burn_rate",
                min(1.0, 0.6 + fast / (10.0 * cfg.burn_fast_threshold)),
                f"tenant {tenant!r} burned error budget at {fast:.1f}x "
                f"(5m) AND {slow:.1f}x (1h) peaking at t={t:.1f} in the "
                "recorded window",
                {
                    "tenant": tenant,
                    "burn_fast": round(fast, 3),
                    "burn_slow": round(slow, 3),
                    "t_peak": round(t, 3),
                },
            ))

    # -- tier_thrash (worst flapping window in the record) -------------
    checked.append("tier_thrash")
    events = _tier_move_events(series)
    best = _max_flap(events, cfg.tier_thrash_window_s)
    if best is not None and best[0] >= cfg.tier_thrash_min_cycles:
        cycles, demotes, promotes, shard = best
        findings.append(Finding(
            "tier_thrash",
            min(1.0, 0.4 + 0.1 * cycles),
            f"subtree shard {shard} flapped host<->disk {cycles}x "
            f"({demotes} demotes / {promotes} promotes) inside one "
            f"{cfg.tier_thrash_window_s:.0f}s window of the recorded "
            "history — the tier was paying a disk round trip per "
            "watermark crossing before the dump",
            {
                "shard": int(shard),
                "demotes": int(demotes),
                "promotes": int(promotes),
                "cycles": int(cycles),
                "window_s": cfg.tier_thrash_window_s,
            },
        ))

    findings.sort(
        key=lambda f: (-f.score, POSTMORTEM_RULES.index(f.rule))
    )
    first_t = None
    for pts in series.values():
        for p in pts:
            if first_t is None or p[1] < first_t:
                first_t = p[1]
    return {
        "source": "blackbox",
        "node": dump.get("node"),
        "unclean": bool(dump.get("unclean")),
        "findings": [f.as_dict() for f in findings],
        "healthy": not findings,
        "rules_checked": checked,
        "window": [
            round(first_t, 3) if first_t is not None else None,
            round(float(dump["last_t"]), 3)
            if dump.get("last_t") is not None
            else None,
        ],
        "samples": int(dump.get("last_seq", -1)) + 1,
        "series": len(series),
    }
