"""Observability: metrics registry + profiler tracing + the request-flight
tracing plane (SURVEY §5) + the fleet telemetry plane (gossiped node
digests, radix-tree convergence audit, health scoring) + the mesh-wide
plane (PR 9: cross-node trace stitching, per-shard heat/skew, TPU step
attribution) + the history axis (PR 13: bounded telemetry time-series
rings, crash-surviving black-box dumps, post-mortem doctoring)."""

from radixmesh_tpu.obs.blackbox import BlackBox, load_blackbox
from radixmesh_tpu.obs.fleet_plane import (
    FleetConfig,
    FleetPlane,
    FleetView,
    NodeDigest,
)
from radixmesh_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    set_registry,
)
from radixmesh_tpu.obs.step_plane import StepAccounting
from radixmesh_tpu.obs.timeseries import TelemetryHistory
from radixmesh_tpu.obs.trace_plane import (
    FlightRecorder,
    Span,
    TraceContext,
    configure,
    get_recorder,
    new_trace_id,
    set_recorder,
    stitch_traces,
    write_trace,
)
from radixmesh_tpu.obs.tracing import annotate, profile, recorded, timed

__all__ = [
    "FleetConfig",
    "FleetPlane",
    "FleetView",
    "NodeDigest",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "set_registry",
    "FlightRecorder",
    "Span",
    "TraceContext",
    "configure",
    "get_recorder",
    "set_recorder",
    "write_trace",
    "new_trace_id",
    "stitch_traces",
    "StepAccounting",
    "TelemetryHistory",
    "BlackBox",
    "load_blackbox",
    "annotate",
    "profile",
    "recorded",
    "timed",
]
