"""Observability: metrics registry + profiler tracing (SURVEY §5)."""

from radixmesh_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    set_registry,
)
from radixmesh_tpu.obs.tracing import annotate, profile, timed

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "set_registry",
    "annotate",
    "profile",
    "timed",
]
