"""Observability: metrics registry + profiler tracing + the request-flight
tracing plane (SURVEY §5)."""

from radixmesh_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    set_registry,
)
from radixmesh_tpu.obs.trace_plane import (
    FlightRecorder,
    Span,
    TraceContext,
    configure,
    get_recorder,
    set_recorder,
    write_trace,
)
from radixmesh_tpu.obs.tracing import annotate, profile, recorded, timed

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "set_registry",
    "FlightRecorder",
    "Span",
    "TraceContext",
    "configure",
    "get_recorder",
    "set_recorder",
    "write_trace",
    "annotate",
    "profile",
    "recorded",
    "timed",
]
