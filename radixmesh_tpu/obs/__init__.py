"""Observability: metrics registry + profiler tracing + the request-flight
tracing plane (SURVEY §5) + the fleet telemetry plane (gossiped node
digests, radix-tree convergence audit, health scoring)."""

from radixmesh_tpu.obs.fleet_plane import (
    FleetConfig,
    FleetPlane,
    FleetView,
    NodeDigest,
)
from radixmesh_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    set_registry,
)
from radixmesh_tpu.obs.trace_plane import (
    FlightRecorder,
    Span,
    TraceContext,
    configure,
    get_recorder,
    set_recorder,
    write_trace,
)
from radixmesh_tpu.obs.tracing import annotate, profile, recorded, timed

__all__ = [
    "FleetConfig",
    "FleetPlane",
    "FleetView",
    "NodeDigest",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "set_registry",
    "FlightRecorder",
    "Span",
    "TraceContext",
    "configure",
    "get_recorder",
    "set_recorder",
    "write_trace",
    "annotate",
    "profile",
    "recorded",
    "timed",
]
