"""Profiling helpers over ``jax.profiler`` (SURVEY §5: the reference has no
tracing at all — only rank-prefixed logging, ``util/log.py:5-13``).

Two levels:

- :func:`annotate` — named span inside an already-running trace; shows up
  on the TensorBoard/xplane timeline alongside XLA ops. No-op overhead when
  no trace is active.
- :func:`profile` — capture a full device+host trace of a block into a
  TensorBoard logdir.

Both degrade to no-ops if the profiler backend is unavailable (e.g. some
CPU-only CI images), so production code can annotate unconditionally.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

from radixmesh_tpu.obs.metrics import Histogram
from radixmesh_tpu.obs.trace_plane import get_recorder

__all__ = ["annotate", "profile", "timed", "recorded"]


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span on the profiler timeline (xplane TraceAnnotation)."""
    try:
        import jax.profiler as _prof

        cm = _prof.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler backend missing
        cm = contextlib.nullcontext()
    with cm:
        yield


@contextlib.contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture a device+host profiler trace of the block to ``log_dir``
    (view with TensorBoard's profile plugin)."""
    try:
        import jax.profiler as _prof

        cm = _prof.trace(log_dir)
    except Exception:  # pragma: no cover - profiler backend missing
        cm = contextlib.nullcontext()
    with cm:
        yield


@contextlib.contextmanager
def timed(hist: Histogram, name: str | None = None) -> Iterator[None]:
    """Observe the block's wall time into ``hist`` and, when a profiler
    trace is running, annotate the span with ``name``."""
    t0 = time.monotonic()
    with annotate(name or hist.name):
        try:
            yield
        finally:
            hist.observe(time.monotonic() - t0)


@contextlib.contextmanager
def recorded(lane: str, name: str, **args) -> Iterator[None]:
    """Both observability planes in one block: an xplane annotation for
    profiler captures AND a flight-recorder span (``obs/trace_plane.py``)
    on ``lane`` for the request-flight timeline. One branch when the
    recorder is disabled (it still annotates — that is already a no-op
    without a live profiler trace)."""
    rec = get_recorder()
    if not rec.enabled:
        with annotate(name):
            yield
        return
    t0 = time.monotonic()
    with annotate(name):
        try:
            yield
        finally:
            rec.event(lane, name, t0, time.monotonic() - t0, **args)
