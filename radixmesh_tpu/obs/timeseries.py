"""In-process telemetry history: bounded time-series rings over every plane.

Everything the observability stack built through PR 12 is point-in-time:
``/metrics`` is the counter value *now*, ``/cluster/telemetry`` is the
fold *now*, ``/debug/waterfall`` is the ring *now*. Nothing on a node can
answer "what did the skew score do over the last ten minutes" without an
external scraper — and when a node dies (routine, since the PR 7
recovery plane made crashes a latency blip) every gauge it held dies
with it. This module is the missing history axis:

- A :class:`TelemetryHistory` samples, at a fixed cadence (default 1 s),
  **every registered metric family** (one ``Registry.snapshot()`` — the
  same flat series a scraper sees) plus the derived planes a scrape
  can't reach: fleet-view health scores / digest ages / replication
  lags, the cluster shard-heat map + skew, step-plane MFU / pad
  fraction, and per-tenant SLO burn counters.
- Storage is **fixed-capacity, change-compressed rings**: one global
  sample sequence, one bounded deque of ``(seq, t, value)`` points per
  series appended ONLY when the value changed since its last point
  (delta encoding for the dominant case — most series are flat between
  events), so ~15 min of 1 s samples over hundreds of series stays in
  low single-digit MB. Series that vanish from the snapshot for a full
  window are pruned; series past the ``max_series`` cap are dropped and
  counted, never silently.
- ``GET /debug/timeseries?family=&since=&limit=`` (both frontends)
  serves the rings with **cursor pagination**: ``since`` is a sample
  sequence number, the response carries ``next_since`` + ``has_more``,
  and the limit cut lands on a sequence boundary so a paginating
  client never sees half a sample.
- **Self-accounting**: the sampler registers ``radixmesh_history_*``
  families for its own sample count / cost / ring size, so the
  history's overhead is itself visible in the history (the BLACKBOX
  acceptance artifact gates it under 1% of a step-accounting run).
- The doctor's burn-rate windows feed from here: every sample forwards
  the SLO burn counters into any bound
  :class:`~radixmesh_tpu.obs.doctor.BurnRateTracker`, so the 5 m / 1 h
  windows are dense regardless of how rarely anyone GETs
  ``/cluster/doctor`` (the PR 12 can't-judge gap).
- The black box (``obs/blackbox.py``) rides the ``on_sample`` hook to
  write crash-surviving incremental segments of these rings.

Import-light on purpose (stdlib only): router nodes, the black box
loader, and artifact tests use it without pulling in a backend. The
clock is injectable (virtual-time tests drive :meth:`sample` directly
without starting the thread).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time

from radixmesh_tpu.obs.metrics import (
    TRANSFER_SECONDS_BUCKETS,
    get_registry,
)
from radixmesh_tpu.utils.logging import get_logger, throttled

__all__ = ["TelemetryHistory", "DERIVED_PREFIXES", "BUCKET_FAMILIES"]

# Derived-source series namespaces (everything else in the rings is a
# registry family). Kept distinct from the ``radixmesh_`` scrape
# namespace on purpose: these are *readings of other planes' reports*
# (fleet fold, heat map, step accounting, SLO counters), not registered
# families — a collision would double-count a real series.
DERIVED_PREFIXES = ("fleet:", "shard:", "step:", "slo:", "goodput:")

# Histogram families sampled WITH their cumulative per-bucket counts
# (``Registry.snapshot(bucket_families=...)``): the per-tenant request
# latency distributions a fleet collector (obs/aggregator.py) merges
# bucket-by-bucket across nodes for true fleet percentiles. Opt-in and
# short on purpose — buckets multiply a family's series count ~16x, and
# change-compression only keeps that cheap for families whose buckets
# move at request cadence, not token cadence.
BUCKET_FAMILIES = (
    "radixmesh_request_ttft_seconds",
    "radixmesh_request_e2e_seconds",
    # Per-tenant inter-token latency (obs/token_timeline.py): token-
    # cadence observations, but the RING only pays per bucket-count
    # CHANGE per sample tick — steady decode moves one or two buckets
    # per second, the same cost profile as the request families under
    # load. Fleet ITL percentiles merge these in obs/aggregator.py.
    "radixmesh_token_itl_seconds",
)


class _Series:
    """One change-compressed ring: ``points`` holds (seq, t, value)
    appended only on value change; ``last_seen_seq`` tracks liveness
    (a series absent from the snapshot for a full window is pruned)."""

    __slots__ = ("points", "last_value", "last_seen_seq")

    def __init__(self, capacity: int):
        from collections import deque

        self.points: "deque[tuple[int, float, float]]" = deque(
            maxlen=capacity
        )
        self.last_value: float | None = None
        self.last_seen_seq = -1


class TelemetryHistory:
    """The sampler + rings. Every input is an optional duck-typed seam
    (the doctor convention):

    - ``mesh``: a MeshCache — fleet health scores / ages / lags, shard
      heat + skew.
    - ``engine``: an Engine — step-plane MFU / pad fraction (when step
      accounting is on).
    - ``slo``: an OverloadController — per-tenant admitted/shed burn
      counters (also forwarded to bound burn trackers).

    Construct one per frontend; :meth:`start` runs the sampler thread,
    or call :meth:`sample` directly (tests, virtual time)."""

    def __init__(
        self,
        interval_s: float = 1.0,
        capacity: int = 900,
        mesh=None,
        engine=None,
        slo=None,
        node: str = "",
        max_series: int = 4096,
        registry=None,
        now=time.monotonic,
        bucket_families: tuple = BUCKET_FAMILIES,
    ):
        if capacity <= 0:
            raise ValueError("history capacity must be positive")
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.mesh = mesh
        self.engine = engine
        self.slo = slo
        self.node = node
        self.max_series = int(max_series)
        self.bucket_families = tuple(bucket_families)
        self._registry = registry
        self._now = now
        # Monotonic→wall conversion for post-mortem readers (the
        # FlightRecorder convention): dumps carry it so crash windows
        # can be reported in operator time.
        self.wall_offset = time.time() - time.monotonic()
        self.log = get_logger("obs.timeseries")
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        self._seq = -1  # last completed sample sequence
        self._last_sample_t = 0.0
        self._dropped_series = 0
        # Names already counted as refused — the counter means "series
        # dropped", not "sample-writes refused", so a capped series
        # must not re-count on every subsequent tick.
        self._refused: set[str] = set()
        self._sample_seconds_total = 0.0  # this instance's own cost
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Post-sample hook (obs/blackbox.py installs its segment
        # writer): called with the completed sample's seq OUTSIDE the
        # ring lock, on the sampler thread.
        self.on_sample = None
        # Burn-rate sinks (obs/doctor.py): every sample forwards the
        # SLO burn counters here, so the doctor's windows are dense
        # regardless of diagnose() cadence.
        self._burn_sinks: list = []

        reg = registry if registry is not None else get_registry()
        self._m_samples = reg.counter(
            "radixmesh_history_samples_total",
            "telemetry-history samples taken (obs/timeseries.py)",
        )
        self._m_sample_seconds = reg.histogram(
            "radixmesh_history_sample_seconds",
            "wall cost of one telemetry-history sample sweep — the "
            "sampler's own overhead, self-accounted so the history's "
            "cost is visible in the history",
            buckets=TRANSFER_SECONDS_BUCKETS,
        )
        self._m_series = reg.gauge(
            "radixmesh_history_series",
            "live series rings held by the telemetry history",
        )
        self._m_points = reg.gauge(
            "radixmesh_history_points",
            "total retained points across all telemetry-history rings",
        )
        self._m_dropped = reg.counter(
            "radixmesh_history_dropped_series_total",
            "series refused because the history hit its max_series cap "
            "(no silent caps: a missing ring is a counted drop)",
        )

    # -- wiring --------------------------------------------------------

    def bind_burn_tracker(self, tracker) -> None:
        """Feed ``tracker.sample(burn_counts, t)`` at every history
        sample (the doctor binds its :class:`BurnRateTracker` here so
        its windows never depend on GET cadence)."""
        with self._lock:
            if tracker not in self._burn_sinks:
                self._burn_sinks.append(tracker)

    # -- the sample sweep ----------------------------------------------

    def sample(self, t: float | None = None) -> int:
        """Take one snapshot of every source into the rings; returns
        the completed sample's sequence number. Thread-safe (the
        sampler thread and a test driving virtual time may interleave;
        folds are serialized by the ring lock)."""
        t0 = time.monotonic()
        t = self._now() if t is None else float(t)
        snap: dict[str, float] = {}
        reg = self._registry if self._registry is not None else get_registry()
        snap.update(reg.snapshot(bucket_families=self.bucket_families))
        self._derived_snapshot(snap)
        burn_counts = None
        if self.slo is not None:
            try:
                burn_counts = self.slo.burn_counts()
            except Exception:  # noqa: BLE001 — a seam bug must not kill sampling
                burn_counts = None
            if burn_counts:
                for tenant, c in burn_counts.items():
                    snap[f'slo:admitted{{tenant="{tenant}"}}'] = float(
                        c.get("admitted", 0)
                    )
                    snap[f'slo:shed{{tenant="{tenant}"}}'] = float(
                        c.get("shed", 0)
                    )
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._last_sample_t = t
            dropped = 0
            for name, value in snap.items():
                s = self._series.get(name)
                if s is None:
                    if len(self._series) >= self.max_series:
                        if name not in self._refused:
                            self._refused.add(name)
                            dropped += 1
                        continue
                    self._refused.discard(name)
                    s = self._series[name] = _Series(self.capacity)
                s.last_seen_seq = seq
                if s.last_value is None or value != s.last_value:
                    s.points.append((seq, t, float(value)))
                    s.last_value = float(value)
            self._dropped_series += dropped
            # Prune series that vanished from the snapshot for a full
            # window (label churn must not grow the dict unboundedly).
            if seq % self.capacity == 0 and seq > 0:
                stale = [
                    n for n, s in self._series.items()
                    if s.last_seen_seq < seq - self.capacity
                ]
                for n in stale:
                    del self._series[n]
                # The refused ledger is bounded the same way: a name
                # still refused a full window later counts again.
                self._refused.clear()
            n_series = len(self._series)
            n_points = sum(len(s.points) for s in self._series.values())
            burn_sinks = list(self._burn_sinks)
        # Self-accounting + hooks outside the ring lock: metric family
        # locks and the black box's file IO must never nest inside it.
        if burn_counts:
            for sink in burn_sinks:
                sink.sample(burn_counts, t=t)
        cost = time.monotonic() - t0
        with self._lock:
            self._sample_seconds_total += cost
        self._m_samples.inc()
        self._m_sample_seconds.observe(cost)
        self._m_series.set(n_series)
        self._m_points.set(n_points)
        if dropped:
            self._m_dropped.inc(dropped)
        hook = self.on_sample
        if hook is not None:
            hook(seq)
        return seq

    def _derived_snapshot(self, snap: dict[str, float]) -> None:
        """Fold the non-registry planes into the sample. Each seam is
        crash-isolated: a broken plane loses its series, never the
        sample."""
        mesh = self.mesh
        if mesh is not None:
            try:
                fleet = mesh.fleet
                health = fleet.health()
                snap["fleet:alive_nodes"] = float(len(health))
                for rank, h in health.items():
                    snap[f'fleet:health_score{{rank="{rank}"}}'] = float(
                        h["score"]
                    )
                    snap[f'fleet:health_age_seconds{{rank="{rank}"}}'] = (
                        float(h["age_s"])
                    )
                for rank, d in fleet.digests().items():
                    snap[
                        f'fleet:replication_lag_seconds{{rank="{rank}"}}'
                    ] = float(d.replication_lag_s)
                    snap[
                        f'fleet:decode_ewma_seconds{{rank="{rank}"}}'
                    ] = float(getattr(d, "decode_ewma_s", 0.0))
            except Exception:  # noqa: BLE001 — seam isolation
                pass
            try:
                if getattr(mesh, "sharded", False):
                    heat = mesh.fleet.shard_heat()
                    snap["shard:skew_ratio"] = float(heat["skew_score"])
                    snap["shard:reporters"] = float(heat["reporters"])
                    for sid, load in heat["shards"].items():
                        snap[f'shard:heat{{shard="{sid}"}}'] = float(load)
            except Exception:  # noqa: BLE001 — seam isolation
                pass
        eng = self.engine
        acct = getattr(eng, "step_acct", None) if eng is not None else None
        if acct is not None:
            try:
                rep = acct.report()
                for kind in ("prefill", "decode"):
                    k = rep.get(kind)
                    if isinstance(k, dict):
                        snap[f'step:mfu{{kind="{kind}"}}'] = float(k["mfu"])
                        snap[f'step:pad_fraction{{kind="{kind}"}}'] = float(
                            k["pad_fraction"]
                        )
                        snap[f'step:waves{{kind="{kind}"}}'] = float(
                            k["waves"]
                        )
            except Exception:  # noqa: BLE001 — seam isolation
                pass
        gp = getattr(eng, "goodput", None) if eng is not None else None
        if gp is not None:
            try:
                rep = gp.report(
                    step_acct=acct, spec=getattr(eng, "spec_ledger", None)
                )
                snap["goodput:tokens_per_second"] = float(
                    rep["tokens_per_second"]
                )
                for tenant, t in rep["tenants"].items():
                    snap[
                        f'goodput:tokens_per_second{{tenant="{tenant}"}}'
                    ] = float(t["tokens_per_second"])
                    snap[
                        f'goodput:stall_seconds{{tenant="{tenant}"}}'
                    ] = float(t["stall_seconds"])
                for kind, frac in rep["waste"].items():
                    snap[f'goodput:waste_fraction{{kind="{kind}"}}'] = (
                        float(frac)
                    )
            except Exception:  # noqa: BLE001 — seam isolation
                pass

    # -- fleet ingest ---------------------------------------------------

    def ingest(self, node: str, body: dict) -> int:
        """Fold one ``/debug/timeseries`` page from a peer into these
        rings, node-labeled — the fleet aggregator's write path. The
        fold is cursor-agnostic: the caller (obs/aggregator.py) owns
        ``since``/``next_since`` bookkeeping; this method just stores
        whatever page it is handed.

        Semantics that keep the store a valid :class:`TelemetryHistory`:

        - **One store sequence per call.** Peer sequence numbers from
          different nodes are incomparable, so every point of the page
          lands under a single local seq — deques stay seq-ordered and
          :meth:`query` pagination cuts stay on whole-ingest boundaries.
        - **Peer time is rebased to this store's clock** via the page's
          ``wall_offset`` (``t + peer_wall_offset - self.wall_offset``),
          so a peer restart (monotonic reset) cannot reorder its points.
        - **Node labels are injected**, never trusted from the wire:
          ``fam{k="v"}`` becomes ``fam{k="v",node="peer"}``, so two
          peers' identical series never collide in one ring.
        - A series with no points in the page but a live ``last`` value
          is seeded once (change-compression: "no point" means "did not
          change", and a merge still needs its current value).
        """
        peer_offset = float(body.get("wall_offset", self.wall_offset))
        shift = peer_offset - self.wall_offset
        series = body.get("series", {})
        t_now = self._now()
        with self._lock:
            self._seq += 1
            seq = self._seq
            dropped = 0
            for name, sdata in series.items():
                if name.endswith("}"):
                    labeled = name[:-1] + f',node="{node}"' + "}"
                else:
                    labeled = f'{name}{{node="{node}"}}'
                s = self._series.get(labeled)
                if s is None:
                    if len(self._series) >= self.max_series:
                        if labeled not in self._refused:
                            self._refused.add(labeled)
                            dropped += 1
                        continue
                    self._refused.discard(labeled)
                    s = self._series[labeled] = _Series(self.capacity)
                s.last_seen_seq = seq
                pts = sdata.get("points") or ()
                for p in pts:
                    s.points.append((seq, float(p[1]) + shift, float(p[2])))
                if pts:
                    s.last_value = float(pts[-1][2])
                elif s.last_value is None:
                    last = sdata.get("last") or (None, None)
                    if last[1] is not None:
                        s.points.append((seq, t_now, float(last[1])))
                        s.last_value = float(last[1])
            self._dropped_series += dropped
        if dropped:
            self._m_dropped.inc(dropped)
        return seq

    # -- reads ---------------------------------------------------------

    def query(
        self,
        family: str | None = None,
        since: int = -1,
        limit: int = 2000,
    ) -> dict:
        """The ``GET /debug/timeseries`` body: every series whose name
        starts with ``family`` (None/"" = all), points with
        ``seq > since``, at most ``limit`` points — cut on a SAMPLE
        boundary (all points of a sequence ship together, so a
        paginating client never reads half a sample). ``next_since``
        is the cursor for the next page; ``has_more`` says whether one
        exists. Change-compressed semantics: a series with no point in
        range did not change — ``last`` carries its current value."""
        since = int(since)
        limit = max(1, int(limit))
        with self._lock:
            seq = self._seq
            matched: dict[str, _Series] = {
                n: s
                for n, s in self._series.items()
                if not family or n.startswith(family)
            }
            # Decide whether the limit can bind BEFORE materializing:
            # the dump()/segment path asks with an unbounded limit
            # every few samples, and building + sorting every retained
            # seq under the ring lock would stall the sampler tick
            # (and the watchdog heartbeat behind it) for nothing. The
            # O(series) deque-length bound clears that path without
            # touching a point; a genuinely bounded query then counts
            # with an early exit at limit+1, never a second full scan.
            cutoff = seq
            has_more = False
            over_limit = False
            # Eligible points (seq > since) are a SUFFIX of each
            # seq-ordered deque, so every scan below walks reversed()
            # and stops at the first pre-cursor point — a paginating
            # client's already-consumed prefix is never re-touched
            # under the ring lock (the sampler tick, and the watchdog
            # heartbeat behind it, sit on this lock).
            if sum(len(s.points) for s in matched.values()) > limit:
                total = 0
                for s in matched.values():
                    if not s.points or s.points[-1][0] <= since:
                        continue
                    for p in reversed(s.points):
                        if p[0] <= since:
                            break
                        total += 1
                        if total > limit:
                            over_limit = True
                            break
                    if over_limit:
                        break
            if over_limit:
                # Bounded selection, not a full sort: the cut only
                # needs the limit-th smallest eligible seq (a heap of
                # size limit), and "anything past the cut" only needs
                # each series' newest point — so a paginating client
                # never makes the lock hold O(P log P).
                cutoff = heapq.nsmallest(
                    limit,
                    (
                        p[0]
                        for s in matched.values()
                        for p in itertools.takewhile(
                            lambda p: p[0] > since, reversed(s.points)
                        )
                    ),
                )[-1]
                newest = max(
                    s.points[-1][0]
                    for s in matched.values()
                    if s.points and s.points[-1][0] > since
                )
                has_more = cutoff < seq and newest > cutoff
            series_out: dict[str, dict] = {}
            n_points = 0
            for name, s in matched.items():
                pts = []
                for p in reversed(s.points):
                    if p[0] <= since:
                        break
                    if p[0] <= cutoff:
                        pts.append([p[0], round(p[1], 6), p[2]])
                pts.reverse()
                n_points += len(pts)
                series_out[name] = {
                    "points": pts,
                    "last": [s.last_seen_seq, s.last_value],
                }
        return {
            "node": self.node,
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "wall_offset": round(self.wall_offset, 6),
            "seq": seq,
            "since": since,
            "next_since": cutoff,
            "has_more": has_more,
            "series": series_out,
            "points": n_points,
        }

    def dump(self, since: int = -1) -> dict:
        """Everything retained past ``since`` (no pagination) — the
        black box's segment/flush input."""
        return self.query(family=None, since=since, limit=1 << 62)

    def stats(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "capacity": self.capacity,
                "seq": self._seq,
                "series": len(self._series),
                "points": sum(
                    len(s.points) for s in self._series.values()
                ),
                "dropped_series": self._dropped_series,
                "last_sample_t": self._last_sample_t,
                # This instance's own cumulative sweep cost (the shared
                # radixmesh_history_sample_seconds histogram folds every
                # sampler in the process; the overhead gate needs THIS
                # one's).
                "sample_seconds_total": self._sample_seconds_total,
            }

    def last_sample_age_s(self, t: float | None = None) -> float:
        """Seconds since the last completed sample (inf before the
        first) — the black box watchdog's liveness signal."""
        t = self._now() if t is None else float(t)
        with self._lock:
            if self._seq < 0:
                return float("inf")
            return max(0.0, t - self._last_sample_t)

    # -- thread --------------------------------------------------------

    def start(self) -> "TelemetryHistory":
        if self.interval_s <= 0:
            raise ValueError("cannot start a sampler with interval <= 0")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="telemetry-history"
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — history must not kill the node
                # A repeatable failure here silently halts segment
                # writing and burn feeding while the heartbeat may look
                # live — it must at least be loud (throttled: the loop
                # retries every tick).
                if throttled(("history_sample_failed", id(self))):
                    self.log.exception("telemetry-history sample failed")
            self._stop.wait(self.interval_s)
