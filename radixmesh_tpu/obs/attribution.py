"""Critical-path latency attribution: span DAG → exclusive phase times.

PR 2/9 built the measurement substrate — every request's flight lands as
spans in the :class:`~radixmesh_tpu.obs.trace_plane.FlightRecorder`, and
PR 9 stitched them across nodes — but answering "where did this
request's 400 ms go" still meant a HUMAN reading a Perfetto timeline.
This module automates the reading (the Canopy move: per-request feature
extraction from traces, not raw span dumps):

- :func:`waterfall_from_spans` decomposes one request's end-to-end
  window into **exclusive** per-phase times that sum to e2e *exactly*
  (up to float addition): every instant of the window is attributed to
  the most-specific phase active at that instant (the critical-path
  rule — a decode chunk that overlaps its admission envelope is decode,
  not queueing), and instants no span covers land in the residual
  ``edge`` phase instead of vanishing. The phase taxonomy maps the
  span vocabulary the planes already record — SLO queue → admission →
  restore park → prefill waves → decode chunks → publish →
  replication/resurrection edges — so no call site changed to feed it.
- A :class:`PhaseAttributor` rides the recorder's span-retire hook:
  when a request's terminal span lands (``request_done`` from the
  engine's FINISHED funnel, or the frontend's ``http_request``
  envelope), the trace's buffered spans are decomposed and fed into
  ``radixmesh_request_phase_seconds{phase}`` histograms plus a bounded
  recent-waterfall ring and per-shape aggregates. Sampling off records
  no spans, so the whole plane costs exactly the PR 2 one-branch
  no-op; sampling on costs one O(trace spans) sweep per retired
  request.
- **No waterfalls from holed traces**: a trace that lost spans to the
  recorder's drop-oldest bound (``FlightRecorder.trace_has_drops``)
  is REFUSED — a decomposition with interior gaps would silently
  misattribute the missing intervals to ``edge`` — and the refusal is
  counted (``radixmesh_trace_waterfall_refusals_total``).
- ``GET /debug/waterfall`` (both frontends) serves :meth:`report`:
  the p50/p99 phase breakdown, the per-shape table the doctor's
  prefill-convoy rule consumes, and the recent per-request waterfalls.

Import-light on purpose (stdlib only): router nodes, the doctor, and
artifact tests use it without pulling in a backend.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field

from radixmesh_tpu.obs.metrics import PHASE_SECONDS_BUCKETS, get_registry
from radixmesh_tpu.obs.trace_plane import FlightRecorder, Span, get_recorder

__all__ = [
    "PHASES",
    "PHASE_OF_SPAN",
    "RESIDUAL_PHASE",
    "RETIRE_SPANS",
    "shape_bucket",
    "Waterfall",
    "waterfall_from_spans",
    "PhaseAttributor",
    "ensure_attributor",
]

# The residual phase: window instants no recorded span covers — frontend
# envelope, scheduler gaps between launches, response serialization.
# Present by construction so the decomposition SUMS to e2e instead of
# silently shrinking when instrumentation has gaps.
RESIDUAL_PHASE = "edge"

# Exclusive-attribution priority, most specific LAST-STAGE work first:
# when two phases' spans cover the same instant, the earlier entry wins.
# Compute phases (decode/prefill) beat the movement phase (restore
# park), which beats bookkeeping (publish) and mesh edges, which beat
# the queue envelopes that *contain* all of them — and of the two
# envelopes, slo_queue (the WFQ leg) beats admission (submit→row-
# secured, which CONTAINS the WFQ leg): the inner envelope is the more
# specific story, the outer one keeps only what nothing narrower
# explains.
PHASE_PRIORITY = (
    "decode",
    "prefill",
    "restore_park",
    "publish",
    "replication",
    "resurrection",
    "slo_queue",
    "admission",
)
PHASES = PHASE_PRIORITY + (RESIDUAL_PHASE,)

# Span-name → phase vocabulary (the names the planes already record —
# tests/test_metrics_lint.py pins the span vocabulary; adding a phase
# means adding it HERE and to the priority order above).
PHASE_OF_SPAN = {
    "slo_queue": "slo_queue",
    "slo_shed": "slo_queue",
    "admission_wait": "admission",
    "prefix_match": "admission",
    "kv_restore": "restore_park",
    "prefill_wave": "prefill",
    "decode_chunk": "decode",
    "publish": "publish",
    "mesh_publish": "replication",
    "replication_lag": "replication",
    "resurrect": "resurrection",
    "hedge": "resurrection",
}

# Terminal spans that retire a request's trace: the engine's FINISHED
# funnel records ``request_done`` (every finish path — stop token,
# cancel, shed, deadline — flows through Request.state=FINISHED), and
# the HTTP frontends record the wider ``http_request`` envelope after
# the response flushed. Histograms feed at the FIRST retire (the engine
# window, so phase sums are clock-consistent); a later envelope retire
# only widens the stored waterfall's residual edge.
RETIRE_SPANS = frozenset({"request_done", "http_request"})


def shape_bucket(prompt_tokens: int, floor: int = 32) -> str:
    """Pow2 prompt-length bucket label ("p128" = 65..128 tokens): the
    request-class key the per-shape aggregates, the doctor's convoy and
    spec-efficiency rules, and the engine's speculative counters share —
    one function so the buckets cannot drift between planes."""
    n = max(1, int(prompt_tokens))
    b = floor
    while b < n and b < 1 << 20:
        b <<= 1
    return f"p{b}"


@dataclass
class Waterfall:
    """One request's exclusive phase decomposition."""

    trace_id: int
    t0: float  # window start (monotonic, the retire span's t0)
    e2e_s: float  # window length == sum(phases.values()) up to float
    phases: dict[str, float]  # phase → exclusive seconds (all PHASES)
    retire: str  # which terminal span closed the window
    node: str = ""
    shape: str = ""  # prompt-length bucket ("" = unknown)
    prompt_tokens: int = 0
    output_tokens: int = 0
    span_count: int = 0
    args: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "trace_id": f"{self.trace_id:#018x}",
            "e2e_s": round(self.e2e_s, 6),
            "phases": {p: round(v, 6) for p, v in self.phases.items()},
            "retire": self.retire,
            "node": self.node,
            "shape": self.shape,
            "prompt_tokens": self.prompt_tokens,
            "output_tokens": self.output_tokens,
            "span_count": self.span_count,
        }


def waterfall_from_spans(spans: list[Span], retire: Span) -> Waterfall:
    """Decompose the retire span's window into exclusive phase times.

    The sweep: clip every phase-mapped span to the window, collect the
    interval endpoints, and attribute each elementary segment between
    consecutive endpoints to the highest-priority phase covering its
    midpoint (none → :data:`RESIDUAL_PHASE`). Exclusive by construction:
    each segment lands in exactly one phase, so the phase times sum to
    the window length — the property ``bench.validate_doctor`` gates on
    and ``tests/test_attribution.py`` proves over seeded traces."""
    lo = retire.t0
    hi = retire.t0 + max(0.0, retire.dur)
    prio = {p: i for i, p in enumerate(PHASE_PRIORITY)}
    ivals: list[tuple[float, float, int]] = []  # (start, end, priority)
    prompt_tokens = 0
    for s in spans:
        phase = PHASE_OF_SPAN.get(s.name)
        if s.name == "prefix_match" and s.args:
            prompt_tokens = int(s.args.get("prompt_tokens", 0)) or prompt_tokens
        if phase is None or s.name in RETIRE_SPANS:
            continue
        a, b = max(s.t0, lo), min(s.t0 + max(0.0, s.dur), hi)
        if b > a:
            ivals.append((a, b, prio[phase]))
    phases = {p: 0.0 for p in PHASES}
    # One sorted event sweep, not an all-intervals scan per segment:
    # this runs at retire time ON the engine scheduler thread, and a
    # long generation's trace holds thousands of decode_chunk/publish
    # spans — O(N²) midpoint scanning would stall scheduling for
    # milliseconds per retire. With only len(PHASE_PRIORITY) phases, an
    # active count per priority answers "most specific phase covering
    # this segment" in O(1); an interval [ia, ib) covers the elementary
    # segment [a, b) exactly when ia ≤ a and ib > a, the same membership
    # the midpoint test gives on elementary segments.
    npri = len(PHASE_PRIORITY)
    events: dict[float, list[int]] = {lo: [0] * npri, hi: [0] * npri}
    for ia, ib, pr in ivals:
        events.setdefault(ia, [0] * npri)[pr] += 1
        events.setdefault(ib, [0] * npri)[pr] -= 1
    active = [0] * npri
    points = sorted(events)
    for a, b in zip(points, points[1:]):
        for pr, d in enumerate(events[a]):
            active[pr] += d
        best = next((pr for pr in range(npri) if active[pr] > 0), None)
        phase = RESIDUAL_PHASE if best is None else PHASE_PRIORITY[best]
        phases[phase] += b - a
    args = dict(retire.args or {})
    prompt_tokens = int(args.get("prompt_tokens", prompt_tokens) or 0)
    return Waterfall(
        trace_id=retire.trace_id,
        t0=lo,
        e2e_s=hi - lo,
        phases=phases,
        retire=retire.name,
        node=retire.node,
        shape=shape_bucket(prompt_tokens) if prompt_tokens else "",
        prompt_tokens=prompt_tokens,
        output_tokens=int(args.get("output_tokens", 0) or 0),
        span_count=len(spans),
        args=args,
    )


class PhaseAttributor:
    """Retire-time aggregator: waterfalls → histograms + shape table.

    One instance per recorder (``ensure_attributor`` installs it on the
    retire hook). All state behind one short lock — retires come from
    the engine thread and HTTP handler threads concurrently.
    """

    FED_CAP = 4096  # trace ids remembered as histogram-fed (bounded)

    def __init__(self, recent: int = 256):
        reg = get_registry()
        hist = reg.histogram(
            "radixmesh_request_phase_seconds",
            "exclusive critical-path phase time per retired request "
            "(phases sum to end-to-end; obs/attribution.py)",
            ("phase",),
            buckets=PHASE_SECONDS_BUCKETS,
        )
        # Eager children: every phase series exists at 0 from install.
        self._hist = {p: hist.labels(phase=p) for p in PHASES}
        self._m_refused = reg.counter(
            "radixmesh_trace_waterfall_refusals_total",
            "waterfalls refused because the trace lost spans to the "
            "recorder ring bound (a holed decomposition would "
            "misattribute the missing intervals)",
            ("node",),
        )
        self._lock = threading.Lock()
        self._recent: deque[Waterfall] = deque(maxlen=recent)
        self._fed: deque[int] = deque(maxlen=self.FED_CAP)
        self._fed_set: set[int] = set()
        # shape → {count, e2e_s, phase sums}
        self._by_shape: dict[str, dict] = {}
        self.audited = 0  # waterfalls fed to the histograms
        self.refused = 0  # holed-trace refusals
        self.max_sum_error_s = 0.0  # |sum(phases) - e2e| high-water

    # -- the retire hook ----------------------------------------------

    def install(self, rec: FlightRecorder) -> "PhaseAttributor":
        rec.retire_hook = self.on_retire
        rec.retire_spans = RETIRE_SPANS
        rec.attributor = self
        return self

    def on_retire(self, span: Span, rec: FlightRecorder) -> None:
        tid = span.trace_id
        if not tid:
            return
        if rec.trace_has_drops(tid):
            # No silent caps: the refusal is the datum — but one per
            # TRACE, not per retire (a served request retires twice:
            # request_done, then the http_request envelope), so mark the
            # tid processed in the same ring the fed path uses.
            with self._lock:
                if tid in self._fed_set:
                    return
                if len(self._fed) == self._fed.maxlen:
                    self._fed_set.discard(self._fed[0])
                self._fed.append(tid)
                self._fed_set.add(tid)
                self.refused += 1
            self._m_refused.labels(node=span.node or rec.node or "node").inc()
            return
        wf = waterfall_from_spans(rec.spans_for_trace(tid), span)
        with self._lock:
            if tid not in self._fed_set:
                if len(self._fed) == self._fed.maxlen:
                    self._fed_set.discard(self._fed[0])
                self._fed.append(tid)
                self._fed_set.add(tid)
                self._feed_locked(wf)
            # A later, wider retire (http_request after request_done)
            # REPLACES the stored waterfall — the ring shows the full
            # envelope — but never double-feeds the histograms.
            for i, prev in enumerate(self._recent):
                if prev.trace_id == tid:
                    self._recent[i] = wf
                    break
            else:
                self._recent.append(wf)

    def _feed_locked(self, wf: Waterfall) -> None:
        for phase, secs in wf.phases.items():
            self._hist[phase].observe(secs)
        self.audited += 1
        err = abs(sum(wf.phases.values()) - wf.e2e_s)
        if err > self.max_sum_error_s:
            self.max_sum_error_s = err
        key = wf.shape or "unknown"
        agg = self._by_shape.setdefault(
            key, {"count": 0, "e2e_s": 0.0,
                  "phases": {p: 0.0 for p in PHASES}},
        )
        agg["count"] += 1
        agg["e2e_s"] += wf.e2e_s
        for phase, secs in wf.phases.items():
            agg["phases"][phase] += secs

    # -- reads ---------------------------------------------------------

    def by_shape(self) -> dict[str, dict]:
        """Per-shape totals (count, summed e2e, summed phase seconds) —
        the doctor's convoy-rule input."""
        with self._lock:
            return {
                k: {
                    "count": v["count"],
                    "e2e_s": v["e2e_s"],
                    "phases": dict(v["phases"]),
                }
                for k, v in self._by_shape.items()
            }

    def phase_hist(self, phase: str):
        """One phase's histogram child (count/sum/quantile reads) —
        the doctor's restore-park rule input; None for unknown phases."""
        return self._hist.get(phase)

    def phase_totals(self) -> dict[str, float]:
        """phase → summed exclusive seconds across audited requests."""
        return {p: h.sum for p, h in self._hist.items()}

    def stats(self) -> dict:
        with self._lock:
            return {
                "audited": self.audited,
                "refused": self.refused,
                "recent": len(self._recent),
                "max_sum_error_s": self.max_sum_error_s,
            }

    def report(self, recent: int = 32) -> dict:
        """The ``GET /debug/waterfall`` body: histogram-derived p50/p99
        per phase, per-shape mean breakdown, recent waterfalls."""
        with self._lock:
            recents = [wf.as_dict() for wf in list(self._recent)[-recent:]]
            shapes = {
                k: {
                    "count": v["count"],
                    "mean_e2e_s": round(v["e2e_s"] / max(1, v["count"]), 6),
                    "phase_share": {
                        p: round(s / v["e2e_s"], 4) if v["e2e_s"] > 0 else 0.0
                        for p, s in v["phases"].items()
                    },
                }
                for k, v in self._by_shape.items()
            }
            audited, refused = self.audited, self.refused
            max_err = self.max_sum_error_s
        return {
            "phases": {
                p: {
                    "count": h.count,
                    "p50_s": round(h.quantile(0.5), 6),
                    "p99_s": round(h.quantile(0.99), 6),
                    "sum_s": round(h.sum, 6),
                }
                for p, h in self._hist.items()
            },
            "by_shape": shapes,
            "recent": recents,
            "audited": audited,
            "refused": refused,
            "max_sum_error_s": max_err,
        }


def ensure_attributor(rec: FlightRecorder | None = None) -> PhaseAttributor:
    """The recorder's attributor, installing one if absent — the seam
    the frontends and the doctor resolve through, so a test-swapped
    recorder transparently gets a fresh attributor (and fresh metric
    children in the current registry)."""
    rec = rec if rec is not None else get_recorder()
    attr = getattr(rec, "attributor", None)
    if attr is None:
        attr = PhaseAttributor().install(rec)
    return attr
