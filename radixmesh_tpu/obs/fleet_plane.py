"""Fleet telemetry plane: gossiped node digests, convergence audit, health.

``obs/metrics.py`` answers "how is THIS node doing"; ``obs/trace_plane.py``
answers "where did THIS request's time go". Neither can answer the two
questions a master-free eventually-consistent mesh raises in production:
*are all replicas' radix trees actually converged*, and *which node is
sick* — the paper's consistency story is exactly the part that is
invisible at runtime. This module supplies the fleet-level counterpart:

- Each prefill/decode node periodically assembles a compact, fixed-size
  :class:`NodeDigest` (cache fill + hit rate, host-tier fill, engine
  batch occupancy, decode step-time EWMA, replication lag, SLO tier,
  membership epoch, and the tree's incrementally-maintained
  order-independent **fingerprint** — ``cache/radix_tree.py``) and
  piggybacks it on the existing oplog ring as an idempotent ``DIGEST``
  op (one frame per interval per node; no new connections, no
  wire-format break for old op kinds — ``cache/oplog.py``).
- Every node (the router included, via the master's fan-out) folds
  received digests into a :class:`FleetView`: comparing fingerprints
  across replicas yields a ``convergence_age_seconds`` per pair (how
  long two trees have disagreed), and per-node health scoring — a stall
  watchdog (batch nonempty but decode not progressing), a
  replication-lag threshold, and an eviction-storm detector — produces
  a 0..1 score the :class:`CacheAwareRouter` consumes behind
  ``--health-aware-routing`` to demote sick nodes.
- Both HTTP frontends surface the view as ``GET /cluster/health`` and
  ``GET /cluster/telemetry`` (``server/http_frontend.py``).

The digest is bounded-size **by construction**: a fixed struct layout
(:data:`DIGEST_BYTE_BUDGET` pins the ceiling; ``tests/test_fleet_plane.py``
lints it), so ring piggybacking stays one small frame regardless of tree
size — the fingerprint compresses the whole tree into 8 bytes.

Health-score formula (documented in ARCHITECTURE.md "Fleet health"):
start at 1.0, then take the MINIMUM over the fired detectors' caps —
stall → 0.0, stale digest → 0.2, replication lag over threshold → 0.3,
eviction storm → 0.6. Deterministic, monotone in badness, and each cap
names its reason so operators see *why* a node was demoted.

Import-light on purpose (stdlib + numpy only — no jax): router nodes
and artifact tests use it without pulling in a backend.
"""

from __future__ import annotations

import itertools
import struct
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from radixmesh_tpu.obs.metrics import get_registry
from radixmesh_tpu.policy.lifecycle import lifecycle_code, lifecycle_from_code
from radixmesh_tpu.utils.logging import get_logger

__all__ = [
    "EVICTION_CAUSES",
    "DIGEST_BYTE_BUDGET",
    "NodeDigest",
    "FleetConfig",
    "FleetView",
    "FleetPlane",
    "eviction_counters",
]

# Eviction causes every dashboard and the storm detector distinguish:
# pressure (capacity / preempt — the pool is too small for the traffic)
# vs policy (ttl expiry / mesh replica trim — deliberate bounds).
EVICTION_CAUSES = ("capacity", "ttl", "preempt", "mesh_trim")
# Causes that count toward the eviction-storm detector (policy evictions
# are expected at steady state; pressure evictions at a sustained rate
# mean the node is thrashing its cache).
_STORM_CAUSES = ("capacity", "preempt")


def eviction_counters(node: str):
    """Per-cause eviction counter children for one node/engine label —
    the single registration point, so the family's label schema cannot
    drift between the engine (capacity/preempt) and the mesh replica
    (ttl/mesh_trim). All four children materialize eagerly so the series
    exist at 0 from process start (dashboards never see gaps)."""
    fam = get_registry().counter(
        "radixmesh_cache_evicted_tokens_total",
        "KV tokens evicted from the radix cache, by cause (capacity/"
        "preempt = pool pressure; ttl/mesh_trim = policy bounds)",
        ("node", "cause"),
    )
    return {c: fam.labels(node=node, cause=c) for c in EVICTION_CAUSES}


# ---------------------------------------------------------------------------
# NodeDigest: the fixed-layout gossip payload
# ---------------------------------------------------------------------------

# v2: the tier byte's high nibble carries the membership-lifecycle code
# (policy/lifecycle.py) — same layout, new INTERPRETATION of that byte.
# The version bump exists for the rolling-upgrade window: a v1 decoder
# reading a v2 digest would misparse BOOTSTRAPPING as slo_tier=16, so it
# must reject-and-log (its version check does) rather than misread;
# v2 decoders still accept v1 digests (full-byte tier, lifecycle
# "active" — factually what a pre-lifecycle node is in).
_DIGEST_VERSION = 2
# magic+version+role+tier, rank, epoch, waiting, seq, decode_steps,
# ts, fingerprint, tree_tokens, 5 floats, 4 eviction counters.
_DIGEST_FMT = "<BBBBiiiqqdQq5f4q"
# Hard ceiling on the serialized digest (lint-enforced): ring
# piggybacking must stay one small frame per interval per node.
DIGEST_BYTE_BUDGET = 160
_DIGEST_MAGIC = 0xFD

_ROLE_CODES = {"prefill": 0, "decode": 1, "router": 2}
_ROLE_NAMES = {v: k for k, v in _ROLE_CODES.items()}


@dataclass
class NodeDigest:
    """One node's periodic self-description, compact enough to ride the
    oplog ring every interval. All rates/fills are instantaneous reads;
    monotone counters (``decode_steps``, ``evictions``) let receivers
    derive progress/rates from consecutive digests."""

    rank: int
    role: str  # "prefill" | "decode" | "router"
    seq: int  # per-node monotonic digest number (newest-wins fold)
    ts: float  # origin wall clock (skew degrades ages, not correctness)
    epoch: int  # membership view epoch at assembly time
    fingerprint: int  # radix-tree fingerprint (cache/radix_tree.py)
    tree_tokens: int  # evictable + protected tokens in the mesh replica
    cache_hit_rate: float  # engine lifetime hit rate, 0..1
    pool_fill: float  # 1 - free/total device KV slots, 0..1
    host_fill: float  # host-tier fill, 0..1 (0 when no host tier)
    batch_occupancy: float  # active rows / max_batch, 0..1
    decode_ewma_s: float  # decode step-time EWMA (seconds/token)
    waiting: int  # queued requests
    decode_steps: int  # lifetime decode steps (stall-watchdog progress)
    replication_lag_s: float = 0.0  # recent oplog origin→apply lag EWMA
    slo_tier: int = 0  # graceful-degradation tier (0 = normal)
    evictions: tuple[int, int, int, int] = (0, 0, 0, 0)  # per EVICTION_CAUSES
    # The origin's publish cadence: receivers size their staleness window
    # from it (a router must not mark a 60s-interval fleet stale at 15s).
    interval_s: float = 0.0
    # Membership lifecycle state (policy/lifecycle.py): the router
    # withholds cache-hit routing from "bootstrapping" nodes and all new
    # work from "draining"/"left" ones. Travels in the HIGH NIBBLE of
    # the existing tier byte (tiers are 0-3, lifecycle codes are 0-3) —
    # same layout and size, but a v2 digest version so a pre-lifecycle
    # decoder rejects-and-logs instead of misreading the nibble as
    # slo_tier=16/32 during a rolling upgrade (v1 digests still decode
    # here: full-byte tier, lifecycle "active").
    lifecycle: str = "active"

    def encode(self) -> np.ndarray:
        """Pack into an int32 array — the shape the oplog wire already
        carries (``Oplog.value``), so digests ride existing frames."""
        raw = struct.pack(
            _DIGEST_FMT,
            _DIGEST_MAGIC,
            _DIGEST_VERSION,
            _ROLE_CODES.get(self.role, 2),
            (lifecycle_code(self.lifecycle) << 4) | (self.slo_tier & 0x0F),
            self.rank,
            self.epoch,
            self.waiting,
            self.seq,
            self.decode_steps,
            self.ts,
            self.fingerprint & ((1 << 64) - 1),
            self.tree_tokens,
            self.cache_hit_rate,
            self.pool_fill,
            self.host_fill,
            self.batch_occupancy,
            self.decode_ewma_s,
            *(int(e) for e in self.evictions),
        )
        # replication_lag_s + interval_s travel as a float32 tail (kept
        # out of the fixed prefix so the format string stays one struct).
        raw += struct.pack("<ff", self.replication_lag_s, self.interval_s)
        pad = (-len(raw)) % 4
        return np.frombuffer(raw + b"\x00" * pad, dtype=np.int32).copy()

    @classmethod
    def decode(cls, arr: np.ndarray) -> "NodeDigest":
        raw = np.ascontiguousarray(np.asarray(arr, dtype=np.int32)).tobytes()
        base = struct.calcsize(_DIGEST_FMT)
        if len(raw) < base + 8:
            raise ValueError(f"digest payload too short ({len(raw)} bytes)")
        (
            magic, version, role_code, tier, rank, epoch, waiting, seq,
            decode_steps, ts, fingerprint, tree_tokens, hit_rate, pool_fill,
            host_fill, batch_occ, decode_ewma, ev0, ev1, ev2, ev3,
        ) = struct.unpack_from(_DIGEST_FMT, raw, 0)
        if magic != _DIGEST_MAGIC:
            raise ValueError(f"bad digest magic {magic:#x}")
        if version not in (1, _DIGEST_VERSION):
            raise ValueError(f"unsupported digest version {version}")
        if version == 1:
            # Pre-lifecycle digest: the whole byte is the tier, and the
            # node factually has no lifecycle machinery → "active".
            slo_tier, lifecycle = tier, "active"
        else:
            slo_tier, lifecycle = tier & 0x0F, lifecycle_from_code(tier >> 4)
        lag, interval = struct.unpack_from("<ff", raw, base)
        return cls(
            rank=rank,
            role=_ROLE_NAMES.get(role_code, "router"),
            seq=seq,
            ts=ts,
            epoch=epoch,
            fingerprint=fingerprint,
            tree_tokens=tree_tokens,
            cache_hit_rate=hit_rate,
            pool_fill=pool_fill,
            host_fill=host_fill,
            batch_occupancy=batch_occ,
            decode_ewma_s=decode_ewma,
            waiting=waiting,
            decode_steps=decode_steps,
            replication_lag_s=lag,
            slo_tier=slo_tier,
            evictions=(ev0, ev1, ev2, ev3),
            interval_s=interval,
            lifecycle=lifecycle,
        )

    def encoded_size(self) -> int:
        return int(self.encode().nbytes)

    def as_dict(self) -> dict:
        return {
            "rank": self.rank,
            "role": self.role,
            "seq": self.seq,
            "ts": self.ts,
            "epoch": self.epoch,
            "fingerprint": f"{self.fingerprint & ((1 << 64) - 1):016x}",
            "tree_tokens": self.tree_tokens,
            "cache_hit_rate": round(self.cache_hit_rate, 4),
            "pool_fill": round(self.pool_fill, 4),
            "host_fill": round(self.host_fill, 4),
            "batch_occupancy": round(self.batch_occupancy, 4),
            "decode_ewma_s": round(self.decode_ewma_s, 6),
            "waiting": self.waiting,
            "decode_steps": self.decode_steps,
            "replication_lag_s": round(self.replication_lag_s, 6),
            "slo_tier": self.slo_tier,
            "evictions": dict(zip(EVICTION_CAUSES, self.evictions)),
            "interval_s": round(self.interval_s, 3),
            "lifecycle": self.lifecycle,
        }


# ---------------------------------------------------------------------------
# FleetView: digests folded into convergence + health state
# ---------------------------------------------------------------------------


@dataclass
class FleetConfig:
    """Detector thresholds. ``stale_after_s`` defaults to 3 digest
    intervals at :class:`FleetPlane` construction time."""

    interval_s: float = 5.0
    stale_after_s: float | None = None
    lag_threshold_s: float = 5.0
    eviction_storm_tokens_per_s: float = 50_000.0

    @property
    def effective_stale_after_s(self) -> float:
        if self.stale_after_s is not None:
            return self.stale_after_s
        return 3.0 * self.interval_s


class FleetView:
    """Per-node latest digests + derived convergence/health state.

    Folds happen on mesh transport-reader threads; reads come from HTTP
    handler threads and the router's hot path — all state is guarded by
    one short lock (folds are O(nodes), reads are O(nodes²) over a
    handful of nodes)."""

    def __init__(self, cfg: FleetConfig | None = None, now=time.time):
        self.cfg = cfg or FleetConfig()
        self._now = now
        self._lock = threading.Lock()
        self._digests: dict[int, NodeDigest] = {}
        self._prev: dict[int, NodeDigest] = {}  # previous distinct digest
        self._stalled: dict[int, bool] = {}
        self._storm_rate: dict[int, float] = {}  # pressure-evict tokens/s
        # (lo, hi) rank pair → wall time their fingerprints were first
        # seen unequal; absent = currently equal (or a side unknown).
        self._diverged_at: dict[tuple[int, int], float] = {}
        # Per-rank per-shard fingerprints (prefix-ownership sharding,
        # cache/sharding.py): folded whole-summary-at-a-time from
        # SHARD_SUMMARY gossip. Under sharding whole-tree fingerprints
        # diverge BY DESIGN, so convergence auditing compares these —
        # per shard, across the ranks that own (and therefore report)
        # it — instead of the scalar digest fingerprint.
        self._shard_fps: dict[int, dict[int, int]] = {}
        # (sid, lo rank, hi rank) → wall time the pair's fingerprints
        # for that shard were first seen unequal.
        self._shard_diverged_at: dict[tuple[int, int, int], float] = {}
        # Per-rank per-shard decayed load (tokens/s) from the heat
        # trailer on SHARD_SUMMARY gossip (cache/sharding.py::ShardHeat)
        # — the cluster heat map + skew score the future rebalancer
        # consumes (PR 9 observability).
        self._shard_heat: dict[int, dict[int, float]] = {}
        # Per-rank wall-clock skew estimate: min over recent folds of
        # (local wall at fold - digest origin ts). The minimum tracks
        # (skew + fastest observed transit), so it over-estimates skew
        # by at most the best one-way gossip latency — good enough to
        # align trace timelines (obs/trace_plane.py::stitch_traces);
        # never used for correctness.
        self._clock_skew: dict[int, float] = {}
        # Ranks that announced a PLANNED departure (LEAVE oplog): their
        # straggler digests are refused so a frozen fingerprint cannot
        # re-enter the convergence audit or pin min_score after the
        # membership dropped them. A rejoiner's fresh digests (state
        # bootstrapping/active) clear the mark.
        self._left: set[int] = set()
        self.folds = 0  # digests accepted (lifetime)

    # -- fold ----------------------------------------------------------

    def fold(self, d: NodeDigest) -> bool:
        """Fold one digest; newest-by-(ts, seq) wins per rank (idempotent
        — ring re-delivery of an already-seen digest is a no-op). The
        wall clock leads the ordering: a restarted node's seq counter
        resets to 1, and seq-first comparison would reject its fresh
        digests until seq caught up to the pre-crash value — reading a
        healthy rebooted node as stale/sick for hours. seq breaks ties
        within one origin's clock tick. Returns True when the digest
        advanced the view."""
        now = self._now()
        with self._lock:
            if d.rank in self._left:
                if d.lifecycle in ("draining", "left"):
                    # A straggler from a departed node (the LEAVE beat
                    # its final data-lane digests): refuse the fold.
                    return False
                # Fresh state from a rejoiner: the mark is stale.
                self._left.discard(d.rank)
            cur = self._digests.get(d.rank)
            if cur is not None and (d.ts, d.seq) <= (cur.ts, cur.seq):
                return False
            if cur is not None:
                self._prev[d.rank] = cur
            self._digests[d.rank] = d
            self.folds += 1
            self._update_detectors(d, self._prev.get(d.rank))
            self._update_divergence(d, now)
            if d.ts:
                skew = now - d.ts
                prev_skew = self._clock_skew.get(d.rank)
                self._clock_skew[d.rank] = (
                    skew if prev_skew is None else min(prev_skew, skew)
                )
            return True

    def _update_detectors(self, d: NodeDigest, prev: NodeDigest | None) -> None:
        if prev is None or d.ts <= prev.ts:
            return
        # Stall watchdog: two consecutive digests with a nonempty batch
        # and ZERO decode progress between them — the engine is wedged
        # (device hang, scheduler deadlock), not merely idle.
        self._stalled[d.rank] = (
            d.batch_occupancy > 0.0
            and prev.batch_occupancy > 0.0
            and d.decode_steps == prev.decode_steps
        )
        dt = d.ts - prev.ts
        pressure = sum(
            d.evictions[i] - prev.evictions[i]
            for i, c in enumerate(EVICTION_CAUSES)
            if c in _STORM_CAUSES
        )
        self._storm_rate[d.rank] = max(0.0, pressure) / dt

    def _update_divergence(self, d: NodeDigest, now: float) -> None:
        for other_rank, other in self._digests.items():
            if other_rank == d.rank:
                continue
            pair = (min(d.rank, other_rank), max(d.rank, other_rank))
            if d.fingerprint == other.fingerprint:
                self._diverged_at.pop(pair, None)
            else:
                self._diverged_at.setdefault(pair, now)

    def retain(self, ranks) -> None:
        """Forget every rank not in ``ranks`` — called on membership view
        changes so a decommissioned node's last digest cannot pin
        ``min_score`` at the stale cap and its frozen fingerprint cannot
        hold convergence pairs diverged forever. A rank that rejoins
        simply folds fresh digests again."""
        keep = set(ranks)
        with self._lock:
            known = (
                set(self._digests)
                | set(self._shard_fps)
                | set(self._shard_heat)
            )
            for r in [r for r in known if r not in keep]:
                self._forget_locked(r)

    def forget(self, rank: int) -> None:
        """Drop ONE rank's state — the single-rank mirror of
        :meth:`retain`, called when a peer announces a planned LEAVE
        (``policy/lifecycle.py``). Beyond what the view-change retain
        would eventually do, forgetting on the LEAVE itself guarantees a
        later REJOIN starts from a clean slate: the old replication-lag
        EWMA, stall flag, storm rate, and fingerprint all die with the
        departure instead of being inherited by the reincarnation."""
        with self._lock:
            self._forget_locked(rank)

    def _forget_locked(self, rank: int) -> None:
        for store in (self._digests, self._prev, self._stalled,
                      self._storm_rate, self._shard_fps,
                      self._shard_heat, self._clock_skew):
            store.pop(rank, None)
        for pair in [p for p in self._diverged_at if rank in p]:
            del self._diverged_at[pair]
        for key in [k for k in self._shard_diverged_at if rank in k[1:]]:
            del self._shard_diverged_at[key]

    def mark_left(self, rank: int) -> None:
        """Record a planned departure: ``lifecycle_of`` answers "left"
        (the router refuses the node new work even if a stale view still
        lists it) and straggler digests are refused (see ``fold``)."""
        with self._lock:
            self._left.add(rank)

    # -- reads ---------------------------------------------------------

    def digests(self) -> dict[int, NodeDigest]:
        with self._lock:
            return dict(self._digests)

    def fingerprints(self) -> dict[int, int]:
        """rank → last-gossiped tree fingerprint: the anti-entropy
        repair plane's scan input (one lock hold, no digest copies —
        the scan runs every repair interval on every node)."""
        with self._lock:
            return {r: d.fingerprint for r, d in self._digests.items()}

    def fold_shard_fps(self, rank: int, fps: dict[int, int]) -> None:
        """Fold one rank's per-owned-shard fingerprints (whole-summary
        swap — a summary always carries the rank's complete owned set,
        so stale shard entries cannot linger after an ownership change).
        Updates the per-shard divergence clocks against every other
        reporter of the same shard."""
        now = self._now()
        mask = (1 << 64) - 1
        fps = {int(s): int(f) & mask for s, f in fps.items()}
        with self._lock:
            self._shard_fps[rank] = fps
            for other_rank, other in self._shard_fps.items():
                if other_rank == rank:
                    continue
                lo, hi = min(rank, other_rank), max(rank, other_rank)
                for sid in set(fps) | set(other):
                    key = (sid, lo, hi)
                    a, b = fps.get(sid), other.get(sid)
                    if a is None or b is None or a == b:
                        # Not co-reported (owners report only owned
                        # shards, so co-reporting ⇔ co-ownership) or
                        # agreeing: the pair is not diverged on it.
                        self._shard_diverged_at.pop(key, None)
                    else:
                        self._shard_diverged_at.setdefault(key, now)

    def shard_fps(self, rank: int) -> dict[int, int]:
        """One rank's last-summarized shard fingerprints ({} = none
        seen) — the repair plane's owner-scoped scan input."""
        with self._lock:
            return dict(self._shard_fps.get(rank, {}))

    def shard_fingerprints(self) -> dict[int, dict[int, int]]:
        with self._lock:
            return {r: dict(f) for r, f in self._shard_fps.items()}

    def shard_convergence(self) -> dict:
        """Owner-scoped convergence audit (the sharded counterpart of
        :meth:`convergence`): a pair of ranks is compared ONLY on shards
        both report (= both own); ``converged`` means no co-reported
        shard currently disagrees anywhere in the fleet."""
        now = self._now()
        with self._lock:
            diverged = {}
            for (sid, a, b), since in self._shard_diverged_at.items():
                diverged[f"s{sid}:{a}-{b}"] = max(0.0, now - since)
            reporters = len(self._shard_fps)
        max_age = max(diverged.values(), default=0.0)
        return {
            "diverged": diverged,
            "max_convergence_age_s": round(max_age, 3),
            "converged": not diverged,
            "reporters": reporters,
        }

    def fold_shard_heat(self, rank: int, loads: dict[int, float]) -> None:
        """Fold one rank's per-owned-shard decayed loads (whole-summary
        swap, like :meth:`fold_shard_fps` — stale shard entries cannot
        linger past an ownership change). Empty folds CLEAR the rank
        (an owner reporting no traffic is cold, not unknown)."""
        with self._lock:
            if loads:
                self._shard_heat[rank] = {
                    int(s): max(0.0, float(v)) for s, v in loads.items()
                }
            else:
                self._shard_heat.pop(rank, None)

    def shard_heat(self) -> dict:
        """The cluster heat map + skew score.

        Per-shard fleet load = MAX over reporting owners (co-owners see
        the same inserts, so max — not sum — avoids counting one
        insert RF times; pull-through copies on non-owners never report,
        by construction). ``skew_score`` = max/mean over reported
        shards — the load-imbalance trigger the future shard rebalancer
        gates on (ROADMAP item 1's named follow-up); 1.0 = perfectly
        flat, >> 1 = one shard soaking the fleet."""
        with self._lock:
            by_rank = {r: dict(h) for r, h in self._shard_heat.items()}
        shards: dict[int, float] = {}
        for h in by_rank.values():
            for sid, load in h.items():
                shards[sid] = max(shards.get(sid, 0.0), load)
        skew = 0.0
        hot_shard = None
        if shards:
            mean = sum(shards.values()) / len(shards)
            hot_shard = max(shards, key=shards.get)
            skew = (shards[hot_shard] / mean) if mean > 0 else 0.0
        return {
            "shards": {str(s): round(v, 4) for s, v in sorted(shards.items())},
            "by_rank": {
                str(r): {str(s): round(v, 4) for s, v in sorted(h.items())}
                for r, h in sorted(by_rank.items())
            },
            "skew_score": round(skew, 4),
            "hot_shard": hot_shard,
            "reporters": len(by_rank),
        }

    def clock_offsets(self) -> dict[int, float]:
        """rank → estimated wall-clock skew seconds (min-tracked digest
        transit; see the ``_clock_skew`` comment). The stitcher's
        per-node correction input — telemetry-grade, never used for
        correctness."""
        with self._lock:
            return dict(self._clock_skew)

    def lifecycle_of(self, rank: int) -> str:
        """One rank's gossiped membership-lifecycle state ("active" for
        unknown ranks — normal routing is the safe default)."""
        with self._lock:
            if rank in self._left:
                return "left"
            d = self._digests.get(rank)
            return d.lifecycle if d is not None else "active"

    def lifecycles(self) -> dict[int, str]:
        """rank → lifecycle state, one lock hold (the router's per-route
        withhold/exclude computation)."""
        with self._lock:
            out = {r: d.lifecycle for r, d in self._digests.items()}
            for r in self._left:
                out[r] = "left"
            return out

    def diverged_with(self, rank: int) -> dict[int, float]:
        """Peers currently fingerprint-diverged from ``rank``, with
        seconds since each pair was first seen unequal — the per-node
        slice of :meth:`convergence` a repair operator (or /debug
        tooling) asks for when ONE node is under suspicion."""
        now = self._now()
        out: dict[int, float] = {}
        with self._lock:
            for (a, b), since in self._diverged_at.items():
                if rank == a:
                    out[b] = max(0.0, now - since)
                elif rank == b:
                    out[a] = max(0.0, now - since)
        return out

    def convergence(self) -> dict:
        """Pairwise ``convergence_age_seconds``: 0.0 for agreeing pairs,
        else seconds since their fingerprints were first seen unequal."""
        now = self._now()
        diverged = 0
        with self._lock:
            ranks = sorted(self._digests)
            pairs = {}
            for i, a in enumerate(ranks):
                for b in ranks[i + 1:]:
                    since = self._diverged_at.get((a, b))
                    if since is None:
                        pairs[f"{a}-{b}"] = 0.0
                    else:
                        diverged += 1
                        pairs[f"{a}-{b}"] = max(0.0, now - since)
        max_age = max(pairs.values(), default=0.0)
        return {
            "pairs": pairs,
            "max_convergence_age_s": round(max_age, 3),
            # "Converged" = no pair currently disagrees — NOT age == 0
            # (a pair that diverged this instant has age 0 but is not
            # converged).
            "converged": diverged == 0,
        }

    def health(self) -> dict[int, dict]:
        """Per-rank health: {"score": 0..1, "reasons": [...], "age_s": ...}.
        See the module docstring for the score formula."""
        now = self._now()
        out: dict[int, dict] = {}
        with self._lock:
            for rank, d in self._digests.items():
                score, reasons = 1.0, []
                age = max(0.0, now - d.ts)
                if self._stalled.get(rank):
                    score, reasons = 0.0, reasons + ["stall"]
                # Staleness window: the larger of this view's config and
                # 3× the ORIGIN's own advertised cadence — a router with
                # default config must not mark a slow-cadence fleet stale.
                stale_after = max(
                    self.cfg.effective_stale_after_s, 3.0 * d.interval_s
                )
                if age > stale_after:
                    score = min(score, 0.2)
                    reasons.append("stale_digest")
                if d.replication_lag_s > self.cfg.lag_threshold_s:
                    score = min(score, 0.3)
                    reasons.append("replication_lag")
                if (
                    self._storm_rate.get(rank, 0.0)
                    > self.cfg.eviction_storm_tokens_per_s
                ):
                    score = min(score, 0.6)
                    reasons.append("eviction_storm")
                out[rank] = {
                    "score": round(score, 3),
                    "reasons": reasons,
                    "age_s": round(age, 3),
                    "role": d.role,
                    "lifecycle": d.lifecycle,
                }
        return out

    def health_score(self, rank: int) -> float:
        """One rank's score; 1.0 for unknown ranks (no digest yet — a
        booting fleet must not read as universally sick)."""
        with self._lock:
            if rank not in self._digests:
                return 1.0
        return self.health().get(rank, {"score": 1.0})["score"]

    def sick_ranks(self, threshold: float) -> set[int]:
        """Ranks scoring below ``threshold`` — ONE health computation for
        the router's per-request demotion checks (per-address
        health_score calls would rebuild the full dict per candidate)."""
        return {
            r for r, h in self.health().items() if h["score"] < threshold
        }

    def snapshot(self) -> dict:
        """The ``/cluster/telemetry`` body."""
        digs = self.digests()
        out = {
            "nodes": {str(r): d.as_dict() for r, d in sorted(digs.items())},
            "convergence": self.convergence(),
            "folds": self.folds,
        }
        with self._lock:
            sharded = bool(self._shard_fps)
            heated = bool(self._shard_heat)
        if sharded:
            # Under sharding the scalar audit reads diverged by design;
            # the owner-scoped one is the meaningful signal.
            out["shard_convergence"] = self.shard_convergence()
        if heated:
            out["shard_heat"] = self.shard_heat()
        return out


# ---------------------------------------------------------------------------
# FleetPlane: the per-node digester thread
# ---------------------------------------------------------------------------


class FleetPlane:
    """Assembles this node's :class:`NodeDigest` every ``interval_s`` and
    hands it to ``MeshCache.broadcast_digest`` (which folds it locally and
    rings it — ONE oplog frame per interval). ``engine`` and ``slo`` are
    optional seams: cache-only nodes publish mesh-only digests; serving
    nodes add engine occupancy/latency and the SLO tier."""

    def __init__(
        self,
        mesh,
        engine=None,
        slo=None,
        interval_s: float = 5.0,
        cfg: FleetConfig | None = None,
    ):
        self.mesh = mesh
        self.engine = engine
        self.slo = slo  # OverloadController (or anything with ._tier)
        self.cfg = cfg or FleetConfig(interval_s=interval_s)
        self.cfg.interval_s = interval_s
        # The node's view adopts this plane's thresholds so /cluster/health
        # and the router see the detectors the operator configured.
        mesh.fleet.cfg = self.cfg
        self._seq = itertools.count(1)
        self.published = 0  # digests originated (== ring frames spent)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.log = get_logger(f"fleet.{mesh._node_label}")

    # -- digest assembly ----------------------------------------------

    def build_digest(self) -> NodeDigest:
        mesh = self.mesh
        tree = mesh.tree
        eng = self.engine
        tel = eng.telemetry() if eng is not None else {}
        ev = tel.get("evictions", {})
        mesh_ev = mesh.eviction_totals()
        evictions = tuple(
            int(ev.get(c, 0)) + int(mesh_ev.get(c, 0)) for c in EVICTION_CAUSES
        )
        tier = 0
        if self.slo is not None:
            tier = int(getattr(self.slo, "_tier", 0))
        # Membership lifecycle (policy/lifecycle.py): the plane, when one
        # is attached to the mesh, is the single source of truth — this
        # is a READ; only policy/lifecycle.py ever assigns the state.
        lc = getattr(mesh, "lifecycle", None)
        lifecycle = lc.state.value if lc is not None else "active"
        return NodeDigest(
            rank=mesh.rank,
            role=mesh.role.value,
            seq=next(self._seq),
            ts=time.time(),
            epoch=mesh.view.epoch,
            fingerprint=tree.fingerprint_,
            tree_tokens=tree.evictable_size_ + tree.protected_size_,
            cache_hit_rate=float(tel.get("cache_hit_rate", 0.0)),
            pool_fill=float(tel.get("pool_fill", 0.0)),
            host_fill=float(tel.get("host_fill", 0.0)),
            batch_occupancy=float(tel.get("batch_occupancy", 0.0)),
            decode_ewma_s=float(tel.get("decode_ewma_s", 0.0)),
            waiting=int(tel.get("waiting", 0)),
            decode_steps=int(tel.get("decode_steps", 0)),
            replication_lag_s=float(mesh.lag_ewma_s),
            slo_tier=tier,
            evictions=evictions,
            interval_s=self.cfg.interval_s,
            lifecycle=lifecycle,
        )

    def publish_once(self) -> NodeDigest:
        """One assemble+broadcast cycle (tests and the bench drive this
        directly; the thread just calls it on a timer)."""
        d = self.build_digest()
        self.mesh.broadcast_digest(d)
        self.published += 1
        return d

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "FleetPlane":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet-digester"
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.publish_once()
            except Exception:  # noqa: BLE001 — telemetry must not kill the node
                self.log.exception("digest publish failed")
            self._stop.wait(self.cfg.interval_s)
