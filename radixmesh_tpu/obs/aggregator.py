"""Fleet-wide telemetry aggregation: one store, every node's rings.

Everything observability built through PR 13 answers for ONE node:
``/debug/timeseries`` is this node's rings, ``/cluster/doctor`` judges
this node's seams, a black box dumps this node's history. At the N=200
scale ringscale already simulates, an operator (or the ROADMAP item-2
autoscale executor) must poll 200 endpoints and merge by hand — and the
one merge that matters most, latency percentiles, is exactly the one
hand-merging gets wrong (an average of per-node p99s is not the fleet
p99; it is not ANY quantile of anything). This module is the control
room:

- A :class:`FleetAggregator`, hosted on router/front-door nodes,
  **cursor-pulls** each peer's change-compressed history ring — the
  existing ``/debug/timeseries`` ``since``/``next_since`` pagination is
  the wire protocol (:class:`HttpPeer`), with a direct in-proc seam for
  tests and workloads (:class:`InprocPeer`) — and folds every page into
  one node-labeled fleet :class:`~radixmesh_tpu.obs.timeseries.TelemetryHistory`
  via :meth:`TelemetryHistory.ingest`. ``GET /cluster/timeseries``
  serves the fleet store with the same query/pagination contract as the
  per-node endpoint, so every existing reader works unchanged.
- **Correct cross-node percentiles**: per-node samplers ship their
  request-latency histograms WITH cumulative bucket counts
  (``timeseries.BUCKET_FAMILIES``); :meth:`FleetAggregator.fleet_slo`
  sums the counts bucket-by-bucket across nodes and interpolates the
  quantile inside the merged distribution (:func:`merge_quantile` —
  the same cumulative interpolation ``Histogram.quantile`` uses), so
  ``/cluster/slo`` reports the TRUE fleet p50/p99 TTFT/e2e per tenant.
- **Trace exemplars**: each pull sweep also collects the peers' last
  per-bucket exemplars (``Histogram.observe(value, trace_id=)``), so
  the merged p99's bucket links straight to a PR 9 stitched trace —
  "the fleet p99 is 1.2 s" comes with the trace id of a request that
  actually took that long, and which node it ran on.
- **Fleet doctor inputs**: the per-rank signal folds
  (:meth:`rank_signal`), per-peer pull/advance bookkeeping
  (:meth:`peer_status`), and an aggregated multi-window burn tracker
  with slope (:meth:`fleet_burn_report`) feed the three MeshDoctor
  rules only a cross-node view can judge: ``straggler_node``,
  ``fleet_burn_slope``, and ``telemetry_gap`` (obs/doctor.py).

Restart safety: peer sample sequences are per-boot. A pull whose
``seq`` is BELOW the cursor means the peer restarted (prior-boot ring
gone) — the cursor resets to -1 and the new boot's ring is re-pulled
from its start. Nothing double-counts: the old boot's points are
already folded under their ingest sequences, and the new boot starts
its own. Counted, never silent (``radixmesh_agg_peer_resets_total``).

Import-light on purpose (stdlib only): router nodes host this without
a backend; HTTP transport is urllib against the existing debug
endpoints, so any node that serves ``/debug/timeseries`` is already a
valid peer.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque

from radixmesh_tpu.obs.metrics import (
    TRANSFER_SECONDS_BUCKETS,
    get_registry,
)
from radixmesh_tpu.obs.timeseries import TelemetryHistory
from radixmesh_tpu.utils.logging import get_logger, throttled

__all__ = [
    "FleetAggregator",
    "InprocPeer",
    "HttpPeer",
    "merge_quantile",
    "merge_bucket_counts",
]


def _parse_labels(name: str) -> dict[str, str]:
    """Label dict off a rendered series name
    (``family{k="v",k2="v2"}``); {} when unlabeled/malformed."""
    i = name.find("{")
    if i < 0 or not name.endswith("}"):
        return {}
    out: dict[str, str] = {}
    for part in name[i + 1 : -1].split(","):
        if "=" not in part:
            continue
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip().strip('"')
    return out


def _le_to_float(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def merge_bucket_counts(
    per_node: "list[dict[str, float]]",
) -> tuple[tuple[float, ...], list[float]]:
    """Sum per-node cumulative bucket vectors (``le`` string → count)
    into one merged ``(bounds, cumulative counts)`` pair. Cumulative
    counts are additive across independent streams, so the merged
    vector is exactly the histogram of the union stream — THE operation
    averaging-of-percentiles gets wrong."""
    les: set[str] = set()
    for d in per_node:
        les.update(d)
    bounds = sorted((_le_to_float(le) for le in les))
    merged = []
    for b in bounds:
        le = "+Inf" if b == float("inf") else None
        total = 0.0
        for d in per_node:
            for k, v in d.items():
                if (le is not None and k == le) or (
                    le is None and _le_to_float(k) == b
                ):
                    total += v
        merged.append(total)
    return tuple(b for b in bounds if b != float("inf")), merged


def merge_quantile(
    bounds: "tuple[float, ...]", cumulative: "list[float]", q: float
) -> tuple[float, str | None]:
    """(quantile estimate, bucket ``le`` string) from a merged
    cumulative bucket vector — the same linear-interpolation-inside-
    the-selected-bucket estimate ``Histogram.quantile`` computes from
    its own counts, so a single-node fleet answers identically to the
    node itself. The returned ``le`` is the selected bucket's upper
    bound as a label string (``"+Inf"`` for the overflow bucket) — the
    join key into the exemplar map."""
    if not cumulative:
        return 0.0, None
    total = cumulative[-1]
    if total <= 0:
        return 0.0, None
    target = q * total
    acc = 0.0
    for i, ub in enumerate(bounds):
        in_bucket = cumulative[i] - (cumulative[i - 1] if i else 0.0)
        if acc + in_bucket >= target and in_bucket > 0:
            if ub == float("inf"):
                # No finite upper edge to interpolate toward: report
                # the largest finite bound (the Histogram.quantile
                # convention) but join exemplars in the +Inf bucket,
                # where the observations actually landed.
                return (bounds[i - 1] if i > 0 else 0.0), "+Inf"
            lo = bounds[i - 1] if i > 0 else min(0.0, ub)
            est = lo + (ub - lo) * (target - acc) / in_bucket
            return est, _fmt_le(ub)
        acc += in_bucket
    # Target falls in the +Inf bucket: report the largest finite bound
    # (the Histogram.quantile convention) and join exemplars there.
    return (bounds[-1] if bounds else float("inf")), "+Inf"


def _fmt_le(v: float) -> str:
    """The exact ``le`` label string the exposition layer renders for a
    bound (obs/metrics.py ``_fmt_value``) — merged-quantile bucket ids
    must join against peer exemplar keys byte-for-byte."""
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


# ---------------------------------------------------------------------------
# peers: where a ring comes from
# ---------------------------------------------------------------------------


class InprocPeer:
    """A peer whose ring lives in this process: direct
    :meth:`TelemetryHistory.query` calls, exemplars straight off the
    registry. The seam tests/workloads drive (no sockets), and the
    N=200 fan-in row's simulated transport."""

    def __init__(self, name: str, history, registry=None, rank=None):
        self.name = str(name)
        self.history = history
        self.registry = registry
        self.rank = rank

    def fetch(self, since: int, limit: int) -> dict:
        return self.history.query(since=since, limit=limit)

    def fetch_exemplars(self) -> dict:
        reg = self.registry
        return reg.exemplars() if reg is not None else {}


class HttpPeer:
    """A peer reached over the existing debug endpoints: the ring via
    ``GET /debug/timeseries`` (the pagination contract IS the wire
    protocol), exemplars via the ``exemplars`` section of
    ``GET /debug/state``. Any frontend since PR 13 is a valid peer with
    zero server-side changes."""

    def __init__(self, name: str, base_url: str, timeout_s: float = 2.0,
                 rank=None):
        self.name = str(name)
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.rank = rank

    def _get(self, path: str) -> dict:
        with urllib.request.urlopen(
            f"{self.base_url}{path}", timeout=self.timeout_s
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def fetch(self, since: int, limit: int) -> dict:
        return self._get(f"/debug/timeseries?since={int(since)}&limit={int(limit)}")

    def fetch_exemplars(self) -> dict:
        return self._get("/debug/state").get("exemplars", {})


class _PeerState:
    """Per-peer pull bookkeeping (cursor + liveness), all under the
    aggregator lock."""

    __slots__ = (
        "cursor", "seq", "interval_s", "last_advance_t", "last_ok_t",
        "errors", "resets", "pages",
    )

    def __init__(self):
        self.cursor = -1  # next_since to pull from (-1 = ring start)
        self.seq = -1  # peer's last reported sample sequence
        self.interval_s = 1.0  # peer's reported sampler cadence
        self.last_advance_t = 0.0  # store clock when seq last advanced
        self.last_ok_t = 0.0  # store clock of the last successful pull
        self.errors = 0
        self.resets = 0
        self.pages = 0


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------


class FleetAggregator:
    """The collector. Construct with the peer list (mixed
    :class:`InprocPeer`/:class:`HttpPeer`), :meth:`start` the puller
    thread (or drive :meth:`pull_once` directly — tests, virtual time),
    read ``.store`` (a node-labeled :class:`TelemetryHistory`, ingest-
    only, never sampled) for ``/cluster/timeseries`` and
    :meth:`fleet_slo` for ``/cluster/slo``."""

    def __init__(
        self,
        peers=(),
        interval_s: float = 2.0,
        capacity: int = 900,
        node: str = "fleet",
        max_series: int = 16384,
        registry=None,
        now=time.monotonic,
        page_limit: int = 4000,
        max_pages: int = 64,
        burn_budget: float = 0.01,
    ):
        self.interval_s = float(interval_s)
        self.node = node
        self.page_limit = int(page_limit)
        # Bounded pages per peer per sweep: a peer with a deeper backlog
        # finishes over the next sweeps — fan-in latency stays bounded
        # even when one ring is a full capacity behind.
        self.max_pages = int(max_pages)
        self._now = now
        self.log = get_logger("obs.aggregator")
        # The fleet store: ingest-only (never start()ed — its sample()
        # path would re-sample THIS process's registry, which is not
        # fleet data). Same query surface as any per-node history.
        self.store = TelemetryHistory(
            interval_s=interval_s,
            capacity=capacity,
            node=node,
            max_series=max_series,
            registry=registry,
            now=now,
            bucket_families=(),
        )
        self._lock = threading.Lock()
        self._peers: list = list(peers)
        self._state: dict[str, _PeerState] = {
            p.name: _PeerState() for p in self._peers
        }
        # peer name → registry-keyed exemplar map from its last sweep.
        self._exemplars: dict[str, dict] = {}
        # Aggregated multi-window burn over fleet-summed SLO counters,
        # fed once per sweep; per-tenant (t, fast-burn) trail for the
        # slope the item-2 autoscaler pre-scale signal needs.
        self.burn_tracker = None  # lazily built: avoids import cycle
        self._burn_budget = float(burn_budget)
        self._burn_trail: dict[str, deque] = {}
        self._pull_seconds_total = 0.0
        self._sweeps = 0
        self._last_sweep_t = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        reg = registry if registry is not None else get_registry()
        self._m_pulls = reg.counter(
            "radixmesh_agg_pulls_total",
            "per-peer pull attempts by the fleet aggregator "
            "(obs/aggregator.py)",
        )
        self._m_errors = reg.counter(
            "radixmesh_agg_pull_errors_total",
            "fleet-aggregator pulls that raised (peer down, timeout, "
            "bad body) — the puller retries next sweep",
        )
        self._m_points = reg.counter(
            "radixmesh_agg_points_ingested_total",
            "ring points folded into the fleet store across all peers",
        )
        self._m_resets = reg.counter(
            "radixmesh_agg_peer_resets_total",
            "peer restarts detected by the cursor (reported seq below "
            "the cursor): the cursor rewinds to the new boot's ring "
            "start — counted, never silent",
        )
        self._m_peers = reg.gauge(
            "radixmesh_agg_peers",
            "peers the fleet aggregator is polling",
        )
        self._m_pull_seconds = reg.histogram(
            "radixmesh_agg_pull_seconds",
            "wall cost of one full pull sweep over every peer — the "
            "aggregation-overhead gate input (AGG artifact: < 1% of "
            "run wall time)",
            buckets=TRANSFER_SECONDS_BUCKETS,
        )
        self._m_fleet_nodes = reg.gauge(
            "radixmesh_fleet_nodes",
            "peers with a live ring as of the last sweep (seq advanced "
            "within one gap threshold)",
        )

    # -- wiring --------------------------------------------------------

    def add_peer(self, peer) -> None:
        with self._lock:
            self._peers.append(peer)
            self._state.setdefault(peer.name, _PeerState())

    def peers(self) -> list:
        with self._lock:
            return list(self._peers)

    def _ensure_burn_tracker(self):
        if self.burn_tracker is None:
            from radixmesh_tpu.obs.doctor import BurnRateTracker

            self.burn_tracker = BurnRateTracker(
                self._burn_budget, now=self._now
            )
        return self.burn_tracker

    # -- the pull sweep ------------------------------------------------

    def pull_once(self) -> dict:
        """One sweep: pull every peer's new pages, fold them into the
        fleet store, refresh exemplars + burn windows. Returns the
        sweep summary (the workload's fan-in row input)."""
        t0 = time.monotonic()
        peers = self.peers()
        self._m_peers.set(len(peers))
        points = 0
        errors = 0
        for peer in peers:
            with self._lock:  # add_peer mutates the map concurrently
                st = self._state[peer.name]
            self._m_pulls.inc()
            try:
                reset_seen = False
                for _ in range(self.max_pages):
                    body = peer.fetch(since=st.cursor, limit=self.page_limit)
                    seq = int(body.get("seq", -1))
                    if seq < st.cursor and not reset_seen:
                        # The peer's ring restarted under the cursor
                        # (prior-boot dir rotated away): rewind and
                        # re-pull the new boot's ring from its start.
                        # One rewind per sweep — a peer that reports a
                        # still-lower seq twice is malformed, not
                        # restarting, and must not loop.
                        with self._lock:
                            st.cursor = -1
                            st.seq = -1
                            st.resets += 1
                        self._m_resets.inc()
                        reset_seen = True
                        continue
                    self.store.ingest(peer.name, body)
                    n = int(body.get("points", 0))
                    points += n
                    if n:
                        self._m_points.inc(n)
                    now = self._now()
                    with self._lock:
                        st.pages += 1
                        st.interval_s = float(body.get("interval_s", 1.0))
                        st.cursor = int(body.get("next_since", seq))
                        st.last_ok_t = now
                        if seq > st.seq:
                            st.seq = seq
                            st.last_advance_t = now
                    if not body.get("has_more"):
                        break
                try:
                    ex = peer.fetch_exemplars()
                except Exception:  # noqa: BLE001 — exemplars are best-effort garnish
                    ex = None
                if ex is not None:
                    with self._lock:
                        self._exemplars[peer.name] = ex
            except Exception:  # noqa: BLE001 — a dead peer must not kill the sweep
                errors += 1
                with self._lock:
                    st.errors += 1
                self._m_errors.inc()
                if throttled(("agg_pull_failed", peer.name)):
                    self.log.exception(
                        "fleet pull from peer %r failed", peer.name
                    )
        self._feed_burn()
        now = self._now()
        with self._lock:
            live = sum(
                1
                for st in self._state.values()
                if st.seq >= 0
                and now - st.last_advance_t <= self._gap_threshold(st)
            )
        self._m_fleet_nodes.set(live)
        cost = time.monotonic() - t0
        with self._lock:
            self._pull_seconds_total += cost
            self._sweeps += 1
            self._last_sweep_t = now
        self._m_pull_seconds.observe(cost)
        return {
            "peers": len(peers),
            "errors": errors,
            "points": points,
            "duration_s": cost,
        }

    def _gap_threshold(self, st: _PeerState) -> float:
        """How long a peer's seq may sit still before it counts as
        stalled: several sampler intervals (change-compression never
        stops seq advancing — a live sampler bumps seq every tick even
        when no series changed) plus several pull cadences (the
        aggregator only observes advances when it pulls)."""
        return 3.0 * max(st.interval_s, self.interval_s) + st.interval_s

    def _feed_burn(self) -> None:
        """Sum the per-node ``slo:admitted``/``slo:shed`` counters per
        tenant out of the fleet store and feed the aggregate burn
        tracker; extend each tenant's fast-burn trail for the slope."""
        sums: dict[str, dict[str, float]] = {}
        for kind in ("admitted", "shed"):
            q = self.store.query(family=f"slo:{kind}", limit=1)
            for name, s in q["series"].items():
                tenant = _parse_labels(name).get("tenant")
                if tenant is None or s["last"][1] is None:
                    continue
                c = sums.setdefault(tenant, {"admitted": 0, "shed": 0})
                c[kind] += s["last"][1]
        if not sums:
            return
        tracker = self._ensure_burn_tracker()
        t = self._now()
        tracker.sample(
            {
                tenant: {"admitted": int(c["admitted"]), "shed": int(c["shed"])}
                for tenant, c in sums.items()
            },
            t=t,
        )
        with self._lock:
            for tenant in sums:
                fast, _ = tracker.burn(tenant, 300.0, t=t)
                self._burn_trail.setdefault(
                    tenant, deque(maxlen=512)
                ).append((t, fast))

    # -- fleet reads ---------------------------------------------------

    def fleet_slo(self, quantiles=(0.5, 0.99)) -> dict:
        """The ``GET /cluster/slo`` body: per tenant, the TRUE fleet
        quantiles of TTFT, e2e, and inter-token latency — bucket counts
        summed across nodes, quantile interpolated inside the merged
        distribution — each with the exemplar (trace id + node) of its
        selected bucket, plus the per-tenant speculation acceptance
        panel folded from the ``radixmesh_spec_*`` families."""
        out: dict[str, dict] = {}
        for metric, family in (
            ("ttft", "radixmesh_request_ttft_seconds"),
            ("e2e", "radixmesh_request_e2e_seconds"),
            ("itl", "radixmesh_token_itl_seconds"),
        ):
            q = self.store.query(family=family + "_bucket", limit=1)
            # (tenant, node) → {le: cumulative count}
            per: dict[str, dict[str, dict[str, float]]] = {}
            for name, s in q["series"].items():
                labels = _parse_labels(name)
                le = labels.get("le")
                tenant = labels.get("tenant", "default")
                node = labels.get("node", "?")
                if le is None or s["last"][1] is None:
                    continue
                per.setdefault(tenant, {}).setdefault(node, {})[le] = float(
                    s["last"][1]
                )
            for tenant, by_node in per.items():
                bounds, cum = merge_bucket_counts(list(by_node.values()))
                ent = out.setdefault(tenant, {})[metric] = {
                    "count": int(cum[-1]) if cum else 0,
                    "nodes": sorted(by_node),
                }
                for qq in quantiles:
                    est, le = merge_quantile(
                        bounds + (float("inf"),), cum, qq
                    )
                    key = f"p{int(qq * 100)}"
                    ent[key] = round(est, 6)
                    ent[f"{key}_bucket"] = le
                    ex = self._find_exemplar(family, tenant, le, bounds)
                    if ex is not None:
                        ent[f"{key}_exemplar"] = ex
        self._fold_spec_panel(out)
        with self._lock:
            last_sweep = self._last_sweep_t
        return {
            "node": self.node,
            "tenants": out,
            "peers": self.peer_status(),
            "last_sweep_t": round(last_sweep, 6),
        }

    def _fold_spec_panel(self, out: dict[str, dict]) -> None:
        """Per-tenant speculation acceptance across the fleet (PR 18's
        token-speed plane): for every (tenant, shape, draft-source)
        class, the freshest acceptance EWMA and γ-used per node plus
        proposed/accepted totals SUMMED across nodes — so
        ``/cluster/slo`` answers "is speculation paying for tenant X"
        without a per-node walk. Classes land under
        ``tenants[t]["spec"]["classes"]["shape/source"]``."""
        # (tenant, shape, source) → {"ewma": (seq, val), sums…}
        cells: dict[tuple[str, str, str], dict] = {}

        def _fold(family: str, key: str, freshest: bool):
            q = self.store.query(family=family, limit=1)
            for name, s in q["series"].items():
                labels = _parse_labels(name)
                tenant = labels.get("tenant")
                shape = labels.get("shape")
                source = labels.get("source")
                last = s.get("last")
                if tenant is None or shape is None or last is None:
                    continue
                seq, val = last
                if val is None:
                    continue
                cell = cells.setdefault(
                    (tenant, shape, source or "?"), {}
                )
                if freshest:
                    prev = cell.get(key)
                    if prev is None or seq > prev[0]:
                        cell[key] = (seq, float(val))
                else:
                    cell[key] = cell.get(key, 0.0) + float(val)

        _fold("radixmesh_spec_accept_ratio", "ewma", freshest=True)
        _fold("radixmesh_spec_gamma_used_tokens", "gamma", freshest=True)
        _fold("radixmesh_spec_proposed_tokens_total", "proposed", freshest=False)
        _fold("radixmesh_spec_accepted_tokens_total", "accepted", freshest=False)
        for (tenant, shape, source), cell in sorted(cells.items()):
            panel = out.setdefault(tenant, {}).setdefault(
                "spec", {"classes": {}}
            )
            proposed = cell.get("proposed", 0.0)
            accepted = cell.get("accepted", 0.0)
            panel["classes"][f"{shape}/{source}"] = {
                "accept_ewma": (
                    round(cell["ewma"][1], 4) if "ewma" in cell else None
                ),
                "gamma_tokens": (
                    cell["gamma"][1] if "gamma" in cell else None
                ),
                "proposed": int(proposed),
                "accepted": int(accepted),
            }
        # One headline rate per tenant: acceptance weighted by proposal
        # volume (an EWMA mean would overweight idle classes).
        for tenant, sigs in out.items():
            panel = sigs.get("spec")
            if not panel:
                continue
            p = sum(c["proposed"] for c in panel["classes"].values())
            a = sum(c["accepted"] for c in panel["classes"].values())
            panel["proposed"] = p
            panel["accepted"] = a
            panel["accept_rate"] = round(a / p, 4) if p else None

    def _find_exemplar(
        self, family: str, tenant: str, le: str | None, bounds
    ) -> dict | None:
        """The freshest peer exemplar in the quantile's bucket — or, if
        that bucket holds none (exemplars keep only the LAST traced
        observation per bucket), in any bucket above it: an outlier
        past the quantile is still an honest witness for it."""
        if le is None:
            return None
        floor = _le_to_float(le)
        with self._lock:
            by_peer = {p: dict(ex) for p, ex in self._exemplars.items()}
        best = None
        for peer, series in by_peer.items():
            for key, buckets in series.items():
                if not key.startswith(family + "{"):
                    continue
                if _parse_labels(key).get("tenant") != tenant:
                    continue
                for b_le, ex in buckets.items():
                    if _le_to_float(b_le) < floor:
                        continue
                    cand = (float(ex.get("wall_time", 0.0)), peer, b_le, ex)
                    if best is None or cand[0] > best[0]:
                        best = cand
        if best is None:
            return None
        _, peer, b_le, ex = best
        return {**ex, "node": peer, "le": b_le}

    def rank_signal(self, family: str) -> dict[str, float]:
        """Freshest per-rank value of a rank-labeled fleet series (e.g.
        ``fleet:decode_ewma_seconds``) across every reporting node —
        the straggler rule's input. Multiple nodes gossip a view of the
        same rank; the most recently ingested one wins."""
        q = self.store.query(family=family, limit=1)
        best: dict[str, tuple[int, float]] = {}
        for name, s in q["series"].items():
            rank = _parse_labels(name).get("rank")
            seen, val = s["last"]
            if rank is None or val is None:
                continue
            if rank not in best or seen > best[rank][0]:
                best[rank] = (seen, float(val))
        return {rank: v for rank, (_, v) in sorted(best.items())}

    def peer_status(self, t: float | None = None) -> dict[str, dict]:
        """Per-peer pull/advance bookkeeping — the ``telemetry_gap``
        rule's input and the ``/cluster/slo`` liveness section."""
        t = self._now() if t is None else float(t)
        out = {}
        with self._lock:
            for peer in self._peers:
                st = self._state[peer.name]
                out[peer.name] = {
                    "rank": getattr(peer, "rank", None),
                    "seq": st.seq,
                    "cursor": st.cursor,
                    "interval_s": st.interval_s,
                    "errors": st.errors,
                    "resets": st.resets,
                    "stalled_s": round(t - st.last_advance_t, 6)
                    if st.seq >= 0
                    else None,
                    "gap_threshold_s": round(self._gap_threshold(st), 6),
                }
        return out

    def fleet_burn_report(
        self,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        slope_window_s: float = 60.0,
    ) -> dict[str, dict]:
        """Per-tenant aggregated burn over the fleet-summed counters:
        fast/slow window multiples plus the fast-burn SLOPE over the
        trailing trail — rising burn is the pre-scale signal ROADMAP
        item 2 acts on before either page threshold trips."""
        tracker = self.burn_tracker
        if tracker is None:
            return {}
        t = self._now()
        out: dict[str, dict] = {}
        with self._lock:
            trails = {k: list(v) for k, v in self._burn_trail.items()}
        for tenant in tracker.tenants():
            fast, offered = tracker.burn(tenant, fast_window_s, t=t)
            slow, _ = tracker.burn(tenant, slow_window_s, t=t)
            trail = [
                p for p in trails.get(tenant, []) if p[0] >= t - slope_window_s
            ]
            slope = 0.0
            if len(trail) >= 2 and trail[-1][0] > trail[0][0]:
                slope = (trail[-1][1] - trail[0][1]) / (
                    trail[-1][0] - trail[0][0]
                )
            out[tenant] = {
                "burn_fast": round(fast, 4),
                "burn_slow": round(slow, 4),
                "offered": offered,
                "slope_per_s": round(slope, 6),
                "budget": self._burn_budget,
            }
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "peers": len(self._peers),
                "sweeps": self._sweeps,
                "last_sweep_t": self._last_sweep_t,
                # This instance's own cumulative sweep cost — the AGG
                # artifact's < 1% overhead gate input (the shared
                # radixmesh_agg_pull_seconds histogram folds every
                # aggregator in the process).
                "pull_seconds_total": self._pull_seconds_total,
                "store": self.store.stats(),
            }

    # -- thread --------------------------------------------------------

    def start(self) -> "FleetAggregator":
        if self.interval_s <= 0:
            raise ValueError("cannot start a puller with interval <= 0")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet-aggregator"
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.pull_once()
            except Exception:  # noqa: BLE001 — the control room must not kill the router
                if throttled(("agg_sweep_failed", id(self))):
                    self.log.exception("fleet aggregation sweep failed")
            self._stop.wait(self.interval_s)
