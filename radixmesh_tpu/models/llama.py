"""Llama-3-family transformer, pure functional JAX (no flax/nnx).

The reference is cache-only — "There is no model, no attention kernel, no
scheduler" (SURVEY, verified over all 37 files) — so this module implements
the serving stack's model side that the north star requires
(``BASELINE.json``: Llama-3-8B on v5e, Qwen2-72B 32k on v5p). Design:

- **Params are a flat pytree** with per-layer tensors stacked on a leading
  layer axis, consumed by ``lax.scan`` — one traced layer body instead of
  ``n_layers`` copies, which keeps XLA compile time flat in depth and makes
  layer-sharded (pp) layouts a reshape away.
- **Two entry points**: ``prefill_forward`` (new tokens attend to an
  optional cached prefix — the radix-cache reuse path) and ``decode_step``
  (one token per sequence; writes K/V into the paged pool *inside* the scan
  and attends via the Pallas paged kernel on TPU). Everything under one
  ``jit`` per call; the KV pool array is donated so decode updates HBM in
  place.
- **Sharding-ready**: ``param_logical_axes`` names every axis logically
  ("embed", "q_heads", "kv_heads", "ffn", "vocab"); ``parallel/sharding.py``
  maps logical names to mesh axes (tp/dp/...) so the same model code runs
  single-chip or pjit-sharded.
- Qwen2 is the same architecture with QKV biases and its own dims
  (``models/qwen2.py``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from radixmesh_tpu.ops.attention import (
    attend_prefill,
    paged_chunk_attention,
    paged_decode_attention,
)
from radixmesh_tpu.ops.norm import rms_norm
from radixmesh_tpu.ops.rope import apply_rope, rope_frequencies
from radixmesh_tpu.ops.sampling import sample_tokens

__all__ = [
    "ModelConfig",
    "init_params",
    "prefill_forward",
    "prefill_forward_sp",
    "prefill_chunk_paged",
    "decode_step",
    "decode_multi",
    "decode_multi_compact",
    "param_logical_axes",
    "convert_hf_state_dict",
]


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 128256
    hidden: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    intermediate: int = 14336
    rope_theta: float = 500000.0
    # Tuple of (key, value) pairs, not a dict: ModelConfig is a jit-static
    # argument and must hash.
    rope_scaling: tuple | None = None
    rms_eps: float = 1e-5
    qkv_bias: bool = False  # True for Qwen2
    tie_embeddings: bool = False
    max_seq_len: int = 8192
    dtype: Any = jnp.bfloat16

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)

    @classmethod
    def llama3_8b(cls) -> "ModelConfig":
        """Meta-Llama-3-8B: NO rope scaling (the HF config's
        rope_scaling is null at this generation, same as the 70B;
        scaling arrives with 3.1) and the 8k window."""
        return cls(rope_scaling=None)

    @classmethod
    def llama3_70b(cls) -> "ModelConfig":
        """Meta-Llama-3-70B: NO rope scaling (the HF config's
        rope_scaling is null at this generation; scaling arrives with
        3.1) and the 8k window."""
        return cls(
            hidden=8192,
            n_layers=80,
            n_heads=64,
            n_kv_heads=8,
            head_dim=128,
            intermediate=28672,
            rope_scaling=None,
        )

    @classmethod
    def llama31_8b(cls) -> "ModelConfig":
        """Llama-3.1-8B: the 3.0-8B dims plus the 3.1 llama3-style rope
        scaling + 128k window the base 3.0-8B preset deliberately lacks
        (mirrors the 70B/3.1-70B split); serving length stays
        pool-bounded."""
        return cls.llama3_8b().replace(
            rope_scaling=(
                ("factor", 8.0),
                ("low_freq_factor", 1.0),
                ("high_freq_factor", 4.0),
                ("original_max_position_embeddings", 8192),
            ),
            max_seq_len=131072,
        )

    @classmethod
    def llama31_70b(cls) -> "ModelConfig":
        """Llama-3.1-70B: the 70B dims plus the 3.1 rope scaling + 128k
        window the base 3.0-70B preset deliberately lacks."""
        return cls.llama3_70b().replace(
            rope_scaling=(
                ("factor", 8.0),
                ("low_freq_factor", 1.0),
                ("high_freq_factor", 4.0),
                ("original_max_position_embeddings", 8192),
            ),
            max_seq_len=131072,
        )

    @classmethod
    def llama32_1b(cls) -> "ModelConfig":
        return cls(
            vocab_size=128256,
            hidden=2048,
            n_layers=16,
            n_heads=32,
            n_kv_heads=8,
            head_dim=64,
            intermediate=8192,
            tie_embeddings=True,
            rope_scaling=(
                ("factor", 32.0),
                ("low_freq_factor", 1.0),
                ("high_freq_factor", 4.0),
                ("original_max_position_embeddings", 8192),
            ),
            max_seq_len=131072,
        )

    @classmethod
    def llama32_3b(cls) -> "ModelConfig":
        return cls.llama32_1b().replace(
            hidden=3072, n_layers=28, n_heads=24, n_kv_heads=8,
            head_dim=128, intermediate=8192,
        )

    @classmethod
    def tiny(cls) -> "ModelConfig":
        """Test/bench config: same architecture, toy dims."""
        return cls(
            vocab_size=512,
            hidden=128,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            head_dim=32,
            intermediate=256,
            max_seq_len=512,
        )


def _dense_init(key, shape, in_axis_size, dtype):
    scale = 1.0 / np.sqrt(in_axis_size)
    return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dtype)


def init_params(cfg: ModelConfig, key: jax.Array) -> dict:
    keys = jax.random.split(key, 8)
    L, H = cfg.n_layers, cfg.hidden
    qd, kvd = cfg.n_heads * cfg.head_dim, cfg.n_kv_heads * cfg.head_dim
    params = {
        "embed": _dense_init(keys[0], (cfg.vocab_size, H), H, cfg.dtype),
        "final_norm": jnp.ones((H,), dtype=cfg.dtype),
        "layers": {
            "attn_norm": jnp.ones((L, H), dtype=cfg.dtype),
            "mlp_norm": jnp.ones((L, H), dtype=cfg.dtype),
            "wq": _dense_init(keys[1], (L, H, qd), H, cfg.dtype),
            "wk": _dense_init(keys[2], (L, H, kvd), H, cfg.dtype),
            "wv": _dense_init(keys[3], (L, H, kvd), H, cfg.dtype),
            "wo": _dense_init(keys[4], (L, qd, H), qd, cfg.dtype),
            "w_gate": _dense_init(keys[5], (L, H, cfg.intermediate), H, cfg.dtype),
            "w_up": _dense_init(keys[6], (L, H, cfg.intermediate), H, cfg.dtype),
            "w_down": _dense_init(
                keys[7], (L, cfg.intermediate, H), cfg.intermediate, cfg.dtype
            ),
        },
    }
    if cfg.qkv_bias:
        params["layers"]["bq"] = jnp.zeros((L, qd), dtype=cfg.dtype)
        params["layers"]["bk"] = jnp.zeros((L, kvd), dtype=cfg.dtype)
        params["layers"]["bv"] = jnp.zeros((L, kvd), dtype=cfg.dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = _dense_init(
            jax.random.fold_in(key, 99), (H, cfg.vocab_size), H, cfg.dtype
        )
    return params


def param_logical_axes(cfg: ModelConfig, params: dict | None = None) -> dict:
    """Logical axis names per parameter, mapped to mesh axes by
    ``parallel/sharding.py`` (tp shards "q_heads"/"kv_heads"/"ffn"/"vocab",
    everything else replicates). Pass ``params`` to also cover the
    ``<name>_s`` scale leaves of W8A16-quantized weights (``ops/wquant.py``
    — each scale shards like its weight's OUTPUT axis)."""
    axes = {
        "embed": ("vocab", "embed"),
        "final_norm": ("embed",),
        "layers": {
            "attn_norm": ("layer", "embed"),
            "mlp_norm": ("layer", "embed"),
            "wq": ("layer", "embed", "q_heads"),
            "wk": ("layer", "embed", "kv_heads"),
            "wv": ("layer", "embed", "kv_heads"),
            "wo": ("layer", "q_heads", "embed"),
            "w_gate": ("layer", "embed", "ffn"),
            "w_up": ("layer", "embed", "ffn"),
            "w_down": ("layer", "ffn", "embed"),
        },
    }
    if cfg.qkv_bias:
        axes["layers"]["bq"] = ("layer", "q_heads")
        axes["layers"]["bk"] = ("layer", "kv_heads")
        axes["layers"]["bv"] = ("layer", "kv_heads")
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    if params is not None:
        # Each W8A16 scale shards like its weight's OUTPUT (last) axis —
        # derived from the weight's own entry so a layout change can't
        # drift the two apart.
        for name in list(axes["layers"]):
            if name + "_s" in params.get("layers", {}):
                axes["layers"][name + "_s"] = ("layer", axes["layers"][name][-1])
        if "embed_s" in params:
            axes["embed_s"] = ("vocab",)
        if "lm_head_s" in params:
            axes["lm_head_s"] = ("vocab",)
    return axes


# fp32 inputs on TPU are otherwise demoted to one-pass bf16 multiplies;
# HIGHEST makes fp32 honest and is a no-op for bf16 operands.
_PREC = jax.lax.Precision.HIGHEST


def _wmm(lp: dict, name: str, eq: str, x: jnp.ndarray, reshape=None,
         **einsum_kw):
    """Dense matmul honoring W8A16 storage (``ops/wquant.py``): int8
    weights feed the MXU as bf16 (only HBM *streaming* shrinks — compute
    precision is unchanged) and the per-out-channel scale applies to the
    output, which is exact for per-out-channel quantization."""
    w = lp[name]
    if w.dtype == jnp.int8:
        wm = w.astype(x.dtype)
        if reshape is not None:
            wm = wm.reshape(reshape)
        y = jnp.einsum(eq, x, wm, precision=_PREC, **einsum_kw)
        y = y * lp[name + "_s"]
        # The f32 scale would otherwise promote the whole activation
        # stream to f32 from the first quantized layer on — cast back
        # unless the caller asked for a widened output (the logits head).
        if "preferred_element_type" not in einsum_kw:
            y = y.astype(x.dtype)
        return y
    if reshape is not None:
        w = w.reshape(reshape)
    return jnp.einsum(eq, x, w, precision=_PREC, **einsum_kw)


def _embed_lookup(params: dict, tokens: jnp.ndarray) -> jnp.ndarray:
    """Embedding gather honoring W8A16 storage: int8 rows dequantize by
    their per-row scale right after the (int8-narrow) gather."""
    e = params["embed"]
    if e.dtype == jnp.int8:
        x = e[tokens].astype(jnp.float32) * params["embed_s"][tokens][..., None]
        return x.astype(params["final_norm"].dtype)
    return e[tokens]


def _qkv(lp: dict, x: jnp.ndarray, cfg: ModelConfig):
    """x: [B, S, H] → q [B,S,Hq,D], k/v [B,S,Hkv,D]."""
    q = _wmm(lp, "wq", "bsh,hd->bsd", x)
    k = _wmm(lp, "wk", "bsh,hd->bsd", x)
    v = _wmm(lp, "wv", "bsh,hd->bsd", x)
    if cfg.qkv_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    B, S = x.shape[:2]
    return (
        q.reshape(B, S, cfg.n_heads, cfg.head_dim),
        k.reshape(B, S, cfg.n_kv_heads, cfg.head_dim),
        v.reshape(B, S, cfg.n_kv_heads, cfg.head_dim),
    )


def _mlp(lp: dict, x: jnp.ndarray) -> jnp.ndarray:
    gate = jax.nn.silu(_wmm(lp, "w_gate", "bsh,hi->bsi", x))
    up = _wmm(lp, "w_up", "bsh,hi->bsi", x)
    return _wmm(lp, "w_down", "bsi,ih->bsh", gate * up)


def _attn_out(lp: dict, attn: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """The wo projection shared by every forward variant: attn is
    [B, S, Hq·D] (or already [B, S, Hq, D])."""
    B, S = attn.shape[:2]
    return _wmm(
        lp, "wo", "bsqd,qdh->bsh",
        attn.reshape(B, S, cfg.n_heads, cfg.head_dim),
        reshape=(cfg.n_heads, cfg.head_dim, cfg.hidden),
    )


def _logits(params: dict, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    if cfg.tie_embeddings:
        name, eq = "embed", "bsh,vh->bsv"
    else:
        name, eq = "lm_head", "bsh,hv->bsv"
    return _wmm(
        params, name, eq, x, preferred_element_type=jnp.float32
    )


@partial(jax.jit, static_argnames=("cfg",))
def prefill_forward(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, S_new]
    positions: jnp.ndarray,  # [B, S_new] absolute positions
    cached_k: jnp.ndarray,  # [L, B, P_max, Hkv, D] rotated prefix K, RIGHT-aligned
    cached_v: jnp.ndarray,  # [L, B, P_max, Hkv, D]
    prefix_lengths: jnp.ndarray,  # [B] valid cached-prefix tokens (≤ P_max)
):
    """Prefill new tokens against an optional cached prefix.

    Ragged prefixes are **right-aligned** in the ``P_max`` prefix region
    (row ``b`` occupies ``[P_max - prefix_lengths[b], P_max)``); the front
    padding is masked via ``kv_start``, so batched prefill with different
    hit lengths is exact. Pass ``P_max = 0`` arrays for no cache.

    Returns ``(logits [B,S,V], new_k [L,B,S,Hkv,D], new_v [...])`` — the
    caller writes new_k/new_v into the paged pool at the slots the radix
    tree allocated, which is how a served prompt becomes a reusable cached
    prefix (the contract the reference's commented-out scheduler hooks
    sketch, ``radix_cache.py:439-519``).
    """
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    x = _embed_lookup(params, tokens)
    p_max = cached_k.shape[2]
    s_new = tokens.shape[1]
    pad = p_max - prefix_lengths  # [B] front padding per row
    # Index-space position of query t (abs position p) inside the context
    # buffer [pad | prefix | new]: p + pad.
    attn_pos = positions + pad[:, None]
    kv_end = jnp.full_like(prefix_lengths, p_max + s_new)

    def layer(x, xs):
        lp, ck, cv = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(lp, h, cfg)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        k_ctx = jnp.concatenate([ck, k], axis=1)  # [B, P_max + S, Hkv, D]
        v_ctx = jnp.concatenate([cv, v], axis=1)
        attn = attend_prefill(q, k_ctx, v_ctx, attn_pos, kv_end, kv_start=pad)
        x = x + _attn_out(lp, attn, cfg)
        h2 = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(lp, h2)
        return x, (k, v)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], cached_k, cached_v)
    )
    return _logits(params, cfg, x), new_k, new_v


@partial(jax.jit, static_argnames=("cfg", "mesh", "axis"))
def prefill_forward_sp(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, S] — S divisible by the sp axis size
    positions: jnp.ndarray,  # [B, S]
    mesh,
    axis: str = "sp",
    logits_at: jnp.ndarray | None = None,  # [B] per-row positions, or None
):
    """Sequence-parallel prefill: activations sharded over the ``sp`` mesh
    axis along S, attention via ring attention (K/V blocks rotate over ICI
    with ``ppermute`` while each chip keeps its query shard — SURVEY §5's
    long-context requirement, serving-side). Everything outside attention
    partitions via GSPMD from the sharding constraint alone.

    Scaling regime: sp multiplies prefill FLOPs/HBM across chips (TTFT for
    long prompts); the CHUNKED path (``prefill_chunk_paged``) bounds
    memory on one chip. The engine composes them: sp-prefill the fresh
    span when a mesh with sp>1 is present, chunk otherwise.

    Returns ``(logits, new_k [L, B, S, Hkv, D], new_v)`` — sequence-
    sharded; callers scatter into the paged pool (GSPMD inserts the
    collectives). Logits are [B, S, V] — unless ``logits_at`` gives one
    position per row, in which case only those rows hit the LM head and
    logits are [B, 1, V]: a 32k-prompt serve must not materialize an
    S×vocab tensor it samples one row of.
    """
    from radixmesh_tpu.parallel.ring_attention import ring_self_attention

    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    from jax.sharding import NamedSharding, PartitionSpec

    seq_sharded = NamedSharding(mesh, PartitionSpec(None, axis))
    tokens = jax.lax.with_sharding_constraint(tokens, seq_sharded)
    x = _embed_lookup(params, tokens)

    def layer(x, xs):
        lp = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(lp, h, cfg)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        attn = ring_self_attention(q, k, v, mesh, axis=axis)
        x = x + _attn_out(lp, attn, cfg)
        h2 = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(lp, h2)
        return x, (k, v)

    x, (new_k, new_v) = jax.lax.scan(layer, x, params["layers"])
    if logits_at is not None:
        x = jnp.take_along_axis(x, logits_at[:, None, None], axis=1)
    return _logits(params, cfg, x), new_k, new_v


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "page_size", "kv_block_pages", "mesh", "use_kernel",
        "interpret",
    ),
    donate_argnums=(4,),
    donate_argnames=("kv_scale",),
)
def prefill_chunk_paged(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, C] one chunk of the prompt (tail-padded)
    positions: jnp.ndarray,  # [B, C] absolute positions
    kv_pool: jnp.ndarray,  # [2, L, Hkv, num_slots, D] (donated)
    slots: jnp.ndarray,  # [B, C] pool slot per chunk token (pad → scratch)
    page_table: jnp.ndarray,  # [B, max_pages] request pages, in order
    kv_lengths: jnp.ndarray,  # [B] context tokens valid after this chunk
    page_size: int = 16,
    kv_block_pages: int = 32,
    kv_scale: jnp.ndarray | None = None,  # [2, L, Hkv, num_slots] int8 pool
    mesh=None,
    use_kernel: bool | None = None,
    interpret: bool = False,
):
    """One CHUNK of long-context prefill against the paged pool (SURVEY §5:
    the 32k Qwen2 gate must never materialize O(S²) scores — VERDICT
    round-1 gap #4). Prior context (cached prefix + earlier chunks)
    streams blockwise out of the pool pages READ-ONLY; the chunk's own
    K/V rides dense through the layer scan and is scattered into the pool
    ONCE after the scan. Keeping the pool out of the scan carry matters:
    a per-layer scatter + page read of the carry made XLA materialize a
    full pool copy every layer (the same bug the fused decode kernel
    fixes on its path). Peak memory is O(C · kv_block), independent of
    prompt length; the host loops chunks, so compile cost is one variant
    per (B, C, max_pages) bucket triple.

    Returns ``(logits [B, C, V], kv_pool)`` — plus the updated
    ``kv_scale`` when the pool is int8-quantized.
    """
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    x = _embed_lookup(params, tokens)  # [B, C, H]
    num_slots = kv_pool.shape[3]
    pages_shape = (
        2, cfg.n_layers, cfg.n_kv_heads,
        num_slots // page_size, page_size, cfg.head_dim,
    )
    kv_pages = kv_pool.reshape(pages_shape)
    scale_pages = (
        None
        if kv_scale is None
        else kv_scale.reshape(
            2, cfg.n_layers, cfg.n_kv_heads, num_slots // page_size, page_size
        )
    )
    # Tokens in the pool BEFORE this chunk: chunk start per row. (Padded
    # rows may carry clamped positions; their outputs are discarded and
    # the masking below stays finite either way.)
    prior_lengths = jnp.minimum(positions[:, 0], kv_lengths)

    def layer(x, xs):
        l_idx, lp = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(lp, h, cfg)  # [B,C,*,D]
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        if kv_scale is not None:
            # Quantize NOW and attend the dequantized copy, so the chunk
            # sees exactly what any later pool read will see (the fused
            # decode kernel keeps the same invariant) — otherwise logits
            # drift between a speculative verify pass and plain decode.
            from radixmesh_tpu.ops.quant import quantize_for_store

            k_int, v_int, k_sc, v_sc, k, v = quantize_for_store(k, v)
        attn = paged_chunk_attention(
            q,
            k,
            v,
            kv_pages,
            page_table,
            positions,
            prior_lengths,
            kv_lengths,
            l_idx,
            kv_block_pages=kv_block_pages,
            kv_scales=scale_pages,
            use_kernel=use_kernel,
            mesh=mesh,
            interpret=interpret,
        )
        x = x + _attn_out(lp, attn, cfg)
        h2 = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(lp, h2)
        if kv_scale is not None:
            return x, (k_int, v_int, k_sc, v_sc)
        return x, (k.astype(kv_pool.dtype), v.astype(kv_pool.dtype))

    if kv_scale is not None:
        x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
            layer, x, (jnp.arange(cfg.n_layers), params["layers"])
        )
        # Already quantized in-layer (same ints attention saw); scatter the
        # int8 payloads + scales: scan stacks [L, B, C, Hkv(, D)] → the
        # pool target [:, :, :, slots[B, C]] expects [2, L, Hkv, B, C(, D)].
        new_kv = jnp.stack([new_k, new_v]).transpose(0, 1, 4, 2, 3, 5)
        new_s = jnp.stack([new_ks, new_vs]).transpose(0, 1, 4, 2, 3)
        kv_pool = kv_pool.at[:, :, :, slots].set(new_kv)
        kv_scale = kv_scale.at[:, :, :, slots].set(new_s)
        return _logits(params, cfg, x), kv_pool, kv_scale
    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (jnp.arange(cfg.n_layers), params["layers"])
    )
    # One scatter for the whole chunk across all layers: scan stacks
    # [L, B, C, Hkv, D]; the pool indexed at [:, :, :, slots[B,C]] expects
    # [2, L, Hkv, B, C, D].
    new_kv = jnp.stack([new_k, new_v]).transpose(0, 1, 4, 2, 3, 5)
    kv_pool = kv_pool.at[:, :, :, slots].set(new_kv)
    return _logits(params, cfg, x), kv_pool


@partial(
    jax.jit,
    static_argnames=("cfg", "page_size", "mesh"),
    donate_argnums=(3,),
    donate_argnames=("kv_scale",),
)
def decode_step(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] current token per sequence
    kv_pool: jnp.ndarray,  # [2, L, Hkv, num_slots, D] (donated)
    slots: jnp.ndarray,  # [B] pool slot for this token's KV
    page_table: jnp.ndarray,  # [B, max_pages]
    lengths: jnp.ndarray,  # [B] context length incl. this token
    page_size: int = 16,
    mesh=None,
    kv_scale: jnp.ndarray | None = None,  # [2, L, Hkv, num_slots] int8 pool
):
    """One decode step for a continuous batch: writes this token's K/V into
    the paged pool inside the layer scan, attends over the radix-cache
    pages (Pallas kernel on TPU), returns ``(logits [B,V], kv_pool)``.

    ``page_size`` is a property of the pool/page-table pairing (static so
    the pages view is a pure reshape). ``mesh`` (static) enables the
    tensor-parallel kernel path: heads/pool sharded over the mesh's tp
    axis, the Pallas kernel shard_map'd per chip; all other ops partition
    via GSPMD from the params/pool shardings."""
    return _decode_core(
        params, cfg, tokens, kv_pool, slots, page_table, lengths, page_size,
        mesh, kv_scale,
    )


@partial(
    jax.jit,
    static_argnames=("cfg", "page_size", "k_steps", "mesh"),
    donate_argnums=(3,),
    donate_argnames=("kv_scale",),
)
def decode_multi(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] current token per sequence
    kv_pool: jnp.ndarray,  # [2, L, Hkv, num_slots, D] (donated)
    page_table: jnp.ndarray,  # [B, max_pages] — pages preallocated k ahead
    lengths: jnp.ndarray,  # [B] context length incl. the first fed token
    key: jax.Array,
    temperatures: jnp.ndarray,  # [B]
    top_ps: jnp.ndarray,  # [B]
    page_size: int = 16,
    k_steps: int = 8,
    mesh=None,
    kv_scale: jnp.ndarray | None = None,
    top_ks: jnp.ndarray | int = 0,  # [B] (0 = off)
):
    """``k_steps`` decode iterations fused in ONE dispatch: sampling stays
    on device and each sampled token feeds the next step, so the host pays
    a single round trip per k tokens instead of per token — on RPC-
    tunneled devices (observed ~67 ms per host materialization) that round
    trip IS the per-token latency. The caller preallocates pages covering
    positions ``lengths-1 .. lengths+k-2`` per row; token slots are
    derived from the page table on device. Returns ``(sampled [k, B],
    kv_pool)``; stop-token/length bookkeeping happens on host afterwards
    (surplus tokens past a stop are discarded — latency is bought with a
    little bubble compute)."""
    B = tokens.shape[0]
    rows = jnp.arange(B)

    def step(carry, i):
        toks, pool, scale, k = carry
        lens = lengths + i
        pos = lens - 1
        slots = (
            page_table[rows, pos // page_size] * page_size + pos % page_size
        )
        res = _decode_core(
            params, cfg, toks, pool, slots, page_table, lens, page_size, mesh,
            scale,
        )
        logits, pool = res[0], res[1]
        if scale is not None:
            scale = res[2]
        k, sk = jax.random.split(k)
        nxt = sample_tokens(
            logits, sk, temperature=temperatures, top_p=top_ps, top_k=top_ks
        ).astype(jnp.int32)
        return (nxt, pool, scale, k), nxt

    (_, kv_pool, kv_scale, _), sampled = jax.lax.scan(
        step, (tokens, kv_pool, kv_scale, key), jnp.arange(k_steps)
    )
    if kv_scale is not None:
        return sampled, kv_pool, kv_scale
    return sampled, kv_pool


@partial(
    jax.jit,
    static_argnames=("cfg", "page_size", "k_steps", "mesh"),
    donate_argnums=(3,),
    donate_argnames=("kv_scale",),
)
def decode_multi_compact(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B]
    kv_pool: jnp.ndarray,  # [2, L, Hkv, num_slots, D] (donated)
    compact_pages: jnp.ndarray,  # [n_c] UNIQUE full-pool page ids (pad = dup
    #                               of the scratch page — see contract below)
    page_table_c: jnp.ndarray,  # [B, maxp] indices into compact_pages
    lengths: jnp.ndarray,  # [B] context length incl. the first fed token
    key: jax.Array,
    temperatures: jnp.ndarray,
    top_ps: jnp.ndarray,
    page_size: int = 16,
    k_steps: int = 8,
    mesh=None,
    kv_scale: jnp.ndarray | None = None,
    top_ks: jnp.ndarray | int = 0,
):
    """``decode_multi`` over a gathered COMPACT working set — the decode
    path for backends without the aliased Pallas kernel (CPU today).

    Without aliasing, every layer's KV write into the full pool is an XLA
    scatter that copies the WHOLE pool — ``k·L`` pool-sized copies per
    launch dominated decode wherever donation falls back to copying (the
    wide-workload convoy, VERDICT round-3 weak #2/#6). Here the launch
    pays ONE pool-sized gather of the live pages into a working-set pool
    (batch · bucketed-pages sized, typically 100-1000× smaller), runs the
    whole fused loop against it, and scatters the touched pages back
    once. On TPU the aliased fused kernel is strictly better — this
    function exists for everything else.

    CONTRACT: ``compact_pages`` entries must be unique except for
    padding, which must duplicate the engine's SCRATCH page (duplicate
    scatter-back targets write that page multiple times; scratch contents
    are never read unmasked, so last-write-wins is harmless there and
    must be harmless ONLY there). ``page_table_c`` maps every row's pages
    (and inactive rows entirely) to compact indices.

    Returns ``(sampled [k, B], kv_pool)`` (+ scale) — the ``decode_multi``
    contract.
    """
    L, Hkv, D = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    num_slots = kv_pool.shape[3]
    P = num_slots // page_size
    n_c = compact_pages.shape[0]
    pages = kv_pool.reshape(2, L, Hkv, P, page_size, D)
    sub_pool = pages[:, :, :, compact_pages].reshape(
        2, L, Hkv, n_c * page_size, D
    )
    sub_scale = None
    if kv_scale is not None:
        scale_pages = kv_scale.reshape(2, L, Hkv, P, page_size)
        sub_scale = scale_pages[:, :, :, compact_pages].reshape(
            2, L, Hkv, n_c * page_size
        )
    res = decode_multi(
        params, cfg, tokens, sub_pool, page_table_c, lengths, key,
        temperatures, top_ps, page_size=page_size, k_steps=k_steps,
        mesh=mesh, kv_scale=sub_scale, top_ks=top_ks,
    )
    sampled, sub_pool = res[0], res[1]
    pages = pages.at[:, :, :, compact_pages].set(
        sub_pool.reshape(2, L, Hkv, n_c, page_size, D)
    )
    kv_pool = pages.reshape(2, L, Hkv, num_slots, D)
    if kv_scale is not None:
        scale_pages = scale_pages.at[:, :, :, compact_pages].set(
            res[2].reshape(2, L, Hkv, n_c, page_size)
        )
        return sampled, kv_pool, scale_pages.reshape(2, L, Hkv, num_slots)
    return sampled, kv_pool


def _decode_core(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    kv_pool: jnp.ndarray,
    slots: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    page_size: int,
    mesh,
    kv_scale: jnp.ndarray | None = None,
):
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    positions = lengths - 1  # [B]
    x = _embed_lookup(params, tokens)[:, None, :]  # [B, 1, H]
    B = tokens.shape[0]
    num_slots = kv_pool.shape[3]
    pages_shape = (
        2, cfg.n_layers, cfg.n_kv_heads,
        num_slots // page_size, page_size, cfg.head_dim,
    )

    scales_shape = (
        2, cfg.n_layers, cfg.n_kv_heads, num_slots // page_size, page_size,
    )

    def layer(carry, xs):
        x, kv_pool, kv_scale = carry
        l_idx, lp = xs
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(lp, h, cfg)  # [B,1,*,D]
        q = apply_rope(q, positions[:, None], inv_freq)
        k = apply_rope(k, positions[:, None], inv_freq)
        # Fused write+attend: the Pallas kernel writes this token's K/V row
        # into the (aliased) pool and attends over this layer's pages — the
        # pool buffer flows through the scan with zero copies. (A separate
        # XLA scatter + kernel read used to force a full pool copy per
        # layer: ~4 GB of HBM traffic per step at bench shapes.) For
        # quantized pools the raw row goes in (the kernel quantizes) and
        # the scale pool rides the carry the same zero-copy way.
        if kv_scale is not None:
            attn, kv_pages, scale_pages = paged_decode_attention(
                q[:, 0],
                k[:, 0],
                v[:, 0],
                kv_pool.reshape(pages_shape),
                slots,
                page_table,
                lengths,
                l_idx,
                mesh=mesh,
                kv_scales=kv_scale.reshape(scales_shape),
            )
            kv_scale = scale_pages.reshape(
                2, cfg.n_layers, cfg.n_kv_heads, num_slots
            )
        else:
            attn, kv_pages = paged_decode_attention(
                q[:, 0],
                k[:, 0].astype(kv_pool.dtype),
                v[:, 0].astype(kv_pool.dtype),
                kv_pool.reshape(pages_shape),
                slots,
                page_table,
                lengths,
                l_idx,
                mesh=mesh,
            )
        kv_pool = kv_pages.reshape(2, cfg.n_layers, cfg.n_kv_heads, num_slots,
                                   cfg.head_dim)
        x = x + _wmm(
            lp, "wo", "bqd,qdh->bh",
            attn.reshape(B, cfg.n_heads, cfg.head_dim),
            reshape=(cfg.n_heads, cfg.head_dim, cfg.hidden),
        )[:, None, :]
        h2 = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        x = x + _mlp(lp, h2)
        return (x, kv_pool, kv_scale), None

    (x, kv_pool, kv_scale), _ = jax.lax.scan(
        layer, (x, kv_pool, kv_scale), (jnp.arange(cfg.n_layers), params["layers"])
    )
    logits = _logits(params, cfg, x)[:, 0]
    if kv_scale is not None:
        return logits, kv_pool, kv_scale
    return logits, kv_pool


# ---------------------------------------------------------------------------
# HF checkpoint conversion
# ---------------------------------------------------------------------------


def convert_hf_state_dict(cfg: ModelConfig, state: dict) -> dict:
    """Map a HuggingFace Llama/Qwen2 state dict (numpy arrays, HF names)
    into this module's stacked-layer param pytree.

    Accepts ``model.layers.{i}.self_attn.q_proj.weight`` etc. (HF stores
    ``[out, in]``; we store ``[in, out]`` so every projection is applied as
    ``x @ W``).
    """
    L = cfg.n_layers

    def get(name):
        return np.asarray(state[name])

    def proj(name_fmt):
        return jnp.stack(
            [
                jnp.asarray(get(name_fmt.format(i)).T, dtype=cfg.dtype)
                for i in range(L)
            ]
        )

    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype=cfg.dtype),
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype=cfg.dtype),
        "layers": {
            "attn_norm": jnp.stack(
                [
                    jnp.asarray(
                        get(f"model.layers.{i}.input_layernorm.weight"),
                        dtype=cfg.dtype,
                    )
                    for i in range(L)
                ]
            ),
            "mlp_norm": jnp.stack(
                [
                    jnp.asarray(
                        get(f"model.layers.{i}.post_attention_layernorm.weight"),
                        dtype=cfg.dtype,
                    )
                    for i in range(L)
                ]
            ),
            "wq": proj("model.layers.{}.self_attn.q_proj.weight"),
            "wk": proj("model.layers.{}.self_attn.k_proj.weight"),
            "wv": proj("model.layers.{}.self_attn.v_proj.weight"),
            "wo": proj("model.layers.{}.self_attn.o_proj.weight"),
            "w_gate": proj("model.layers.{}.mlp.gate_proj.weight"),
            "w_up": proj("model.layers.{}.mlp.up_proj.weight"),
            "w_down": proj("model.layers.{}.mlp.down_proj.weight"),
        },
    }
    if cfg.qkv_bias:
        for ours, theirs in (("bq", "q_proj"), ("bk", "k_proj"), ("bv", "v_proj")):
            params["layers"][ours] = jnp.stack(
                [
                    jnp.asarray(
                        get(f"model.layers.{i}.self_attn.{theirs}.bias"),
                        dtype=cfg.dtype,
                    )
                    for i in range(L)
                ]
            )
    if cfg.tie_embeddings:
        pass
    elif "lm_head.weight" in state:
        params["lm_head"] = jnp.asarray(get("lm_head.weight").T, dtype=cfg.dtype)
    else:
        params["lm_head"] = params["embed"].T
    return params
