from radixmesh_tpu.models.llama import (
    ModelConfig,
    init_params,
    prefill_forward,
    decode_step,
    param_logical_axes,
    convert_hf_state_dict,
)
from radixmesh_tpu.models import qwen2  # noqa: F401  (registers presets)

_PRESETS = {
    "llama3-8b": ModelConfig.llama3_8b,
    "llama3-70b": ModelConfig.llama3_70b,
    "llama3.1-8b": ModelConfig.llama31_8b,
    "llama3.1-70b": ModelConfig.llama31_70b,
    "llama3.2-1b": ModelConfig.llama32_1b,
    "llama3.2-3b": ModelConfig.llama32_3b,
    "llama3-tiny": ModelConfig.tiny,
    "qwen2-72b": qwen2.qwen2_72b,
    "qwen2-7b": qwen2.qwen2_7b,
    "qwen2.5-14b": qwen2.qwen25_14b,
    "qwen2.5-32b": qwen2.qwen25_32b,
    "qwen2-tiny": qwen2.qwen2_tiny,
}


def get_config(name: str, **overrides) -> ModelConfig:
    """Model registry: named presets for the BASELINE.json target configs."""
    try:
        cfg = _PRESETS[name]()
    except KeyError:
        raise ValueError(f"unknown model {name!r}; known: {sorted(_PRESETS)}")
    return cfg.replace(**overrides) if overrides else cfg


__all__ = [
    "ModelConfig",
    "init_params",
    "prefill_forward",
    "decode_step",
    "param_logical_axes",
    "convert_hf_state_dict",
    "get_config",
]
