"""Qwen2 model family: architecturally Llama with QKV biases and its own
dimensions, so the forward/param machinery is ``models/llama.py`` reused
verbatim — only the configs differ. Target config Qwen2-72B @ 32k context
is the BASELINE.json v5p-64 scale-out gate."""

from __future__ import annotations

from radixmesh_tpu.models.llama import ModelConfig


def qwen2_72b() -> ModelConfig:
    return ModelConfig(
        vocab_size=152064,
        hidden=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        head_dim=128,
        intermediate=29568,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        qkv_bias=True,
        max_seq_len=32768,
    )


def qwen2_7b() -> ModelConfig:
    return ModelConfig(
        vocab_size=152064,
        hidden=3584,
        n_layers=28,
        n_heads=28,
        n_kv_heads=4,
        head_dim=128,
        intermediate=18944,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        qkv_bias=True,
        max_seq_len=32768,
    )


def qwen25_14b() -> ModelConfig:
    """Qwen2.5 shares the Qwen2 architecture (qkv biases, 1e6 rope).
    NB the 14B/32B sizes use rms_norm_eps=1e-5 in their HF configs —
    unlike the 7B/72B sizes' 1e-6."""
    return ModelConfig(
        vocab_size=152064,
        hidden=5120,
        n_layers=48,
        n_heads=40,
        n_kv_heads=8,
        head_dim=128,
        intermediate=13824,
        rope_theta=1000000.0,
        rms_eps=1e-5,
        qkv_bias=True,
        max_seq_len=32768,
    )


def qwen25_32b() -> ModelConfig:
    return qwen25_14b().replace(n_layers=64, intermediate=27648)


def qwen2_tiny() -> ModelConfig:
    return ModelConfig(
        vocab_size=512,
        hidden=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=32,
        intermediate=256,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        qkv_bias=True,
        max_seq_len=512,
    )
