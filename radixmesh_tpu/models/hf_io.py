"""HuggingFace checkpoint IO: load real Llama/Qwen2 weights for serving.

The reference has no model at all (SURVEY §0 "What it is NOT"); its
north-star serving stack (``BASELINE.json`` "north_star": serve
Llama-3-8B) needs a path from the HF-format checkpoints those models ship
as — a directory of ``*.safetensors`` shards plus
``model.safetensors.index.json`` — into this framework's stacked-layer
param pytree (``models/llama.py::convert_hf_state_dict``).

Pure numpy + safetensors: no torch in the loading path, tensors go
host-numpy → ``jnp`` in the converter (one cast to the model dtype, which
on TPU is the HBM copy).

``save_hf_state_dict`` writes the same layout back (sharded, with index)
— used by the golden round-trip test and by operators exporting
checkpoints this framework trained/edited.
"""

from __future__ import annotations

import json
import os
from typing import TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from radixmesh_tpu.models.llama import ModelConfig

__all__ = ["load_hf_checkpoint", "load_hf_state_dict", "save_hf_state_dict"]

_INDEX = "model.safetensors.index.json"
_SINGLE = "model.safetensors"


def load_hf_state_dict(ckpt_dir: str) -> dict[str, np.ndarray]:
    """Read every tensor from an HF-format checkpoint directory.

    Handles both layouts HF emits: one ``model.safetensors`` file, or
    N shards named by ``model.safetensors.index.json``'s ``weight_map``.
    Returns plain numpy arrays keyed by HF names
    (``model.layers.3.self_attn.q_proj.weight`` …).
    """
    from safetensors.numpy import load_file

    index_path = os.path.join(ckpt_dir, _INDEX)
    single_path = os.path.join(ckpt_dir, _SINGLE)
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        shards = sorted(set(index["weight_map"].values()))
        state: dict[str, np.ndarray] = {}
        for shard in shards:
            state.update(load_file(os.path.join(ckpt_dir, shard)))
        missing = set(index["weight_map"]) - set(state)
        if missing:
            raise ValueError(
                f"checkpoint index names {len(missing)} tensors its shards "
                f"don't contain (e.g. {sorted(missing)[:3]})"
            )
        return state
    if os.path.exists(single_path):
        return dict(load_file(single_path))
    # Fall back to any stray .safetensors files (some exports skip the
    # index when there is exactly one shard with a non-standard name).
    parts = sorted(
        f for f in os.listdir(ckpt_dir) if f.endswith(".safetensors")
    )
    if not parts:
        raise FileNotFoundError(
            f"no {_SINGLE}, {_INDEX}, or *.safetensors in {ckpt_dir}"
        )
    state = {}
    for part in parts:
        state.update(load_file(os.path.join(ckpt_dir, part)))
    return state


def load_hf_checkpoint(ckpt_dir: str, cfg: "ModelConfig") -> dict:
    """HF checkpoint directory → this framework's param pytree."""
    from radixmesh_tpu.models.llama import convert_hf_state_dict

    return convert_hf_state_dict(cfg, load_hf_state_dict(ckpt_dir))


def save_hf_state_dict(
    state: dict[str, np.ndarray],
    ckpt_dir: str,
    max_shard_bytes: int = 4 << 30,
) -> None:
    """Write an HF-layout safetensors checkpoint (shards + index).

    Greedy sharding by insertion order, mirroring HF's writer closely
    enough that HF loaders (and :func:`load_hf_state_dict`) accept it.
    """
    from safetensors.numpy import save_file

    os.makedirs(ckpt_dir, exist_ok=True)
    shards: list[dict[str, np.ndarray]] = [{}]
    sizes = [0]
    for name, arr in state.items():
        nbytes = int(np.asarray(arr).nbytes)
        if sizes[-1] and sizes[-1] + nbytes > max_shard_bytes:
            shards.append({})
            sizes.append(0)
        shards[-1][name] = np.ascontiguousarray(arr)
        sizes[-1] += nbytes
    if len(shards) == 1:
        save_file(shards[0], os.path.join(ckpt_dir, _SINGLE))
        return
    n = len(shards)
    weight_map = {}
    for i, shard in enumerate(shards):
        fname = f"model-{i + 1:05d}-of-{n:05d}.safetensors"
        save_file(shard, os.path.join(ckpt_dir, fname))
        for name in shard:
            weight_map[name] = fname
    with open(os.path.join(ckpt_dir, _INDEX), "w") as f:
        json.dump(
            {"metadata": {"total_size": sum(sizes)}, "weight_map": weight_map},
            f,
        )
