"""Guarded-by race inference: lock-set analysis per class attribute.

RacerD (Blackshear et al., OOPSLA 2018) showed data races are findable
WITHOUT annotations by computing, per field access, the set of locks
held, then letting the codebase's own majority usage declare the
guarding lock (Engler et al., SOSP 2001: a convention most sites follow
is a contract the deviant sites break). This checker is that idea at
meshcheck scale:

1. **Lock sets.** For every ``self._x`` access in a class that owns at
   least one lock, compute the locks held — ``with self._lock:``
   nesting, composed through intra-class helper calls with the same
   name-shaped resolution as ``lock_order.py``'s acquisition graph:
   each call edge carries the caller's held set one level into the
   callee, and a private helper's AMBIENT set is the intersection of
   its callers' effective sets (a fixpoint, so lock-then-three-helpers
   chains stay guarded while any single off-lock path degrades the
   intersection to unguarded — RacerD's compositional summary rule).
   Entry frames (public methods, thread targets, helpers nobody calls)
   have an empty ambient set: their callers are other threads. Closure
   bodies are skipped during the normal walk — EXCEPT closures the
   method hands to ``threading.Thread``/``Timer`` (the hedge-leg
   shape), whose bodies are re-walked with an EMPTY held set: they run
   on the spawned thread, not under the spawning frame's locks, so an
   off-lock write inside one is exactly as racy as any other.
2. **Guard inference.** The guard of an attribute is the lock held at
   the MAJORITY of its write sites (all sites when there is only one
   write) — inferred, never annotated. No majority → no contract → no
   finding: deliberately unsynchronized single-thread state stays
   quiet.
3. **Concurrency gate.** A deviant access is only a race if the thread
   map (``thread_roots.py``) says it can actually run concurrently with
   a conflicting guarded access: the two sites' thread-root sets span
   two distinct roots, or share a multi-instance root (HTTP handlers,
   per-peer readers). Single-root state — engine-thread-only fields —
   never fires. A public method no spawned root reaches still runs on
   SOMEBODY's thread (``close()`` on the exit path, ``drain()`` from a
   signal handler — the close-vs-rejoin race class), so it gets a
   synthetic per-method ``caller:`` root, inherited by the private
   helpers only it reaches; two different public entry points are
   assumed concurrently callable, one is not.

Invariants:

- ``guarded-by-race`` — a WRITE without the inferred guard that can run
  concurrently with a guarded access (write-write / lost-update), or a
  guard-free READ deviating from an otherwise-unanimous guard
  convention while a guarded write can run concurrently (read-write:
  torn/stale read). The finding names the attribute, the inferred
  guard, the guard's site coverage, both ``file:line`` sites, and the
  thread roots on each side.

Reads get the stricter unanimity bar on purpose: CPython's GIL makes
single-reference reads atomic, so the lock-free-read idiom (volatile
snapshot, re-checked fast path) is pervasive and LEGAL here — a read is
only deviant when every other access agrees on the guard. Writes get
the plain majority bar: an off-lock write to majority-guarded state is
how the drain-claim and close-vs-rejoin races happened.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .callgraph import get_callgraph
from .core import Checker, Finding, SourceIndex, dotted_name, iter_functions
from .lock_order import LockOrderChecker, _lock_ctor_kind
from .thread_roots import get_thread_map

__all__ = ["GuardedByChecker", "MUTATORS"]

# Method calls on an attribute that mutate the underlying container —
# a ``self._q.append(...)`` is a write to ``_q``'s state even though the
# attribute binding itself is only loaded.
MUTATORS = frozenset((
    "append", "appendleft", "extend", "extendleft", "insert",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "add", "update", "setdefault", "difference_update",
    "intersection_update", "symmetric_difference_update",
    "put", "put_nowait", "sort", "reverse",
))

# Constructors whose product is internally synchronized (or is itself a
# lock): accesses to these attributes are exempt — calling .set() on an
# Event or .put_nowait() on a Queue is safe from any thread.
_THREADSAFE_CTORS = frozenset((
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "LifoQueue",
    "PriorityQueue", "SimpleQueue", "local",
))

# Registration/handle factories: metric families and logger handles are
# internally locked (obs/metrics.py) — a value built through any of
# these is exempt like the ctors above.
_HANDLE_CALLS = frozenset((
    "counter", "gauge", "histogram", "labels",
    "get_logger", "get_recorder", "get_registry",
))


def _threadsafe_value(value: ast.expr) -> bool:
    for node in ast.walk(value):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        last = name.split(".")[-1]
        if last in _THREADSAFE_CTORS or last in _HANDLE_CALLS:
            return True
    return False


@dataclass(frozen=True)
class _Access:
    attr: str
    kind: str  # "read" | "write"
    line: int
    held: frozenset


@dataclass
class _MethodFacts:
    accesses: list[_Access] = field(default_factory=list)
    # (held at call site, callee method name, line)
    calls: list[tuple[frozenset, str, int]] = field(default_factory=list)


@dataclass(frozen=True)
class _Instance:
    """One access in one calling context."""

    attr: str
    kind: str
    line: int
    held: frozenset
    frame: str  # method qual whose roots attribute this instance


class GuardedByChecker:
    id = "guarded-by"
    description = (
        "per-attribute lock-set inference (with-nesting, composed "
        "through intra-class helper chains): the majority-usage guard "
        "is a contract; an off-guard write — or a read deviating from "
        "a unanimous convention — that two thread roots can run "
        "concurrently is a race"
    )
    invariants = ("guarded-by-race",)

    # Majority bar for write-site guard inference.
    WRITE_MAJORITY = 0.5

    def check(self, index: SourceIndex) -> list[Finding]:
        cg = get_callgraph(index)
        tmap = get_thread_map(index)
        root_targets = {r.key for r in tmap.roots if r.key is not None}
        findings: list[Finding] = []
        for mod in index.iter_modules():
            if mod.tree is None or mod.rel.startswith("analysis/"):
                continue
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._check_class(
                        mod.rel, node, cg, tmap, root_targets, findings
                    )
        return findings

    # ------------------------------------------------------------------
    # per-class analysis
    # ------------------------------------------------------------------

    def _check_class(self, rel, cls_node, cg, tmap, root_targets, findings):
        locks: set[str] = set()
        exempt: set[str] = set()
        methods = {
            n.name: n for n in cls_node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for fn in methods.values():
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.Assign):
                    continue
                for t in stmt.targets:
                    name = dotted_name(t)
                    if not (name and name.startswith("self.") and name.count(".") == 1):
                        continue
                    attr = name.split(".", 1)[1]
                    if _lock_ctor_kind(stmt.value):
                        locks.add(attr)
                    elif _threadsafe_value(stmt.value):
                        exempt.add(attr)
        if not locks:
            return  # no lock, no inferable contract

        facts: dict[str, _MethodFacts] = {}
        for name, fn in methods.items():
            f = facts[name] = _MethodFacts()
            self._walk(fn.body, locks, frozenset(), methods, f)
            # Closures handed to Thread/Timer run on the SPAWNED thread:
            # re-walk their bodies with no held locks (the normal walk
            # skips nested defs; inline-called closures stay skipped —
            # they run under the caller's locks and attributing an empty
            # held set to them would be a false positive factory).
            for sub in self._spawned_closures(fn):
                self._walk(sub.body, locks, frozenset(), methods, f)

        internal_callers: dict[str, set[str]] = {}
        for caller, f in facts.items():
            if caller == "__init__":
                continue  # construction happens-before publication
            for _, callee, _ in f.calls:
                internal_callers.setdefault(callee, set()).add(caller)

        # Ambient lock sets (the compositional fixpoint): a method that
        # is an ENTRY frame — public, a thread target, or called by
        # nobody in the class — runs with no inherited locks; a private
        # helper inherits the INTERSECTION over its call sites of
        # (caller's ambient ∪ locks held at the site). Monotone
        # decreasing from "all locks", so recursion converges.
        ambient: dict[str, frozenset] = {}
        all_locks = frozenset(locks)
        for name in facts:
            qual = f"{cls_node.name}.{name}"
            entry = (
                not name.startswith("_")
                or not internal_callers.get(name)
                or (rel, qual) in root_targets
            )
            ambient[name] = frozenset() if entry else all_locks
        changed = True
        while changed:
            changed = False
            for caller, f in facts.items():
                if caller == "__init__":
                    continue
                for held, callee, _line in f.calls:
                    if callee not in ambient or ambient[callee] == frozenset():
                        continue
                    eff = ambient[callee] & (ambient[caller] | held)
                    if eff != ambient[callee]:
                        ambient[callee] = eff
                        changed = True

        instances: dict[str, list[_Instance]] = {}
        for name, f in facts.items():
            if name == "__init__":
                continue
            qual = f"{cls_node.name}.{name}"
            for a in f.accesses:
                instances.setdefault(a.attr, []).append(
                    _Instance(a.attr, a.kind, a.line, a.held | ambient[name], qual)
                )

        # Per-frame thread roots: the spawned/declared roots that reach
        # the frame, else — for frames only a public caller can enter —
        # a synthetic caller: root per public entry method, propagated
        # to the private helpers it reaches intra-class.
        caller_roots: dict[str, set[str]] = {n: set() for n in facts}
        for name in facts:
            if name == "__init__" or name.startswith("_"):
                continue
            reach = {name}
            frontier = [name]
            while frontier:
                cf = facts.get(frontier.pop())
                if cf is None:
                    continue
                for _, callee, _ in cf.calls:
                    if callee in facts and callee not in reach:
                        reach.add(callee)
                        frontier.append(callee)
            for m in reach:
                caller_roots[m].add(f"caller:{cls_node.name}.{name}")
        frame_roots: dict[str, tuple[str, ...]] = {}
        for name in facts:
            qual = f"{cls_node.name}.{name}"
            real = tmap.roots_of((rel, qual))
            frame_roots[qual] = real or tuple(sorted(caller_roots[name]))

        for attr, insts in sorted(instances.items()):
            if attr in locks or attr in exempt:
                continue
            self._check_attr(
                rel, cls_node.name, attr, insts, tmap, frame_roots, findings
            )

    @staticmethod
    def _spawned_closures(fn):
        """Nested defs inside ``fn`` that are handed to a Thread/Timer
        as targets (name-matched within the same function)."""
        from .thread_roots import _spawn_kind, _target_expr

        targets: set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            kind = _spawn_kind(node)
            if kind is None:
                continue
            t = _target_expr(node, kind)
            if isinstance(t, ast.Name):
                targets.add(t.id)
        if not targets:
            return
        for node in ast.walk(fn):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not fn
                and node.name in targets
            ):
                yield node

    # ------------------------------------------------------------------
    # statement walk: held-lock tracking (the lock_order discipline)
    # ------------------------------------------------------------------

    def _walk(self, stmts, locks, held, methods, f: _MethodFacts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # a closure runs on another thread, not under held
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held
                for item in stmt.items:
                    lock = self._self_lock(item.context_expr, locks)
                    if lock is not None:
                        inner = inner | {lock}
                # The with-items' own expressions still run under the
                # OUTER held set (the lock acquisition itself).
                for item in stmt.items:
                    self._scan_expr(item.context_expr, locks, held, methods, f)
                self._walk(stmt.body, locks, inner, methods, f)
                continue
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    self._scan_store(t, locks, held, methods, f)
                self._scan_expr(stmt.value, locks, held, methods, f)
                continue
            if isinstance(stmt, ast.AugAssign):
                self._scan_store(stmt.target, locks, held, methods, f, aug=True)
                self._scan_expr(stmt.value, locks, held, methods, f)
                continue
            if isinstance(stmt, ast.AnnAssign):
                if stmt.value is not None:
                    self._scan_store(stmt.target, locks, held, methods, f)
                    self._scan_expr(stmt.value, locks, held, methods, f)
                continue
            if isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    self._scan_store(t, locks, held, methods, f)
                continue
            for blocks in LockOrderChecker._nested_blocks(stmt):
                self._walk(blocks, locks, held, methods, f)
            for expr in self._own_exprs(stmt):
                self._scan_expr(expr, locks, held, methods, f)

    @staticmethod
    def _own_exprs(stmt):
        for _name, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                yield value
            elif isinstance(value, list):
                yield from (v for v in value if isinstance(v, ast.expr))

    @staticmethod
    def _self_attr(node) -> str | None:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _self_lock(self, expr, locks) -> str | None:
        attr = self._self_attr(expr)
        return attr if attr in locks else None

    def _scan_store(self, target, locks, held, methods, f, aug=False) -> None:
        attr = self._self_attr(target)
        if attr is not None:
            f.accesses.append(_Access(attr, "write", target.lineno, held))
            return
        if isinstance(target, ast.Subscript):
            attr = self._self_attr(target.value)
            if attr is not None:
                f.accesses.append(_Access(attr, "write", target.lineno, held))
            else:
                self._scan_expr(target.value, locks, held, methods, f)
            self._scan_expr(target.slice, locks, held, methods, f)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._scan_store(elt, locks, held, methods, f)
            return
        if isinstance(target, (ast.Attribute, ast.Starred)):
            self._scan_expr(target, locks, held, methods, f)

    def _scan_expr(self, expr, locks, held, methods, f) -> None:
        if expr is None:
            return
        mutated: set[ast.AST] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                # self._m(...) — intra-class helper call (one level).
                if isinstance(node.func, ast.Attribute):
                    base = self._self_attr(node.func)
                    if base is not None and base in methods:
                        f.calls.append((held, base, node.lineno))
                        continue
                    # self._q.append(...) — container mutation.
                    inner = self._self_attr(node.func.value)
                    if inner is not None and node.func.attr in MUTATORS:
                        mutated.add(node.func.value)
        for node in ast.walk(expr):
            attr = self._self_attr(node)
            if attr is None or attr in locks:
                continue
            kind = "write" if node in mutated else "read"
            f.accesses.append(_Access(attr, kind, node.lineno, held))

    # ------------------------------------------------------------------
    # per-attribute verdict
    # ------------------------------------------------------------------

    def _check_attr(self, rel, cls, attr, insts, tmap, frame_roots, findings) -> None:
        writes = [i for i in insts if i.kind == "write"]
        if not writes:
            return  # read-only after construction: no race possible

        def roots(i: _Instance):
            return frame_roots.get(i.frame, ())

        # Guard inference: majority over write sites (all sites when
        # only one write exists — one guarded write among consistently
        # guarded reads is still a convention).
        basis = writes if len(writes) >= 2 else insts
        counts: dict[str, int] = {}
        for i in basis:
            for lock in i.held:
                counts[lock] = counts.get(lock, 0) + 1
        if not counts:
            return  # nothing ever guarded: no contract to deviate from
        guard, n_guard = max(counts.items(), key=lambda kv: (kv[1], kv[0]))
        if n_guard < 2 or n_guard / len(basis) <= self.WRITE_MAJORITY:
            return
        guarded = [i for i in insts if guard in i.held]
        coverage = f"{len(guarded)}/{len(insts)}"

        seen: set[tuple[int, str]] = set()
        for u in insts:
            if guard in u.held:
                continue
            key = (u.line, u.kind)
            if key in seen:
                continue
            if u.kind == "read":
                # Unanimity bar: every OTHER access must hold the guard
                # (the lock-free-read idiom is legal unless the class's
                # own convention says otherwise).
                others = [i for i in insts if i.line != u.line]
                if not others or any(guard not in i.held for i in others):
                    continue
                conflicting = [i for i in guarded if i.kind == "write"]
            else:
                conflicting = guarded
            hit = next(
                (v for v in conflicting
                 if tmap.concurrent(roots(u), roots(v))),
                None,
            )
            if hit is None:
                continue
            seen.add(key)
            pair = (
                "write-write" if u.kind == "write" and hit.kind == "write"
                else "read-write"
            )
            findings.append(Finding(
                rel, u.line, "guarded-by-race",
                f"{cls}.{attr}: {u.kind} without the inferred guard "
                f"'{guard}' (held at {coverage} access sites) — "
                f"{pair} race with the guarded {hit.kind} at "
                f"{rel}:{hit.line}; this side runs on thread root(s) "
                f"{list(roots(u))}, that side on {list(roots(hit))}",
            ))
