"""Metric vocabulary, checked at registration call sites.

Every family the serving stack registers must be ``radixmesh_``-prefixed
(one grep finds the fleet's series; no collision with other exporters on
a shared scrape) and unit-suffixed so dashboards never guess units. The
runtime lint (``tests/test_metrics_lint.py``) walks what actually landed
in the registry; this checker reads the same rules off the AST at every
``counter()/gauge()/histogram()`` call site, so a family registered only
on a code path no lint test constructs is still checked.

Invariants:

- ``metrics-prefix`` — family name missing the ``radixmesh_`` prefix.
- ``metrics-unit`` — counter without ``_total``; histogram without a
  base unit (``_seconds``/``_bytes``/``_tokens``); gauge without a
  declared unit from :data:`GAUGE_SUFFIXES` (a new suffix is a
  conscious vocabulary decision made HERE, not a typo that slips
  through).
- ``metrics-literal`` — the family name is not a string literal; a
  computed name can't be vocabulary-checked statically and breaks the
  one-grep-finds-everything property.

The suffix vocabulary lives here as the single source of truth; the
runtime lint imports it.
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, SourceIndex

__all__ = ["MetricsVocabChecker", "UNIT_SUFFIXES", "GAUGE_SUFFIXES", "PREFIX"]

PREFIX = "radixmesh_"

# Base units (counters are ``_total``; histograms observe seconds/bytes/
# tokens). Gauges may additionally be counts of a named thing or one of
# the declared dimensionless states.
UNIT_SUFFIXES = ("_seconds", "_bytes", "_total", "_tokens")
GAUGE_SUFFIXES = UNIT_SUFFIXES + (
    "_requests", "_slots", "_nodes", "_rows",
    "_epoch", "_rank", "_flag", "_tier", "_tokens_per_second",
    "_state",  # lifecycle state code (policy/lifecycle.py)
    "_shards",  # owned-shard count (cache/sharding.py)
    "_bytes_per_insert",  # per-insert wire-cost EWMA (cache/sharding.py)
    "_ratio",  # dimensionless max/mean skew (PR 9 heat map)
    "_mfu",  # model-FLOPs-utilization estimate (obs/step_plane.py)
    "_fraction",  # 0..1 share, e.g. wave padding (obs/step_plane.py)
)

_KINDS = ("counter", "gauge", "histogram")

# The metrics framework itself (defines the factories) is exempt.
_FRAMEWORK = "obs/metrics.py"


class MetricsVocabChecker:
    id = "metrics-vocab"
    description = (
        "metric families are radixmesh_-prefixed and unit-suffixed, "
        "checked statically at every counter()/gauge()/histogram() "
        "registration call site"
    )

    def check(self, index: SourceIndex) -> list[Finding]:
        findings: list[Finding] = []
        for mod in index.iter_modules():
            if (
                mod.tree is None
                or mod.rel == _FRAMEWORK
                or mod.rel.startswith("analysis/")
            ):
                continue
            for node in ast.walk(mod.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _KINDS
                ):
                    continue
                kind = node.func.attr
                # The family name is the first positional or the
                # ``name=`` keyword — a keyword-form registration must
                # not silently bypass the vocabulary.
                if node.args:
                    name_arg = node.args[0]
                else:
                    name_arg = next(
                        (k.value for k in node.keywords if k.arg == "name"),
                        None,
                    )
                    if name_arg is None:
                        continue  # no name argument: not a registration
                if not (
                    isinstance(name_arg, ast.Constant)
                    and isinstance(name_arg.value, str)
                ):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-literal",
                        f"{kind}() family name is not a string literal — "
                        "computed names defeat static vocabulary checks "
                        "and fleet-wide grep",
                    ))
                    continue
                name = name_arg.value
                if not name.startswith(PREFIX):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-prefix",
                        f"{name!r}: missing the {PREFIX!r} prefix",
                    ))
                    continue
                if kind == "counter" and not name.endswith("_total"):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-unit",
                        f"{name!r}: counter without _total",
                    ))
                elif kind == "histogram" and not name.endswith(
                    ("_seconds", "_bytes", "_tokens")
                ):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-unit",
                        f"{name!r}: histogram without a base unit suffix",
                    ))
                elif kind == "gauge" and not name.endswith(GAUGE_SUFFIXES):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-unit",
                        f"{name!r}: gauge without a declared unit (extend "
                        "GAUGE_SUFFIXES in analysis/metrics_vocab.py if "
                        "this is a conscious vocabulary addition)",
                    ))
        return findings
