"""Metric vocabulary, checked at registration call sites.

Every family the serving stack registers must be ``radixmesh_``-prefixed
(one grep finds the fleet's series; no collision with other exporters on
a shared scrape) and unit-suffixed so dashboards never guess units. The
runtime lint (``tests/test_metrics_lint.py``) walks what actually landed
in the registry; this checker reads the same rules off the AST at every
``counter()/gauge()/histogram()`` call site, so a family registered only
on a code path no lint test constructs is still checked.

Invariants:

- ``metrics-prefix`` — family name missing the ``radixmesh_`` prefix.
- ``metrics-unit`` — counter without ``_total``; histogram without a
  base unit (``_seconds``/``_bytes``/``_tokens``); gauge without a
  declared unit from :data:`GAUGE_SUFFIXES` (a new suffix is a
  conscious vocabulary decision made HERE, not a typo that slips
  through).
- ``metrics-literal`` — the family name is not a string literal; a
  computed name can't be vocabulary-checked statically and breaks the
  one-grep-finds-everything property.
- ``metrics-dead`` — a family is registered but never emitted: no
  ``.set()/.inc()/.dec()/.observe()`` anywhere in the tree flows from
  any of its registration handles. A registered-but-silent family is a
  dashboard lying by omission (the PR 9 heat-gauge clearing bug class:
  a series everyone believed was live had quietly stopped being
  written). Handle flow is tracked through assignment aliases,
  ``.labels()`` chains, dict/comprehension fan-outs, and literal
  ``getattr(x, "_m_foo")`` indirection, per module, with emit sites
  counted tree-wide.

The suffix vocabulary lives here as the single source of truth; the
runtime lint imports it.
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, SourceIndex, dotted_name

__all__ = ["MetricsVocabChecker", "UNIT_SUFFIXES", "GAUGE_SUFFIXES", "PREFIX"]

PREFIX = "radixmesh_"

# Base units (counters are ``_total``; histograms observe seconds/bytes/
# tokens). Gauges may additionally be counts of a named thing or one of
# the declared dimensionless states.
UNIT_SUFFIXES = ("_seconds", "_bytes", "_total", "_tokens")
GAUGE_SUFFIXES = UNIT_SUFFIXES + (
    "_requests", "_slots", "_nodes", "_rows",
    "_epoch", "_rank", "_flag", "_tier", "_tokens_per_second",
    "_state",  # lifecycle state code (policy/lifecycle.py)
    "_shards",  # owned-shard count (cache/sharding.py)
    "_bytes_per_insert",  # per-insert wire-cost EWMA (cache/sharding.py)
    "_ratio",  # dimensionless max/mean skew (PR 9 heat map)
    "_mfu",  # model-FLOPs-utilization estimate (obs/step_plane.py)
    "_fraction",  # 0..1 share, e.g. wave padding (obs/step_plane.py)
    "_series",  # telemetry-history ring count (obs/timeseries.py)
    "_points",  # telemetry-history retained points (obs/timeseries.py)
    "_rf_boost",  # extra owners beyond the base walk (cache/rebalance.py)
    "_extents",  # committed durable-tier extent files (cache/kv_tier.py)
    "_peers",  # fleet-aggregator polled peer count (obs/aggregator.py)
    "_waves",  # consecutive decode-deferred wave count (engine/waves.py)
)

_KINDS = ("counter", "gauge", "histogram")

# The metrics framework itself (defines the factories) is exempt.
_FRAMEWORK = "obs/metrics.py"


class MetricsVocabChecker:
    id = "metrics-vocab"
    description = (
        "metric families are radixmesh_-prefixed and unit-suffixed, "
        "checked statically at every counter()/gauge()/histogram() "
        "registration call site"
    )
    invariants = (
        "metrics-prefix", "metrics-unit", "metrics-literal", "metrics-dead",
    )

    def check(self, index: SourceIndex) -> list[Finding]:
        findings: list[Finding] = []
        # family name -> first registration site (for the dead finding)
        registered: dict[str, tuple[str, int]] = {}
        for mod in index.iter_modules():
            if (
                mod.tree is None
                or mod.rel == _FRAMEWORK
                or mod.rel.startswith("analysis/")
            ):
                continue
            for node in ast.walk(mod.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _KINDS
                ):
                    continue
                kind = node.func.attr
                # The family name is the first positional or the
                # ``name=`` keyword — a keyword-form registration must
                # not silently bypass the vocabulary.
                if node.args:
                    name_arg = node.args[0]
                else:
                    name_arg = next(
                        (k.value for k in node.keywords if k.arg == "name"),
                        None,
                    )
                    if name_arg is None:
                        continue  # no name argument: not a registration
                if not (
                    isinstance(name_arg, ast.Constant)
                    and isinstance(name_arg.value, str)
                ):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-literal",
                        f"{kind}() family name is not a string literal — "
                        "computed names defeat static vocabulary checks "
                        "and fleet-wide grep",
                    ))
                    continue
                name = name_arg.value
                registered.setdefault(name, (mod.rel, node.lineno))
                if not name.startswith(PREFIX):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-prefix",
                        f"{name!r}: missing the {PREFIX!r} prefix",
                    ))
                    continue
                if kind == "counter" and not name.endswith("_total"):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-unit",
                        f"{name!r}: counter without _total",
                    ))
                elif kind == "histogram" and not name.endswith(
                    ("_seconds", "_bytes", "_tokens")
                ):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-unit",
                        f"{name!r}: histogram without a base unit suffix",
                    ))
                elif kind == "gauge" and not name.endswith(GAUGE_SUFFIXES):
                    findings.append(Finding(
                        mod.rel, node.lineno, "metrics-unit",
                        f"{name!r}: gauge without a declared unit (extend "
                        "GAUGE_SUFFIXES in analysis/metrics_vocab.py if "
                        "this is a conscious vocabulary addition)",
                    ))

        emitted = self._emitted_families(index)
        for name, (rel, line) in sorted(registered.items()):
            if name not in emitted:
                findings.append(Finding(
                    rel, line, "metrics-dead",
                    f"{name!r} is registered but never "
                    ".set()/.inc()/.dec()/.observe()d anywhere in the "
                    "tree — a silent series reads as 'zero activity' on "
                    "every dashboard; emit it or delete the family",
                ))
        return findings

    # ------------------------------------------------------------------
    # dead-family flow analysis
    # ------------------------------------------------------------------

    _EMIT_VERBS = ("set", "inc", "dec", "observe")

    def _emitted_families(self, index: SourceIndex) -> set[str]:
        """Family names with at least one emit site. Taint is scoped
        PER MODULE — two unrelated modules both naming a handle
        ``self._m`` must not alias each other's families (a dead family
        would hide behind a live one's emit) — with two deliberate
        cross-module edges: a bare name follows the module's explicit
        imports (a handle FACTORY like ``eviction_counters`` taints its
        own name where it is defined, and callers reach it through the
        import), and a literal ``getattr(x, "_m_foo")`` resolves
        against the tree-wide attribute taint (getattr IS the explicit
        cross-module indirection)."""
        from .callgraph import get_callgraph

        imports = get_callgraph(index).imports
        taint: dict[tuple[str, str], set[str]] = {}  # (module, name) -> fams
        attr_global: dict[str, set[str]] = {}  # attr name -> fams (getattr only)
        # Worklist of (module, target (name, is_attr) pairs, value expr):
        # plain assignments, for-loop targets (iterating a dict of
        # handles), and function returns.
        pending: list[tuple[str, list[tuple[str, bool]], ast.expr]] = []
        for mod in index.iter_modules():
            if mod.tree is None or mod.rel.startswith("analysis/"):
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    if getattr(node, "value", None) is None:
                        continue
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    pending.append(
                        (mod.rel, self._target_names(targets), node.value)
                    )
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    pending.append(
                        (mod.rel, self._target_names([node.target]), node.iter)
                    )
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Return) and sub.value is not None:
                            pending.append(
                                (mod.rel, [(node.name, False)], sub.value)
                            )
        changed = True
        while changed:
            changed = False
            for rel, names, value in pending:
                if not names:
                    continue
                fams = self._value_families(value, rel, taint, attr_global, imports)
                if not fams:
                    continue
                for base, is_attr in names:
                    cur = taint.setdefault((rel, base), set())
                    if not fams <= cur:
                        cur |= fams
                        changed = True
                    if is_attr:
                        gcur = attr_global.setdefault(base, set())
                        if not fams <= gcur:
                            gcur |= fams
                            changed = True

        emitted: set[str] = set()
        for mod in index.iter_modules():
            if mod.tree is None or mod.rel.startswith("analysis/"):
                continue
            for node in ast.walk(mod.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._EMIT_VERBS
                ):
                    emitted |= self._value_families(
                        node.func.value, mod.rel, taint, attr_global, imports
                    )
        return emitted

    def _value_families(self, value, rel, taint, attr_global, imports) -> set[str]:
        """Families flowing through ``value`` in module ``rel``: literal
        registration calls, ``getattr(x, "_m_foo")`` with a literal
        attr (tree-wide attribute taint), and loads of tainted names —
        bare names fall back through the module's imports (through
        .labels() chains, subscripts, comprehensions; ast.walk sees
        them all)."""
        out: set[str] = set()
        imap = imports.get(rel, {})
        for node in ast.walk(value):
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _KINDS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    out.add(node.args[0].value)
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "getattr"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)
                ):
                    out |= attr_global.get(node.args[1].value, set())
            elif isinstance(node, ast.Name):
                hit = taint.get((rel, node.id))
                if hit is None and node.id in imap:
                    hit = taint.get((imap[node.id], node.id))
                out |= hit or set()
            elif isinstance(node, ast.Attribute):
                out |= taint.get((rel, node.attr), set())
        return out

    def _target_names(self, targets) -> list[tuple[str, bool]]:
        out: list[tuple[str, bool]] = []
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                out.extend(self._target_names(t.elts))
            else:
                base = self._base_name(t)
                if base is not None:
                    out.append((base, not isinstance(t, ast.Name)))
        return out

    @staticmethod
    def _base_name(expr: ast.expr) -> str | None:
        """The name a handle chain hangs off: ``self._m_x[k].labels(y)``
        → ``_m_x``; plain ``x`` → ``x``."""
        while True:
            if isinstance(expr, ast.Call):
                if isinstance(expr.func, ast.Attribute):
                    expr = expr.func.value
                    continue
                return None
            if isinstance(expr, ast.Subscript):
                expr = expr.value
                continue
            if isinstance(expr, ast.Attribute):
                return expr.attr
            if isinstance(expr, ast.Name):
                return expr.id
            return None
