"""Wire-kind exhaustiveness, verified structurally.

The oplog protocol's forward-compat contract (``cache/oplog.py``): every
kind added AFTER the unknown-kind pass-through tolerance (``PREFETCH``
and everything newer) must be registered in ``EXTENSION_KINDS`` so an
old wire forwards it instead of raising — and every kind the mesh
actually speaks must have an encode site and an explicit receive branch
BEFORE the data-apply default, so a non-data payload can never fall
through and corrupt a replica's tree.

The old lint verified this by substring (``"OplogType.X" in src``); this
checker reads structure:

- ``wire-unregistered`` — an ``OplogType`` member declared at/after
  ``PREFETCH`` that is not a member of the ``EXTENSION_KINDS`` set
  display (reported at the member's declaration line).
- ``wire-no-encode`` — a kind in ``EXTENSION_KINDS``/``DATA_KINDS``
  that is never passed as a call argument anywhere in the package
  (``Oplog(OplogType.K, ...)`` or through a sender helper like
  ``send_repair(rank, OplogType.K, ...)``) — dead vocabulary.
- ``wire-no-receive`` — a kind in ``EXTENSION_KINDS``/``DATA_KINDS``
  with no comparison against ``OplogType.K`` inside any
  ``oplog_received`` function — the frame would fall through to the
  data-apply default.
- ``wire-data-kinds`` — ``DATA_KINDS`` drifted from the exact
  replicated-tree-op set {INSERT, DELETE, RESET} that drives
  early-probe arming.
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, SourceIndex, dotted_name, iter_functions

__all__ = ["WireKindsChecker"]

_OPLOG = "cache/oplog.py"
_EXPECTED_DATA = ("INSERT", "DELETE", "RESET")


def _kind_refs(root: ast.AST) -> set[str]:
    """All ``OplogType.K`` member names referenced under ``root``."""
    out = set()
    for node in ast.walk(root):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "OplogType"
        ):
            out.add(node.attr)
    return out


class WireKindsChecker:
    id = "wire-kinds"
    description = (
        "every oplog kind in EXTENSION_KINDS/DATA_KINDS has an encode "
        "site, an explicit receive branch, and (post-tolerance kinds) a "
        "registration — verified structurally, not by substring"
    )
    invariants = (
        "wire-unregistered", "wire-no-encode", "wire-no-receive",
        "wire-data-kinds",
    )

    def check(self, index: SourceIndex) -> list[Finding]:
        if _OPLOG not in index or index.module(_OPLOG).tree is None:
            return []
        tree = index.module(_OPLOG).tree
        members, member_lines = self._enum_members(tree)
        if not members:
            return []
        ext, ext_line = self._set_members(tree, "EXTENSION_KINDS")
        data, data_line = self._set_members(tree, "DATA_KINDS")
        findings: list[Finding] = []

        # 1. Registration: PREFETCH (where the pass-through tolerance
        # shipped) and every later kind must be in EXTENSION_KINDS.
        if "PREFETCH" in members:
            tolerance_at = members.index("PREFETCH")
            for name in members[tolerance_at:]:
                if name not in ext:
                    findings.append(Finding(
                        _OPLOG, member_lines[name], "wire-unregistered",
                        f"OplogType.{name} post-dates the unknown-kind "
                        "pass-through tolerance but is missing from "
                        "EXTENSION_KINDS — an old wire would raise on it "
                        "instead of forwarding",
                    ))

        # 2. DATA_KINDS is pinned to the replicated tree ops.
        if data_line and tuple(sorted(data)) != tuple(sorted(_EXPECTED_DATA)):
            findings.append(Finding(
                _OPLOG, data_line, "wire-data-kinds",
                f"DATA_KINDS is {sorted(data)}, expected exactly "
                f"{sorted(_EXPECTED_DATA)} (it drives early-probe "
                "arming: the kinds whose loss diverges a replica, and "
                "nothing else)",
            ))

        # 3/4. Encode sites + receive branches for the spoken vocabulary.
        spoken = [n for n in members if n in ext or n in data]
        encoded = self._encoded_kinds(index)
        received = self._received_kinds(index)
        for name in spoken:
            if name not in encoded:
                findings.append(Finding(
                    _OPLOG, member_lines.get(name, ext_line or 1),
                    "wire-no-encode",
                    f"OplogType.{name} is registered but never passed "
                    "to any call in the package — no encode site",
                ))
            if name not in received:
                findings.append(Finding(
                    _OPLOG, member_lines.get(name, ext_line or 1),
                    "wire-no-receive",
                    f"OplogType.{name} has no explicit comparison branch "
                    "in any oplog_received — the frame would fall "
                    "through to the data-apply default",
                ))
        return findings

    # ------------------------------------------------------------------

    def _enum_members(self, tree) -> tuple[list[str], dict[str, int]]:
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == "OplogType":
                names: list[str] = []
                lines: dict[str, int] = {}
                for stmt in node.body:
                    if (
                        isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                    ):
                        names.append(stmt.targets[0].id)
                        lines[stmt.targets[0].id] = stmt.lineno
                return names, lines
        return [], {}

    def _set_members(self, tree, set_name: str) -> tuple[set[str], int | None]:
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == set_name
            ):
                return _kind_refs(node.value), node.lineno
        return set(), None

    def _encoded_kinds(self, index: SourceIndex) -> set[str]:
        out: set[str] = set()
        for mod in index.iter_modules():
            if mod.tree is None or mod.rel == _OPLOG:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    out |= _kind_refs(arg)
        return out

    def _received_kinds(self, index: SourceIndex) -> set[str]:
        out: set[str] = set()
        for mod in index.iter_modules():
            if mod.tree is None:
                continue
            for qual, cls, fn in iter_functions(mod.tree):
                if fn.name != "oplog_received":
                    continue
                for node in ast.walk(fn):
                    if isinstance(node, ast.Compare):
                        out |= _kind_refs(node)
        return out
