"""meshcheck: the AST-based static-analysis plane.

Nine PRs grew the cache layer into a heavily threaded serving mesh whose
safety invariants (send-seam confinement, lifecycle/ownership/heat
single-writers, bounded waits, oplog-kind exhaustiveness) were enforced
by ~850 lines of regex greps across three lint test files. A regex
cannot see a lock acquired through a helper call, a write reached
through an alias, or a blocking call two frames down a hot path — this
package replaces the greps with real ``ast`` analysis:

- :mod:`core` — the pluggable framework: :class:`SourceIndex` (one AST
  parse per product file), the :class:`Checker` protocol, per-finding
  ``file:line`` + invariant-id reporting, and the justification-comment
  suppression grammar (``# meshcheck: ok[<invariant-id>] <why>``).
- :mod:`lock_order` — lock-acquisition graph per module (``with``
  nesting, including through one level of intra-module helper calls);
  fails on cycles and on re-entry into a non-reentrant lock.
- :mod:`single_writer` — assignment/call-site analysis for the
  lifecycle-state, shard-ownership, and shard-heat single-writer
  contracts (catches aliased writes and ``setattr``), plus the
  mesh send-seam confinement rule.
- :mod:`hot_path` — intra-package call graph from the serving entry
  points; flags reachable no-timeout ``wait()/join()/get()``,
  ``time.sleep``, and device-sync calls, and carries the tree-wide
  timeout/sleep audits.
- :mod:`wire_kinds` — every oplog kind in ``EXTENSION_KINDS`` /
  ``DATA_KINDS`` has an encode site, a receive branch, and a
  registration, verified structurally.
- :mod:`metrics_vocab` — the ``radixmesh_`` prefix + unit-suffix
  vocabulary, checked at ``counter()/gauge()/histogram()`` call sites.

Run it: ``python scripts/meshcheck.py`` (CI: the whole plane is one
quick-gate test, ``tests/test_analysis.py::test_tree_is_clean``).
"""

from __future__ import annotations

from .core import (
    AnalysisResult,
    Checker,
    Finding,
    SourceIndex,
    Suppression,
    package_root,
    run_checkers,
)
from .guarded_by import GuardedByChecker
from .hot_path import HotPathChecker
from .lock_order import LockOrderChecker
from .metrics_vocab import MetricsVocabChecker
from .protocol import ProtocolChecker
from .single_writer import SingleWriterChecker
from .thread_roots import ThreadRootsChecker, get_thread_map
from .wire_kinds import WireKindsChecker

__all__ = [
    "AnalysisResult",
    "Checker",
    "Finding",
    "SourceIndex",
    "Suppression",
    "package_root",
    "run_checkers",
    "all_checkers",
    "changed_scope",
    "LockOrderChecker",
    "SingleWriterChecker",
    "HotPathChecker",
    "WireKindsChecker",
    "MetricsVocabChecker",
    "ThreadRootsChecker",
    "GuardedByChecker",
    "ProtocolChecker",
    "get_thread_map",
]


def all_checkers() -> list:
    """One fresh instance of every registered checker, default config —
    the set ``scripts/meshcheck.py`` and the quick gate run. Order: the
    thread-root checker runs before guarded-by (both read the shared
    thread map, memoized on the index either way)."""
    return [
        LockOrderChecker(),
        SingleWriterChecker(),
        HotPathChecker(),
        WireKindsChecker(),
        MetricsVocabChecker(),
        ThreadRootsChecker(),
        GuardedByChecker(),
        ProtocolChecker(),
    ]


def changed_scope(index: SourceIndex, changed: list[str]) -> set[str]:
    """The file scope ``scripts/meshcheck.py --changed`` reports on: the
    changed package-relative modules plus every module that (transitively)
    imports one of them — a change can invalidate any finding computed in
    a module that calls into it. Unknown paths are ignored (deleted
    files)."""
    from .callgraph import get_callgraph

    dependents = get_callgraph(index).module_dependents()
    scope: set[str] = set()
    frontier = [rel for rel in changed if rel in index.modules]
    while frontier:
        rel = frontier.pop()
        if rel in scope:
            continue
        scope.add(rel)
        frontier.extend(dependents.get(rel, ()))
    return scope


import functools as _functools


@_functools.lru_cache(maxsize=1)
def tree_index() -> SourceIndex:
    """The installed package parsed once, cached for the process."""
    return SourceIndex(package_root())


@_functools.lru_cache(maxsize=1)
def check_tree() -> AnalysisResult:
    """The full default plane over the installed package, cached for
    the process — the quick-gate lint tests (test_mesh_lint /
    test_hotpath_lint / test_metrics_lint / test_analysis) all read
    slices of ONE run instead of re-parsing the tree per file. Source
    is assumed immutable within a process (true for tests and the
    CLI); call ``check_tree.cache_clear()`` / ``tree_index.cache_clear()``
    after editing files."""
    return run_checkers(tree_index(), all_checkers())
