"""Lock-order deadlock detection.

Extracts a lock-acquisition graph per module from the ASTs: a node per
lock object the module constructs (``self._lock = threading.Lock()``
attributes per class, plus module-level locks), an edge A→B wherever B
is acquired (``with``) while A is held — INCLUDING through one level of
intra-module helper calls (``with self._b: self._helper()`` where
``_helper`` does ``with self._a:`` yields B→A, the exact shape no grep
can see). Fails on:

- ``lock-order-cycle``: a cycle in the acquisition graph — two threads
  entering the cycle from different edges deadlock (the PR 6
  drain-claim race class).
- ``lock-order-reentry``: re-acquisition of a NON-reentrant lock
  (``threading.Lock`` / ``Condition``) while it is already held —
  self-deadlock on the spot. Re-entering an ``RLock`` is legal and
  ignored (the mesh's ``RLock`` does this by design).

Resolution is deliberately name-shaped, not type-inferred: a lock is
identified by ``(module, class, attribute)``. Cross-object acquisitions
(``other._lock``) are out of scope — the repo's discipline is that no
module reaches into another object's lock, which the single-writer and
send-seam checkers enforce from the other direction.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Checker, Finding, SourceIndex, dotted_name, iter_functions

__all__ = ["LockOrderChecker"]

_LOCK_KINDS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}


def _lock_ctor_kind(value: ast.expr) -> str | None:
    """``threading.Lock()`` / ``Lock()`` / ``threading.Condition(x)`` →
    the lock kind; None for any other initializer."""
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    if name is None:
        return None
    return _LOCK_KINDS.get(name.rsplit(".", 1)[-1])


@dataclass
class _Edge:
    src: str
    dst: str
    rel: str
    line: int
    via: str | None = None  # helper qualname when the edge crosses a call


@dataclass
class _FuncFacts:
    """Per-function facts from pass 1."""

    direct: list[tuple[str, int]] = field(default_factory=list)  # (lock, line)
    # (held locks at the call site, callee qualname, line)
    calls: list[tuple[tuple[str, ...], str, int]] = field(default_factory=list)


class LockOrderChecker:
    id = "lock-order"
    description = (
        "per-module lock-acquisition graph (with-nesting, one level of "
        "intra-module helper calls) must be acyclic; non-reentrant locks "
        "must never be re-acquired while held"
    )
    invariants = ("lock-order-cycle", "lock-order-reentry")

    def check(self, index: SourceIndex) -> list[Finding]:
        findings: list[Finding] = []
        edges: list[_Edge] = []
        kinds: dict[str, str] = {}  # lock id -> lock/rlock/condition
        for mod in index.iter_modules():
            if mod.tree is None:
                continue
            self._check_module(mod.rel, mod.tree, edges, kinds, findings)

        findings.extend(self._cycles(edges))
        return findings

    # ------------------------------------------------------------------
    # per-module extraction
    # ------------------------------------------------------------------

    def _check_module(self, rel, tree, edges, kinds, findings) -> None:
        # Lock inventory: module-level names + per-class self attributes.
        module_locks: dict[str, str] = {}  # name -> lock id
        class_locks: dict[str, dict[str, str]] = {}  # class -> attr -> id
        for node in tree.body:
            if isinstance(node, ast.Assign):
                kind = _lock_ctor_kind(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            lid = f"{rel}:{t.id}"
                            module_locks[t.id] = lid
                            kinds[lid] = kind
        for qual, cls, fn in iter_functions(tree):
            if cls is None:
                continue
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.Assign):
                    continue
                kind = _lock_ctor_kind(stmt.value)
                if not kind:
                    continue
                for t in stmt.targets:
                    name = dotted_name(t)
                    if name and name.startswith("self.") and name.count(".") == 1:
                        attr = name.split(".", 1)[1]
                        lid = f"{rel}:{cls}.{attr}"
                        class_locks.setdefault(cls, {})[attr] = lid
                        kinds[lid] = kind

        if not module_locks and not class_locks:
            return

        # Pass 1: per-function acquisition facts.
        facts: dict[str, _FuncFacts] = {}
        methods_by_class: dict[str, set[str]] = {}
        module_funcs: set[str] = set()
        for qual, cls, fn in iter_functions(tree):
            if cls is None:
                module_funcs.add(qual)
            else:
                methods_by_class.setdefault(cls, set()).add(fn.name)
        for qual, cls, fn in iter_functions(tree):
            f = facts[qual] = _FuncFacts()
            self._walk(
                fn.body, rel, cls, class_locks, module_locks, kinds,
                methods_by_class, module_funcs, (), f, edges, findings,
            )

        # Pass 2: one level of helper expansion — locks a callee acquires
        # directly are treated as acquired at the call site.
        for qual, f in facts.items():
            for held, callee, line in f.calls:
                callee_facts = facts.get(callee)
                if callee_facts is None:
                    continue
                for lock, _ in callee_facts.direct:
                    self._note_acquire(
                        lock, held, rel, line, kinds, edges, findings,
                        via=callee,
                    )

    def _resolve_lock(self, expr, cls, class_locks, module_locks) -> str | None:
        name = dotted_name(expr)
        if name is None:
            return None
        if name.startswith("self.") and name.count(".") == 1 and cls:
            return class_locks.get(cls, {}).get(name.split(".", 1)[1])
        if "." not in name:
            return module_locks.get(name)
        return None

    def _note_acquire(
        self, lock, held, rel, line, kinds, edges, findings, via=None,
    ) -> None:
        if lock in held:
            if kinds.get(lock) != "rlock":
                where = f" (via helper {via})" if via else ""
                findings.append(Finding(
                    rel, line, "lock-order-reentry",
                    f"non-reentrant lock {lock.split(':', 1)[1]!r} "
                    f"re-acquired while already held{where} — "
                    "self-deadlock",
                ))
            return  # re-entrant hold: no edge either way
        for h in held:
            edges.append(_Edge(h, lock, rel, line, via))

    def _walk(
        self, stmts, rel, cls, class_locks, module_locks, kinds,
        methods_by_class, module_funcs, held, f, edges, findings,
    ) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # a closure body runs later, not under this hold
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: list[str] = []
                inner_held = held
                for item in stmt.items:
                    lock = self._resolve_lock(
                        item.context_expr, cls, class_locks, module_locks
                    )
                    if lock is None:
                        continue
                    f.direct.append((lock, stmt.lineno))
                    self._note_acquire(
                        lock, inner_held, rel, stmt.lineno, kinds, edges,
                        findings,
                    )
                    if lock not in inner_held:
                        inner_held = inner_held + (lock,)
                        acquired.append(lock)
                self._walk(
                    stmt.body, rel, cls, class_locks, module_locks, kinds,
                    methods_by_class, module_funcs, inner_held, f, edges,
                    findings,
                )
                continue
            # Other compound statements keep the same held set: recurse
            # into their nested blocks, then scan only this statement's
            # OWN expressions for calls (nested blocks carry their own
            # context and are handled by the recursion).
            for blocks in self._nested_blocks(stmt):
                self._walk(
                    blocks, rel, cls, class_locks, module_locks, kinds,
                    methods_by_class, module_funcs, held, f, edges,
                    findings,
                )
            for node in self._own_expressions(stmt):
                if not isinstance(node, ast.Call):
                    continue
                callee = self._resolve_call(
                    node.func, cls, methods_by_class, module_funcs
                )
                if callee is not None:
                    f.calls.append((held, callee, node.lineno))

    @staticmethod
    def _nested_blocks(stmt: ast.stmt):
        """The statement-list children of a compound statement."""
        for name in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, name, None)
            if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
                yield sub
        for h in getattr(stmt, "handlers", []) or []:
            yield h.body
        for case in getattr(stmt, "cases", []) or []:
            yield case.body

    @staticmethod
    def _own_expressions(stmt: ast.stmt):
        """Walk the statement's expression parts without descending into
        nested statement blocks (those recurse separately)."""
        todo: list[ast.AST] = []
        for name, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                todo.append(value)
            elif isinstance(value, list):
                todo.extend(v for v in value if isinstance(v, ast.expr))
        for expr in todo:
            yield from ast.walk(expr)

    def _resolve_call(
        self, func, cls, methods_by_class, module_funcs,
    ) -> str | None:
        name = dotted_name(func)
        if name is None:
            return None
        if name.startswith("self.") and name.count(".") == 1 and cls:
            m = name.split(".", 1)[1]
            if m in methods_by_class.get(cls, ()):
                return f"{cls}.{m}"
            return None
        if "." not in name and name in module_funcs:
            return name
        return None

    # ------------------------------------------------------------------
    # cycle detection (Tarjan SCC over the global edge set)
    # ------------------------------------------------------------------

    def _cycles(self, edges: list[_Edge]) -> list[Finding]:
        graph: dict[str, set[str]] = {}
        by_pair: dict[tuple[str, str], _Edge] = {}
        for e in edges:
            if e.src == e.dst:
                continue
            graph.setdefault(e.src, set()).add(e.dst)
            graph.setdefault(e.dst, set())
            key = (e.src, e.dst)
            if key not in by_pair or (e.rel, e.line) < (
                by_pair[key].rel, by_pair[key].line
            ):
                by_pair[key] = e

        sccs = _tarjan(graph)
        findings = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            members = sorted(scc)
            cyc_edges = sorted(
                (e for (s, d), e in by_pair.items()
                 if s in scc and d in scc),
                key=lambda e: (e.rel, e.line),
            )
            site = cyc_edges[0]
            detail = "; ".join(
                f"{e.src.split(':', 1)[1]}->{e.dst.split(':', 1)[1]} at "
                f"{e.rel}:{e.line}"
                + (f" (via {e.via})" if e.via else "")
                for e in cyc_edges
            )
            findings.append(Finding(
                site.rel, site.line, "lock-order-cycle",
                f"lock-acquisition cycle {{{', '.join(members)}}}: {detail}",
            ))
        return findings


def _tarjan(graph: dict[str, set[str]]) -> list[set[str]]:
    """Iterative Tarjan strongly-connected components."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[set[str]] = []
    counter = [0]

    for start in sorted(graph):
        if start in index:
            continue
        work = [(start, iter(sorted(graph[start])))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc: set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs
