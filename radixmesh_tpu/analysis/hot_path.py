"""Hot-path blocking audit: call graph from the serving entry points.

Builds an intra-package call graph (name-shaped resolution: same-module
functions, ``self.`` methods, constructor-typed ``self.x`` / local
attributes, imported symbols) rooted at the serving entry points —
``Engine.step`` / ``Engine.enqueue`` (admission), ``match_prefix``,
``OverloadController.enqueue``, the disagg submit/step path, and the
oplog receive path — then flags what a grep scoped to one file can
never see: a blocking call two frames down.

Invariants:

- ``hotpath-blocking`` — a function REACHABLE from a serving entry
  point contains a no-timeout ``wait()/join()/get()``, a
  ``time.sleep``, or a device-sync call
  (``block_until_ready``/``jax.device_get``). The finding message
  carries the call chain from the entry point.
- ``timeout-audit`` — tree-wide: a blocking ``wait()/join()/get()``
  with NO timeout/deadline argument anywhere in product code parks a
  thread a dead peer can wedge forever (the PR 7 audit, AST-checked).
  The few intentionally unbounded seams carry in-source
  ``# meshcheck: ok[timeout-audit] <why>`` justifications.
- ``sleep-audit`` — tree-wide: every ``time.sleep`` product call site
  is either on a cold path with an in-source justification or a bug;
  hot ones surface as ``hotpath-blocking`` instead.
- ``hotpath-sync`` — the PR 4 staging boundary, scoped exactly as the
  old grep lint was: the engine scheduler, the hierarchical cache's
  match path, and the disagg admit path must not host-materialize KV
  (``np.asarray(pool.gather...)``, ``gather_padded``, inline
  ``host.read``) or force a device sync; ``cache/kv_transfer.py`` is
  the ONE module allowed to block on device→host data.
- ``hotpath-file-io`` — the PR 15 durable-tier boundary: no blocking
  file I/O (builtin ``open``, ``os.fsync``/``os.replace``/renames/
  unlinks/dir scans, pathlib read/write helpers) REACHABLE from a
  serving entry point. The disk tier (``cache/kv_tier.py``) does all
  of this on the KV-plane worker and on cold boot/drain paths; a
  refactor that drags an extent read into ``Engine.step``,
  ``match_prefix``, or the oplog receive path is a serving stall the
  size of a disk seek, and this invariant is how it gets caught two
  frames down.
"""

from __future__ import annotations

import ast

from .callgraph import get_callgraph
from .core import Checker, Finding, SourceIndex, dotted_name

__all__ = ["HotPathChecker", "DEFAULT_ENTRY_POINTS"]

# (module, qualname) serving entry points. Missing ones are skipped so
# the checker runs unmodified over positive-control fixture trees that
# mimic only one corner of the package.
DEFAULT_ENTRY_POINTS: tuple[tuple[str, str], ...] = (
    ("engine/engine.py", "Engine.step"),
    ("engine/engine.py", "Engine.enqueue"),
    ("cache/mesh_cache.py", "MeshCache.match_prefix"),
    ("cache/mesh_cache.py", "MeshCache.oplog_received"),
    ("slo/control.py", "OverloadController.enqueue"),
    ("engine/disagg.py", "DecodeWorker.submit"),
    ("engine/disagg.py", "DecodeWorker.step"),
)

# The designated sync owner (PR 4): allowed to block on device→host.
_SYNC_OWNER = "cache/kv_transfer.py"

# The old test_hotpath_lint scopes: (functions-or-whole-module, banned
# construct families). ``host_read`` is banned in the engine scheduler
# but NOT in the hierarchical cache's match path — ``match_and_load``'s
# arena read is the documented synchronous fallback; the fused sweep
# gather lives in the flush/plane seam.
_SYNC_SCOPES: dict[str, tuple[tuple[str, ...] | None, tuple[str, ...]]] = {
    # None = the whole module.
    "engine/engine.py": (
        None, ("device_sync", "gather", "host_read"),
    ),
    "cache/host_cache.py": (
        (
            "HierarchicalCache.match_and_load",
            "HierarchicalCache._writeback",
            "HierarchicalCache._evict_host",
        ),
        ("device_sync", "gather"),
    ),
    "engine/disagg.py": (
        ("DecodeWorker._admit_one",),
        ("device_sync", "any_asarray"),
    ),
}

_BLOCKING_ATTRS = ("wait", "join", "get")


def _module_sleep_names(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(bare names bound to time.sleep via ``from time import sleep``
    [as x], module aliases of ``time`` via ``import time as x``) — the
    import styles that would otherwise evade a dotted-name match."""
    bare: set[str] = set()
    mods: set[str] = {"time", "_time"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    bare.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mods.add(alias.asname or alias.name)
    return bare, mods


def _is_time_sleep(call: ast.Call, sleep_names=(), time_mods=("time", "_time")) -> bool:
    name = dotted_name(call.func)
    if name is None:
        return False
    parts = name.split(".")
    if len(parts) == 1:
        return parts[0] in sleep_names
    return len(parts) == 2 and parts[1] == "sleep" and parts[0] in time_mods


def _is_unbounded_blocking(call: ast.Call) -> str | None:
    """``x.wait()`` / ``x.join()`` / ``x.get()`` with NO argument at all
    (a timeout positional or keyword makes those bounded) — plus the
    ``get`` forms whose argument is the BLOCK flag, not a timeout:
    ``q.get(True)`` / ``q.get(block=True)`` park forever."""
    if not (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _BLOCKING_ATTRS
    ):
        return None
    attr = call.func.attr
    if not call.args and not call.keywords:
        return attr
    if attr == "get":
        kw = {k.arg: k.value for k in call.keywords}
        if "timeout" in kw or len(call.args) >= 2:
            return None
        block_true = (
            call.args
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value is True
        ) or (
            isinstance(kw.get("block"), ast.Constant)
            and kw["block"].value is True
        )
        if block_true:
            return "get"
    return None


# Blocking file-I/O shapes for the ``hotpath-file-io`` invariant:
# builtin/io open, the os-module file mutators the extent store uses,
# and the pathlib one-shot read/write helpers.
_FILE_IO_OS = {
    "os.fsync", "os.replace", "os.rename", "os.remove", "os.unlink",
    "os.listdir", "os.makedirs", "io.open",
}
_FILE_IO_ATTRS = ("write_bytes", "write_text", "read_bytes", "read_text")


def _is_file_io(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name == "open":
        return "open()"
    if name in _FILE_IO_OS:
        return name
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _FILE_IO_ATTRS
    ):
        return f".{call.func.attr}()"
    return None


def _is_device_sync(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Attribute) and call.func.attr == "block_until_ready":
        return "block_until_ready"
    name = dotted_name(call.func)
    if name in ("jax.device_get",):
        return "jax.device_get"
    return None


def _banned_construct(call: ast.Call, families: tuple[str, ...]) -> str | None:
    """The PR 4 constructs, by family: ``device_sync``
    (block_until_ready / jax.device_get), ``gather`` (np.asarray over a
    pool gather, the fused gather helper), ``host_read`` (inline
    host-arena read), ``any_asarray`` (the disagg admit path bans every
    host materialization)."""
    name = dotted_name(call.func)
    if "device_sync" in families:
        why = _is_device_sync(call)
        if why is not None:
            return why
    if "gather" in families:
        if name in ("gather_padded",) or (name or "").endswith(".gather_padded"):
            return "gather_padded"
        if name in ("np.asarray", "numpy.asarray") and call.args:
            inner = call.args[0]
            if isinstance(inner, ast.Call):
                inner_name = dotted_name(inner.func) or ""
                if inner_name.split(".")[-1] == "gather" and (
                    "pool" in inner_name.split(".")
                ):
                    return "np.asarray(pool.gather...)"
    if "host_read" in families:
        if name is not None and name.split(".")[-1] == "read" and (
            "host" in name.split(".")
        ):
            return "host.read"
    if "any_asarray" in families:
        if name in ("np.asarray", "numpy.asarray"):
            return "np.asarray"
    return None


class HotPathChecker:
    id = "hot-path"
    description = (
        "no blocking call (unbounded wait/join/get, time.sleep, device "
        "sync) reachable from a serving entry point; tree-wide "
        "timeout/sleep audits; the PR 4 staging boundary"
    )
    invariants = (
        "hotpath-blocking", "timeout-audit", "sleep-audit", "hotpath-sync",
        "hotpath-file-io",
    )

    def __init__(self, entry_points=DEFAULT_ENTRY_POINTS):
        self.entry_points = tuple(entry_points)

    # ------------------------------------------------------------------

    def check(self, index: SourceIndex) -> list[Finding]:
        # The shared call graph (analysis/callgraph.py) — symbol tables
        # and edges are built once per index and shared with the
        # thread-root and guarded-by checkers.
        cg = get_callgraph(index)
        funcs = cg.funcs
        reachable, chains = cg.reach(self.entry_points)
        findings: list[Finding] = []
        self._scan_blocking(index, funcs, reachable, chains, findings)
        self._scan_sync_scopes(index, funcs, findings)
        return findings

    # ------------------------------------------------------------------
    # scans
    # ------------------------------------------------------------------

    def _scan_blocking(self, index, funcs, reachable, chains, findings):
        sleep_names: dict[str, tuple[set[str], set[str]]] = {
            mod.rel: _module_sleep_names(mod.tree)
            for mod in index.iter_modules() if mod.tree is not None
        }
        for (rel, qual), f in funcs.items():
            if rel.startswith("analysis/"):
                continue
            hot = (rel, qual) in reachable
            bare, mods = sleep_names[rel]
            for node in ast.walk(f.node):
                if not isinstance(node, ast.Call):
                    continue
                label = None
                inv = None
                if _is_time_sleep(node, bare, mods):
                    label, inv = "time.sleep", "sleep-audit"
                else:
                    b = _is_unbounded_blocking(node)
                    if b is not None:
                        label, inv = f".{b}() without a timeout", "timeout-audit"
                    else:
                        d = _is_device_sync(node)
                        if d is not None and hot:
                            label, inv = d, "hotpath-blocking"
                        elif hot:
                            f_io = _is_file_io(node)
                            if f_io is not None:
                                label, inv = f_io, "hotpath-file-io"
                if label is None:
                    continue
                if inv == "hotpath-file-io":
                    chain = " -> ".join(chains[(rel, qual)])
                    findings.append(Finding(
                        rel, node.lineno, "hotpath-file-io",
                        f"{label} — blocking file I/O on a serving hot "
                        f"path (reached via {chain}); the disk tier "
                        "does file I/O only on the KV-plane worker "
                        "(cache/kv_tier.py threading contract)",
                    ))
                elif hot:
                    chain = " -> ".join(chains[(rel, qual)])
                    findings.append(Finding(
                        rel, node.lineno, "hotpath-blocking",
                        f"{label} on a serving hot path (reached via "
                        f"{chain})",
                    ))
                else:
                    findings.append(Finding(
                        rel, node.lineno, inv,
                        f"{label} — a dead peer (or a cold loop) parks "
                        "this thread unboundedly; pass a deadline or "
                        "justify in-source"
                        if inv == "timeout-audit"
                        else f"{label} off the hot path — convert to a "
                        "condition/deadline wait or justify in-source",
                    ))

        # Module-level statements (rare, but a sleep at import time is
        # still a sleep).
        for mod in index.iter_modules():
            if mod.tree is None or mod.rel.startswith("analysis/"):
                continue
            bare, mods = sleep_names[mod.rel]
            for stmt in mod.tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Call) and _is_time_sleep(node, bare, mods):
                        findings.append(Finding(
                            mod.rel, node.lineno, "sleep-audit",
                            "time.sleep at module scope",
                        ))

    def _scan_sync_scopes(self, index, funcs, findings):
        for rel, (scope, families) in _SYNC_SCOPES.items():
            if rel not in index:
                continue
            mod = index.module(rel)
            if mod.tree is None:
                continue
            if scope is None:
                nodes = [mod.tree]
            else:
                nodes = [
                    f.node for (r, q), f in funcs.items()
                    if r == rel and q in scope
                ]
            for root in nodes:
                for node in ast.walk(root):
                    if not isinstance(node, ast.Call):
                        continue
                    why = _banned_construct(node, families)
                    if why is not None:
                        findings.append(Finding(
                            rel, node.lineno, "hotpath-sync",
                            f"{why} — blocking KV materialization "
                            f"outside the staging module ({_SYNC_OWNER} "
                            "is the one sync owner)",
                        ))
