"""meshcheck framework core: source index, checker protocol, suppressions.

The contract every checker plugs into:

- :class:`SourceIndex` parses every product file ONCE (``ast`` + a
  tokenize pass for comments) and hands checkers the trees; no checker
  re-reads the filesystem, so a full run is one pass over the package.
- A checker is anything with an ``id``, a ``description``, and a
  ``check(index) -> list[Finding]`` method (:class:`Checker` protocol).
  Each :class:`Finding` names the violated invariant (a stable
  kebab-case id), the package-relative file, the 1-based line, and a
  human message — the file:line is load-bearing: the CI gate prints it
  and the suppression mechanism matches on it.
- Suppression is IN-SOURCE and justified, never config: a comment

      # meshcheck: ok[<invariant-id>(,<invariant-id>)*] <justification>

  on the offending line (or the line directly above it) excuses exactly
  the named invariants there. The justification text is REQUIRED — a
  bare ``ok[...]`` is itself a finding (``suppression-grammar``), and a
  suppression that no longer matches any finding is flagged
  (``stale-suppression``) so the excuse list can never rot — the same
  positive-control discipline the old grep allowlists enforced by hand.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, Protocol, Sequence, runtime_checkable

__all__ = [
    "Finding",
    "Suppression",
    "ModuleInfo",
    "SourceIndex",
    "Checker",
    "AnalysisResult",
    "run_checkers",
    "package_root",
    "iter_functions",
    "dotted_name",
    "FRAMEWORK_INVARIANTS",
]

# Invariant ids emitted by the framework itself (not by any checker).
FRAMEWORK_INVARIANTS = (
    "syntax-error",
    "suppression-grammar",
    "stale-suppression",
)


@dataclass(frozen=True, order=True)
class Finding:
    """One invariant violation at a concrete source location."""

    file: str  # package-relative posix path, e.g. "cache/mesh_cache.py"
    line: int  # 1-based
    invariant: str  # stable kebab-case id, e.g. "lock-order-cycle"
    message: str

    def __str__(self) -> str:  # the CLI / assertion rendering
        return f"{self.file}:{self.line}: [{self.invariant}] {self.message}"


@dataclass
class Suppression:
    """A parsed justification comment (``ok[...]`` / ``file-ok[...]``)."""

    file: str
    line: int  # where the directive itself sits
    invariants: tuple[str, ...]  # ("*",) suppresses any invariant here
    justification: str
    scope: str = "line"  # "line" | "file"
    anchor: int = 0  # last line of the contiguous comment block
    used: bool = False

    def __post_init__(self):
        if not self.anchor:
            self.anchor = self.line

    def covers(self, finding: Finding) -> bool:
        if finding.file != self.file:
            return False
        # Line scope: the directive's own line (trailing comment), any
        # line of its contiguous comment block, or the first line after
        # the block (comment-above style — multi-line justifications
        # are encouraged). File scope: anywhere in the file (the old
        # per-file grep-allowlist shape, e.g. the pallas
        # device-semaphore waits).
        if self.scope != "file" and not (
            self.line <= finding.line <= self.anchor + 1
        ):
            return False
        return "*" in self.invariants or finding.invariant in self.invariants


# Directive grammar. Valid bodies after the banner are
# ``ok[ids] justification`` (this line / the line below) and
# ``file-ok[ids] justification`` (the whole file — the shape of the old
# per-file grep allowlists). Anything else under the banner is a
# grammar error: a typo with no reason must not silently suppress
# nothing. (The banner is spelled split here so this comment does not
# itself register as a directive.)
_DIRECTIVE = re.compile("#\\s*" + "meshcheck" + ":\\s*(?P<body>.*)$")
_OK = re.compile(
    r"^(?P<scope>file-)?ok\[(?P<ids>[a-z0-9*][a-z0-9*,\- ]*)\]"
    r"\s*(?:[-—–:]\s*)?(?P<why>.*)$"
)


@dataclass
class ModuleInfo:
    """One parsed product file."""

    rel: str  # posix path relative to the package root
    path: Path
    source: str
    tree: ast.Module | None  # None when the file failed to parse
    suppressions: list[Suppression] = field(default_factory=list)
    grammar_errors: list[Finding] = field(default_factory=list)


def _parse_comments(rel: str, source: str) -> tuple[list[Suppression], list[Finding]]:
    """Tokenize-based comment scan: string literals that merely CONTAIN
    the directive text (this module's own docstring, tests) never
    register as suppressions."""
    sups: list[Suppression] = []
    errors: list[Finding] = []
    comment_lines: set[int] = set()
    pending: list[tuple[int, str]] = []  # (line, directive body)
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            comment_lines.add(tok.start[0])
            m = _DIRECTIVE.search(tok.string)
            if not m:
                continue
            line = tok.start[0]
            ok = _OK.match(m.group("body").strip())
            why = ok.group("why").strip() if ok else ""
            if not ok or not why:
                errors.append(Finding(
                    rel, line, "suppression-grammar",
                    "malformed meshcheck directive (grammar: "
                    "'# meshcheck: ok[<invariant-id>] <justification>'; "
                    "the justification is required): "
                    f"{tok.string.strip()!r}",
                ))
                continue
            ids = tuple(
                s.strip() for s in ok.group("ids").split(",") if s.strip()
            )
            scope = "file" if ok.group("scope") else "line"
            pending.append((line, ids, why, scope))
    except tokenize.TokenError:
        pass  # the ast parse reports the syntax error with a location
    for line, ids, why, scope in pending:
        anchor = line
        while anchor + 1 in comment_lines:
            anchor += 1
        sups.append(Suppression(rel, line, ids, why, scope, anchor))
    return sups, errors


def package_root() -> Path:
    """The installed ``radixmesh_tpu`` package directory — the default
    analysis root for the CLI and the CI gate."""
    import radixmesh_tpu

    return Path(radixmesh_tpu.__file__).parent


class SourceIndex:
    """Every ``*.py`` under ``root``, parsed once.

    ``root`` is a package-shaped directory: checkers address modules by
    posix-relative path (``cache/mesh_cache.py``), which is also how the
    positive-control fixtures mimic the real tree (a fixture directory
    containing ``engine/engine.py`` indexes identically to the product
    package, so checkers run on fixtures unmodified).
    """

    def __init__(self, root: Path | str):
        self.root = Path(root)
        self.modules: dict[str, ModuleInfo] = {}
        self.errors: list[Finding] = []
        for path in sorted(self.root.rglob("*.py")):
            rel = path.relative_to(self.root).as_posix()
            if "__pycache__" in rel:
                continue
            source = path.read_text()
            try:
                tree = ast.parse(source, filename=rel)
            except SyntaxError as e:
                tree = None
                self.errors.append(Finding(
                    rel, int(e.lineno or 1), "syntax-error",
                    f"file does not parse: {e.msg}",
                ))
            sups, gerrs = _parse_comments(rel, source)
            self.modules[rel] = ModuleInfo(rel, path, source, tree, sups, gerrs)

    def __contains__(self, rel: str) -> bool:
        return rel in self.modules

    def module(self, rel: str) -> ModuleInfo:
        return self.modules[rel]

    def iter_modules(self) -> Iterator[ModuleInfo]:
        for rel in sorted(self.modules):
            yield self.modules[rel]

    def suppressions(self) -> list[Suppression]:
        return [s for m in self.iter_modules() for s in m.suppressions]


@runtime_checkable
class Checker(Protocol):
    """What the framework requires of a checker plugin."""

    id: str
    description: str

    def check(self, index: SourceIndex) -> list[Finding]: ...


@dataclass
class AnalysisResult:
    """One full run: what survived suppression, what was excused, and
    the per-checker accounting the artifact schema pins."""

    findings: list[Finding]  # unsuppressed — the gate fails on any
    suppressed: list[tuple[Finding, Suppression]]
    raw_by_checker: dict[str, list[Finding]]
    kept_by_checker: dict[str, list[Finding]]
    suppressions: list[Suppression]

    @property
    def clean(self) -> bool:
        return not self.findings

    def pretty(self) -> str:
        if not self.findings:
            return "meshcheck: tree is clean"
        return "\n".join(str(f) for f in sorted(self.findings))


def run_checkers(
    index: SourceIndex,
    checkers: Sequence[Checker],
    flag_stale: bool = True,
) -> AnalysisResult:
    """Run every checker over the index, apply suppressions, and (in
    full runs) flag suppressions that excuse nothing. Scoped callers —
    the lint-test wrappers running a single checker — pass
    ``flag_stale=False`` because a suppression aimed at a checker that
    is not in ``checkers`` is not stale, just out of scope."""
    sups = index.suppressions()
    raw_by_checker: dict[str, list[Finding]] = {}
    kept_by_checker: dict[str, list[Finding]] = {}
    findings: list[Finding] = []
    suppressed: list[tuple[Finding, Suppression]] = []

    framework = list(index.errors)
    for m in index.iter_modules():
        framework.extend(m.grammar_errors)
    raw_by_checker["framework"] = framework

    seen: set[tuple[str, int, str]] = set()
    for checker in checkers:
        raw = checker.check(index)
        raw_by_checker[checker.id] = raw
        kept_by_checker[checker.id] = []
        for f in sorted(raw):
            key = (f.file, f.line, f.invariant)
            if key in seen:
                continue
            seen.add(key)
            sup = next((s for s in sups if s.covers(f)), None)
            if sup is not None:
                sup.used = True
                suppressed.append((f, sup))
            else:
                findings.append(f)
                kept_by_checker[checker.id].append(f)

    # Framework findings are never suppressible: a malformed directive
    # or an unparseable file must always surface.
    kept_by_checker["framework"] = list(framework)
    findings.extend(framework)

    if flag_stale:
        stale = [
            Finding(
                s.file, s.line, "stale-suppression",
                f"suppression for {','.join(s.invariants)} excuses no "
                f"finding — remove it (justification was: "
                f"{s.justification!r})",
            )
            for s in sups if not s.used
        ]
        kept_by_checker["framework"].extend(stale)
        raw_by_checker["framework"].extend(stale)
        findings.extend(stale)

    return AnalysisResult(
        findings=sorted(findings),
        suppressed=suppressed,
        raw_by_checker=raw_by_checker,
        kept_by_checker=kept_by_checker,
        suppressions=sups,
    )


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------

def iter_functions(
    tree: ast.Module,
) -> Iterator[tuple[str, str | None, ast.FunctionDef | ast.AsyncFunctionDef]]:
    """Yield ``(qualname, class_name, node)`` for every module-level
    function and every method of a module-level class. Nested defs
    (closures) are analyzed as part of their enclosing function's body
    by checkers that walk, so they are not yielded separately."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", node.name, sub


def dotted_name(node: ast.expr) -> str | None:
    """``self.mesh._lock`` → ``"self.mesh._lock"``; None when the
    expression is not a pure attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
