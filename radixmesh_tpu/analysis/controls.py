"""Positive-control harness: prove every checker still trips.

A static-analysis gate that reports "clean" is only evidence if the
checkers demonstrably still SEE the bug classes they claim to. Each
fixture under ``tests/fixtures/analysis/<name>/`` is a miniature
package tree with a deliberately seeded defect; this module runs the
full default checker set over each fixture and verifies that every
seeded marker trips with the right invariant-id at the right file:line.

Marker grammar (inside fixture files):

- ``# seeded: <invariant-id>[, <invariant-id>...]`` — trailing comment:
  a finding with each listed invariant must land on THIS line.
- ``# seeded-at: <rel-path>:<line> <invariant-id>`` — remote form, for
  lines where a trailing comment would change what is being tested
  (e.g. a malformed suppression directive).

``scripts/meshcheck.py`` embeds the results in the ANALYSIS artifact
(``positive_controls``), and ``bench.validate_analysis`` fails the
artifact if any control did not trip — the analysis-plane equivalent of
the old lint tests' ``test_positive_control_*`` methods, but enforced
for every checker uniformly.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from pathlib import Path

from .core import SourceIndex, run_checkers

__all__ = ["ControlExpectation", "run_positive_controls", "default_fixtures_root"]

_SEEDED = re.compile(r"#\s*seeded:\s*(?P<ids>[a-z0-9\-]+(?:\s*,\s*[a-z0-9\-]+)*)")
_SEEDED_AT = re.compile(
    r"#\s*seeded-at:\s*(?P<rel>\S+):(?P<line>\d+)\s+(?P<id>[a-z0-9\-]+)"
)


@dataclass
class ControlExpectation:
    """One seeded defect and whether the run reproduced it."""

    fixture: str
    invariant: str
    file: str  # fixture-relative posix path
    line: int
    tripped: bool = False

    def as_dict(self) -> dict:
        return {
            "fixture": self.fixture,
            "invariant": self.invariant,
            "file": self.file,
            "line": self.line,
            "tripped": self.tripped,
        }


def default_fixtures_root() -> Path:
    """``tests/fixtures/analysis`` resolved from the repo checkout this
    package was imported from (the fixtures are not shipped in wheels —
    callers outside a checkout pass an explicit root)."""
    import radixmesh_tpu

    return (
        Path(radixmesh_tpu.__file__).parent.parent
        / "tests" / "fixtures" / "analysis"
    )


def run_positive_controls(
    fixtures_root: Path | str | None = None,
    checker_factory=None,
) -> list[ControlExpectation]:
    """Run the default checkers over every fixture tree; return one
    expectation per seeded marker with its tripped verdict. An empty
    return means the fixtures directory is missing — callers treat that
    as a failure (controls that cannot run prove nothing)."""
    from . import all_checkers

    factory = checker_factory or all_checkers
    root = Path(fixtures_root) if fixtures_root else default_fixtures_root()
    out: list[ControlExpectation] = []
    if not root.is_dir():
        return out
    for fixture_dir in sorted(p for p in root.iterdir() if p.is_dir()):
        index = SourceIndex(fixture_dir)
        expectations = _collect_expectations(fixture_dir.name, index)
        if not expectations:
            continue
        # Markers match the UNSUPPRESSED findings — a control asserts
        # what the gate would actually fail on. Fixtures carry no
        # justification comments by design (stale flagging is therefore
        # irrelevant and off).
        result = run_checkers(index, factory(), flag_stale=False)
        hits = {(f.file, f.line, f.invariant) for f in result.findings}
        for exp in expectations:
            exp.tripped = (exp.file, exp.line, exp.invariant) in hits
            out.append(exp)
    return out


def _collect_expectations(
    fixture: str, index: SourceIndex
) -> list[ControlExpectation]:
    out: list[ControlExpectation] = []
    for mod in index.iter_modules():
        for i, text in enumerate(mod.source.splitlines(), start=1):
            m = _SEEDED.search(text)
            if m:
                for inv in re.split(r"\s*,\s*", m.group("ids")):
                    out.append(ControlExpectation(fixture, inv, mod.rel, i))
            m = _SEEDED_AT.search(text)
            if m:
                out.append(ControlExpectation(
                    fixture, m.group("id"), m.group("rel"),
                    int(m.group("line")),
                ))
    return out
