"""Tree-wide thread map: which functions can run concurrently with which.

The mesh runs on ~20 long-lived threads (ring sender loops,
``_owner_sender``, the repair scanner, the kv-transfer worker, the
lifecycle housekeeper, the recovery watcher, per-connection HTTP
handlers, the engine step loop) plus short-lived drain/hedge threads.
Every concurrency checker needs the same fact no single module can
state: *from which thread does this function run?* This module derives
it once per index:

- **Spawn discovery** — every ``threading.Thread(target=...)`` and
  ``threading.Timer(..., fn)`` call site, with the target resolved
  through the shared call graph (``self._run`` methods, module
  functions, constructor-typed attributes). A target that is a nested
  ``def`` (the recovery plane's hedge legs) maps to its ENCLOSING
  function — ``ast.walk`` already folds closure bodies into the
  enclosing frame's call edges, so reachability composes. A target on a
  known class with no in-package body (``self._server.serve_forever``)
  is an *external* root: real concurrency, no package-side frames —
  the handler-class rule below carries its in-package half.
- **HTTP handlers** — any class (module-level or nested) whose base
  names ``BaseHTTPRequestHandler``: each ``do_*`` method is a root, and
  the root is *multi* (``ThreadingHTTPServer`` runs one thread per
  connection, so a handler races with itself).
- **Declared roots** (:data:`DECLARED_ROOTS`) — call-graph seams the
  name-shaped resolver cannot cross (transport read callbacks into
  ``MeshCache.oplog_received``, runner-owned ``Engine.step``, the
  submit-side entry points). Pinned exactly like
  ``hot_path.DEFAULT_ENTRY_POINTS``; missing entries are skipped so the
  map builds unmodified over fixture trees.

A root is **multi** when more than one instance of it can be live at
once: spawned in a loop, spawned at ≥2 sites, an HTTP handler, or a
declared multi seam. Multi matters to the race checker: a single-
instance root cannot race with itself, but two connection handlers can.

Checker invariants (the map must stay COMPLETE to mean anything):

- ``thread-target-unresolved`` — a ``Thread``/``Timer`` target the map
  cannot resolve (lambda, computed callable, ``functools.partial``):
  every function it runs escapes the concurrency plane, so every
  guarded-by verdict downstream of it is unsound. Name a real function
  or justify in-source.
- ``thread-daemonless`` — a spawn without ``daemon=True``: a non-daemon
  thread that outlives ``close()`` wedges interpreter shutdown (the
  housekeeper bug class). Justify the rare thread that must survive.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .callgraph import CallGraph, get_callgraph
from .core import Checker, Finding, SourceIndex, dotted_name, iter_functions

__all__ = [
    "ThreadRoot",
    "ThreadMap",
    "ThreadRootsChecker",
    "DECLARED_ROOTS",
    "get_thread_map",
]

# (module, qualname, root name, multi) — concurrency entry points behind
# callback seams the name-shaped call graph cannot cross. The wire
# receive path runs on one transport reader thread PER PEER (multi), the
# engine step loop is the single runner thread, submits arrive on
# arbitrary caller/handler threads (multi).
DECLARED_ROOTS: tuple[tuple[str, str, str, bool], ...] = (
    ("cache/mesh_cache.py", "MeshCache.oplog_received", "wire-receive", True),
    ("engine/engine.py", "Engine.step", "engine-loop", False),
    ("engine/engine.py", "Engine.enqueue", "submit", True),
    ("slo/control.py", "OverloadController.enqueue", "slo-submit", True),
    ("engine/disagg.py", "DecodeWorker.submit", "disagg-submit", True),
    ("engine/disagg.py", "DecodeWorker.step", "disagg-loop", False),
    ("server/recovery.py", "RecoveryCoordinator.run_to_completion",
     "recovery-edge", True),
)


@dataclass(frozen=True)
class ThreadRoot:
    """One concurrency entry point."""

    name: str  # display name: thread name= literal, else target qual
    key: tuple[str, str] | None  # (rel, qual) start frame; None=external
    spawn_rel: str
    spawn_line: int
    multi: bool  # >1 instance can be live at once
    kind: str  # "spawn" | "timer" | "handler" | "declared" | "external"

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "target": None if self.key is None else f"{self.key[0]}:{self.key[1]}",
            "file": self.spawn_rel,
            "line": self.spawn_line,
            "multi": self.multi,
            "kind": self.kind,
        }


@dataclass
class ThreadMap:
    """The derived map: roots plus per-root reachable function sets."""

    roots: list[ThreadRoot] = field(default_factory=list)
    # function key -> tuple of root names that can be running it
    _roots_of: dict[tuple[str, str], tuple[str, ...]] = field(default_factory=dict)
    _multi: dict[str, bool] = field(default_factory=dict)
    # root name -> call chain per reachable function (finding messages)
    chains: dict[str, dict[tuple[str, str], tuple[str, ...]]] = field(
        default_factory=dict
    )

    def roots_of(self, key: tuple[str, str]) -> tuple[str, ...]:
        return self._roots_of.get(key, ())

    def is_multi(self, root_name: str) -> bool:
        return self._multi.get(root_name, False)

    def concurrent(self, roots_a, roots_b) -> bool:
        """Can an access on one of ``roots_a`` run concurrently with an
        access on one of ``roots_b``? Yes when the sets span two distinct
        roots, or share a multi-instance root."""
        a, b = set(roots_a), set(roots_b)
        if not a or not b:
            return False
        if (a | b) > a or (a | b) > b:
            return True  # two distinct roots exist across the pair
        if len(a | b) >= 2:
            return True
        return any(self._multi.get(r, False) for r in a & b)


_THREAD_CTORS = {"Thread": "spawn", "Timer": "timer"}


def _spawn_kind(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name is None:
        return None
    parts = name.split(".")
    last = parts[-1]
    if last not in _THREAD_CTORS:
        return None
    # `threading.Thread(...)` / bare `Thread(...)` (from-imports).
    if len(parts) == 1 or parts[0] in ("threading", "_threading"):
        return _THREAD_CTORS[last]
    return None


def _target_expr(call: ast.Call, kind: str) -> ast.expr | None:
    if kind == "spawn":
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
        if call.args:
            return call.args[1] if len(call.args) >= 2 else None  # (group, target)
        return None
    # Timer(interval, function)
    for kw in call.keywords:
        if kw.arg == "function":
            return kw.value
    return call.args[1] if len(call.args) >= 2 else None


def _literal_name(call: ast.Call) -> str | None:
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
            if isinstance(kw.value.value, str):
                return kw.value.value
    return None


def _daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return kw.value.value is True
    return False


def _enclosing_handler_classes(tree: ast.Module):
    """Every ClassDef (module-level or nested) whose base name ends with
    'BaseHTTPRequestHandler', with the enclosing function qual if any."""
    out = []  # (classdef, enclosing Func qual or None)
    for qual, cls, fn in iter_functions(tree):
        for node in ast.walk(fn):
            if isinstance(node, ast.ClassDef) and _is_handler(node):
                out.append((node, qual))
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and _is_handler(node):
            out.append((node, None))
    return out


def _is_handler(node: ast.ClassDef) -> bool:
    for base in node.bases:
        name = dotted_name(base) or ""
        if name.split(".")[-1] == "BaseHTTPRequestHandler":
            return True
    return False


def build_thread_map(
    index: SourceIndex, declared=DECLARED_ROOTS
) -> tuple[ThreadMap, list[Finding]]:
    """Derive the thread map; returns it plus the completeness findings
    (unresolved targets, daemonless spawns)."""
    cg = get_callgraph(index)
    findings: list[Finding] = []
    roots: list[ThreadRoot] = []
    spawn_count: dict[tuple[str, str], int] = {}  # target key -> sites

    for mod in index.iter_modules():
        if mod.tree is None or mod.rel.startswith("analysis/"):
            continue
        for qual, cls, fn in iter_functions(mod.tree):
            f = cg.funcs[(mod.rel, qual)]
            loops = _loop_spans(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                kind = _spawn_kind(node)
                if kind is None:
                    continue
                if kind == "spawn" and not _daemon_true(node):
                    findings.append(Finding(
                        mod.rel, node.lineno, "thread-daemonless",
                        "thread spawned without daemon=True — if it "
                        "outlives close() it wedges interpreter "
                        "shutdown; pass daemon=True or justify",
                    ))
                target = _target_expr(node, kind)
                key, external = _resolve_target(target, f, fn, cg)
                if key is None and not external:
                    findings.append(Finding(
                        mod.rel, node.lineno, "thread-target-unresolved",
                        f"{kind} target is not a resolvable function "
                        "reference — every frame it runs escapes the "
                        "concurrency plane (guarded-by verdicts go "
                        "unsound); name a def/method or justify",
                    ))
                    continue
                in_loop = any(a <= node.lineno <= b for a, b in loops)
                name = _literal_name(node) or (
                    key[1] if key is not None else "external"
                )
                if key is not None:
                    spawn_count[key] = spawn_count.get(key, 0) + 1
                roots.append(ThreadRoot(
                    name=name,
                    key=key,
                    spawn_rel=mod.rel,
                    spawn_line=node.lineno,
                    multi=in_loop or kind == "timer",
                    kind="external" if key is None else kind,
                ))
        # HTTP handler classes: each do_* method is a multi root. Nested
        # handler classes (the frontends define them inside __init__)
        # map to the enclosing function — its edge set already contains
        # the handler bodies' calls.
        for cls_node, enclosing in _enclosing_handler_classes(mod.tree):
            dos = [
                n.name for n in cls_node.body
                if isinstance(n, ast.FunctionDef) and n.name.startswith("do_")
            ]
            if not dos:
                continue
            if enclosing is not None:
                key = (mod.rel, enclosing)
            else:
                key = (mod.rel, f"{cls_node.name}.{dos[0]}")
                if key not in cg.funcs:
                    key = None
            roots.append(ThreadRoot(
                # Unique per enclosing frame: two frontends both nest a
                # class named Handler, and a name collision would drop
                # the second root's reachable set on the floor.
                name=f"http:{enclosing or cls_node.name}@{mod.rel}:{cls_node.lineno}",
                key=key,
                spawn_rel=mod.rel,
                spawn_line=cls_node.lineno,
                multi=True,
                kind="handler",
            ))

    # A target spawned from >=2 distinct sites has >=2 live instances.
    # Collapse by (name, target): two spawns of the SAME target under
    # one name are one logical root (multi via the >=2-sites rule); two
    # DIFFERENT targets sharing a display name are distinct live
    # threads and must both keep their reachable sets.
    counted: dict[tuple, ThreadRoot] = {}
    final: list[ThreadRoot] = []
    for r in roots:
        multi = r.multi or (r.key is not None and spawn_count.get(r.key, 0) >= 2)
        r = ThreadRoot(r.name, r.key, r.spawn_rel, r.spawn_line, multi, r.kind)
        ident = (r.name, r.key)
        prev = counted.get(ident)
        if prev is not None:
            if multi and not prev.multi:
                final[final.index(prev)] = counted[ident] = ThreadRoot(
                    prev.name, prev.key, prev.spawn_rel, prev.spawn_line,
                    True, prev.kind,
                )
            continue
        counted[ident] = r
        final.append(r)

    names = {r.name for r in final}
    for rel, qual, name, multi in declared:
        if (rel, qual) in cg.funcs and name not in names:
            final.append(ThreadRoot(
                name, (rel, qual), rel,
                cg.funcs[(rel, qual)].node.lineno, multi, "declared",
            ))

    # Concurrency is judged per NAME (ThreadMap._multi): a name shared
    # by two different targets means two live threads under one label,
    # so the whole group is multi — otherwise the shared name would
    # read as "one single-instance root" and hide real races.
    name_counts: dict[str, int] = {}
    for r in final:
        name_counts[r.name] = name_counts.get(r.name, 0) + 1
    final = [
        ThreadRoot(r.name, r.key, r.spawn_rel, r.spawn_line, True, r.kind)
        if name_counts[r.name] >= 2 and not r.multi else r
        for r in final
    ]

    tmap = ThreadMap(roots=final)
    roots_of: dict[tuple[str, str], set[str]] = {}
    for r in final:
        tmap._multi[r.name] = r.multi
        if r.key is None:
            continue
        reachable, chains = cg.reach([r.key])
        tmap.chains[r.name] = chains
        for key in reachable:
            roots_of.setdefault(key, set()).add(r.name)
    tmap._roots_of = {k: tuple(sorted(v)) for k, v in roots_of.items()}
    return tmap, findings


def _loop_spans(fn) -> list[tuple[int, int]]:
    return [
        (n.lineno, n.end_lineno or n.lineno)
        for n in ast.walk(fn)
        if isinstance(n, (ast.For, ast.While, ast.AsyncFor))
    ]


def _resolve_target(target, f, fn, cg: CallGraph):
    """Resolve a Thread/Timer target expression. Returns ``(key,
    external)``: a function key, or ``(None, True)`` for a known-object
    out-of-package method (stdlib serve_forever), or ``(None, False)``
    when genuinely unresolvable (lambda/partial/computed)."""
    if target is None:
        return None, False
    if isinstance(target, ast.Lambda) or isinstance(target, ast.Call):
        return None, False
    name = dotted_name(target)
    if name is None:
        return None, False
    # Nested def: the closure runs its enclosing frame's resolved calls
    # (ast.walk folds closure bodies into the enclosing function).
    parts = name.split(".")
    if len(parts) == 1:
        for node in ast.walk(fn):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == parts[0]
                and node is not fn
            ):
                return (f.rel, f.qual), False
    hits = list(cg.call_targets(target, f))
    if hits:
        return hits[0], False
    # self.<attr>.<method> where <attr> is constructor-typed to a known
    # class but the method body is out of package (inherited/stdlib):
    # real thread, no in-package frames.
    if len(parts) == 3 and parts[0] == "self" and f.cls is not None:
        if cg.attr_types.get((f.rel, f.cls), {}).get(parts[1]):
            return None, True
    return None, False


def get_thread_map(index: SourceIndex) -> ThreadMap:
    """The index's thread map, derived once per index instance (the
    guarded-by checker and the artifact writer share it)."""
    cached = getattr(index, "_thread_map", None)
    if cached is None:
        cached = build_thread_map(index)
        index._thread_map = cached
    return cached[0]


class ThreadRootsChecker:
    id = "thread-roots"
    description = (
        "tree-wide thread map: every Thread/Timer target resolves into "
        "the call graph (an escaped target blinds the concurrency "
        "plane) and spawns are daemon=True"
    )
    invariants = ("thread-target-unresolved", "thread-daemonless")

    def check(self, index: SourceIndex) -> list[Finding]:
        cached = getattr(index, "_thread_map", None)
        if cached is None:
            cached = build_thread_map(index)
            index._thread_map = cached
        return list(cached[1])
