"""Protocol state-machine checking: declared transition tables vs code.

``wire_kinds.py`` proved the structural idea on the oplog vocabulary:
read the DECLARED set off the AST, read the ACTUAL usage off the AST,
and flag the drift. This checker lifts it to state machines. The mesh
has two load-bearing ones — the membership lifecycle
(``policy/lifecycle.py``: BOOTSTRAPPING→ACTIVE→DRAINING→LEFT) and the
request admission lifecycle (``engine/request.py``: QUEUED↔RUNNING /
RESTORING → FINISHED) — and both have had "review hardening" races
where a site transitioned a state the table never allowed. Each
protocol declares its table IN SOURCE (``_VALID_TRANSITIONS`` /
``VALID_TRANSITIONS``, a set of ``(Enum.SRC, Enum.DST)`` tuples); the
checker extracts the actual relation from assignment and compare sites
across the whole package:

- ``protocol-undeclared-transition`` — an assignment
  ``x.state = Enum.DST`` whose SOURCE state is statically known (the
  innermost enclosing ``if`` compares the same ``.state`` expression
  against ``Enum.SRC``) but ``(SRC, DST)`` is not in the declared
  table; or any assignment/transition call whose DST never appears as a
  destination in the table at all. Assignments inside the declared
  transition function (which validates at runtime) and class-body
  defaults are exempt.
- ``protocol-no-exit`` — an enum member with no outgoing edge in the
  table that is not a declared terminal: a state the machine can enter
  but never leave (reported at the member's declaration line).
- ``protocol-unhandled-state`` — a dispatch site (an ``if``/``elif``
  chain comparing one ``.state`` expression against ≥2 distinct members
  with no ``else``) that does not cover every declared state — the
  uncovered state falls through silently, the exact shape of the PR 9
  heat-gauge clearing bug and wire_kinds' fall-through-to-data-apply.
- ``protocol-no-table`` — the protocol's module parses but its declared
  table vanished: the whole check would silently become vacuous
  (the stale-suppression rule, applied to the checker's own config).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Checker, Finding, SourceIndex, dotted_name, iter_functions

__all__ = ["ProtocolChecker", "ProtocolSpec", "DEFAULT_PROTOCOLS"]


@dataclass(frozen=True)
class ProtocolSpec:
    """One checked state machine."""

    name: str
    module: str  # where the enum + table are declared
    enum: str  # enum class name, e.g. "LifecycleState"
    table: str  # module-level set of (Enum.SRC, Enum.DST) tuples
    state_attrs: tuple[str, ...]  # attribute names holding this state
    terminals: tuple[str, ...]  # states that legally have no exit
    # Functions whose bodies assign the state after validating against
    # the table at runtime (the single-writer transition seam).
    transition_fns: tuple[str, ...] = ()


DEFAULT_PROTOCOLS: tuple[ProtocolSpec, ...] = (
    ProtocolSpec(
        name="lifecycle",
        module="policy/lifecycle.py",
        enum="LifecycleState",
        table="_VALID_TRANSITIONS",
        state_attrs=("_state",),
        terminals=("LEFT",),
        transition_fns=("LifecyclePlane._transition", "LifecyclePlane.__init__"),
    ),
    ProtocolSpec(
        name="request",
        module="engine/request.py",
        enum="RequestState",
        table="VALID_TRANSITIONS",
        state_attrs=("state",),
        terminals=("FINISHED",),
        transition_fns=("Request.__init__",),
    ),
)


class ProtocolChecker:
    id = "protocol"
    description = (
        "state machines match their declared transition tables: no "
        "undeclared transition, no non-terminal state without an exit, "
        "no state dispatch that silently drops a declared state"
    )
    invariants = (
        "protocol-undeclared-transition",
        "protocol-no-exit",
        "protocol-unhandled-state",
        "protocol-no-table",
    )

    def __init__(self, protocols=DEFAULT_PROTOCOLS):
        self.protocols = tuple(protocols)

    def check(self, index: SourceIndex) -> list[Finding]:
        findings: list[Finding] = []
        for spec in self.protocols:
            if spec.module not in index:
                continue  # fixture trees mimic one corner of the package
            mod = index.module(spec.module)
            if mod.tree is None:
                continue
            members = self._enum_members(mod.tree, spec.enum)
            if not members:
                continue
            table, table_line = self._table(mod.tree, spec)
            if table_line is None:
                findings.append(Finding(
                    spec.module, 1, "protocol-no-table",
                    f"{spec.enum} has no declared transition table "
                    f"{spec.table!r} — the {spec.name} protocol check "
                    "is vacuous without it",
                ))
                continue
            self._check_exits(spec, members, table, findings)
            for m in index.iter_modules():
                if m.tree is None or m.rel.startswith("analysis/"):
                    continue
                self._check_module(spec, m.rel, m.tree, members, table, findings)
        return findings

    # ------------------------------------------------------------------
    # declarations
    # ------------------------------------------------------------------

    def _enum_members(self, tree, enum_name) -> dict[str, int]:
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == enum_name:
                out: dict[str, int] = {}
                for stmt in node.body:
                    if (
                        isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                    ):
                        out[stmt.targets[0].id] = stmt.lineno
                return out
        return {}

    def _table(self, tree, spec) -> tuple[set[tuple[str, str]], int | None]:
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == spec.table
            ):
                edges: set[tuple[str, str]] = set()
                for elt in ast.walk(node.value):
                    if isinstance(elt, ast.Tuple) and len(elt.elts) == 2:
                        pair = [self._member(e, spec.enum) for e in elt.elts]
                        if None not in pair:
                            edges.add((pair[0], pair[1]))
                return edges, node.lineno
        return set(), None

    @staticmethod
    def _member(node, enum_name) -> str | None:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == enum_name
        ):
            return node.attr
        return None

    def _check_exits(self, spec, members, table, findings) -> None:
        sources = {s for s, _ in table}
        for name, line in members.items():
            if name not in sources and name not in spec.terminals:
                findings.append(Finding(
                    spec.module, line, "protocol-no-exit",
                    f"{spec.enum}.{name} has no outgoing edge in "
                    f"{spec.table} and is not a declared terminal — a "
                    "machine entering it can never leave",
                ))

    # ------------------------------------------------------------------
    # actual transitions + dispatch exhaustiveness
    # ------------------------------------------------------------------

    def _check_module(self, spec, rel, tree, members, table, findings) -> None:
        destinations = {d for _, d in table}
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        for qual, cls, fn in iter_functions(tree):
            exempt = rel == spec.module and qual in spec.transition_fns
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    dst = self._member(node.value, spec.enum)
                    if dst is None:
                        continue
                    for t in node.targets:
                        attr_chain = dotted_name(t)
                        if attr_chain is None or "." not in attr_chain:
                            continue
                        if attr_chain.rsplit(".", 1)[1] not in spec.state_attrs:
                            continue
                        if exempt:
                            continue
                        src = self._known_source(
                            node, t, attr_chain, spec, parents
                        )
                        if src is not None and (src, dst) not in table:
                            findings.append(Finding(
                                rel, node.lineno,
                                "protocol-undeclared-transition",
                                f"{spec.enum}: transition {src} -> {dst} "
                                f"is not in {spec.module}:{spec.table} — "
                                "declare it or fix the site",
                            ))
                        elif src is None and dst not in destinations:
                            findings.append(Finding(
                                rel, node.lineno,
                                "protocol-undeclared-transition",
                                f"{spec.enum}: assignment to {dst}, which "
                                f"is a destination of NO declared edge in "
                                f"{spec.module}:{spec.table}",
                            ))
                elif isinstance(node, ast.Call):
                    # self._transition(Enum.DST): the runtime validator —
                    # statically, DST must at least be a declared
                    # destination.
                    fname = dotted_name(node.func) or ""
                    short = fname.split(".")[-1]
                    if not any(
                        short == t.split(".")[-1] for t in spec.transition_fns
                    ):
                        continue
                    for arg in node.args:
                        dst = self._member(arg, spec.enum)
                        if dst is not None and dst not in destinations:
                            findings.append(Finding(
                                rel, node.lineno,
                                "protocol-undeclared-transition",
                                f"{spec.enum}: {short}({spec.enum}.{dst}) "
                                f"targets a state that is a destination "
                                f"of NO declared edge in "
                                f"{spec.module}:{spec.table}",
                            ))

            self._check_dispatches(spec, rel, fn, members, findings)

    def _known_source(self, assign, target, attr_chain, spec, parents):
        """The statically-known source state of an assignment: the
        innermost enclosing ``if`` whose test compares the SAME dotted
        ``.state`` chain against one member with ``is``/``==``, with the
        assignment in the body (not orelse). None = unknown (legal —
        most sites transition from several states)."""
        node = assign
        while True:
            parent = parents.get(node)
            if parent is None:
                return None
            if isinstance(parent, ast.If) and node in getattr(parent, "body", []):
                src = self._compare_member(parent.test, attr_chain, spec)
                if src is not None:
                    return src
            node = parent

    def _compare_member(self, test, attr_chain, spec):
        if not (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.Eq))
        ):
            return None
        if dotted_name(test.left) != attr_chain:
            return None
        return self._member(test.comparators[0], spec.enum)

    def _check_dispatches(self, spec, rel, fn, members, findings) -> None:
        """An if/elif chain testing one ``.state`` expression against ≥2
        distinct members with no else must cover every declared state."""
        chains_seen: set[int] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.If) or node.lineno in chains_seen:
                continue
            handled: list[str] = []
            subject: str | None = None
            cur: ast.If | None = node
            exhaustive_else = False
            while cur is not None:
                m = self._dispatch_test(cur.test, spec)
                if m is None:
                    handled = []
                    break
                chain_subject, member = m
                if subject is None:
                    subject = chain_subject
                elif subject != chain_subject:
                    handled = []
                    break
                handled.append(member)
                chains_seen.add(cur.lineno)
                if len(cur.orelse) == 1 and isinstance(cur.orelse[0], ast.If):
                    cur = cur.orelse[0]
                elif cur.orelse:
                    exhaustive_else = True
                    cur = None
                else:
                    cur = None
            if exhaustive_else or len(set(handled)) < 2:
                continue
            missing = sorted(set(members) - set(handled))
            if missing:
                findings.append(Finding(
                    rel, node.lineno, "protocol-unhandled-state",
                    f"{spec.enum} dispatch on {subject!r} handles "
                    f"{sorted(set(handled))} with no else — "
                    f"{missing} fall(s) through silently; handle them "
                    "or add an else",
                ))

    def _dispatch_test(self, test, spec):
        """``x.state is Enum.M`` → (dotted subject, member)."""
        if not (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.Eq))
        ):
            return None
        subject = dotted_name(test.left)
        if subject is None:
            return None
        if subject.rsplit(".", 1)[-1] not in spec.state_attrs:
            return None
        member = self._member(test.comparators[0], spec.enum)
        if member is None:
            return None
        return subject, member
