"""Single-writer enforcement: lifecycle state, shard ownership, shard
heat — plus the mesh send-seam confinement rule.

Replaces the three grep single-writer lints (``test_mesh_lint.py``'s
``TestLifecycleStateOwnership`` / ``TestOwnershipSingleWriter`` /
``TestShardHeatSingleWriter``) with assignment/call-site AST analysis
that also catches what a grep cannot:

- **aliased writes** — ``st = LifecycleState.ACTIVE`` followed by
  ``plane.state = st`` is two findings, not an invisible write; the
  same for ``OM = OwnershipMap`` / ``note = heat.note_insert`` aliases
  of a guarded constructor or counting method;
- **setattr** — ``setattr(plane, "state", LifecycleState.ACTIVE)`` and
  ``setattr(m, "owners", ...)`` are writes, not string operations;
- **comparison reads stay legal** — ``if st is LifecycleState.ACTIVE``
  and ``d.lifecycle != LifecycleState.ACTIVE.value`` bind nothing.

Invariants:

- ``single-writer-lifecycle`` — only ``policy/lifecycle.py`` may bind a
  ``LifecycleState`` value (a module that could flip a node to ACTIVE
  mid-bootstrap silently re-enables cold hit-routing).
- ``single-writer-ownership`` — only ``cache/sharding.py`` constructs
  an ``OwnershipMap`` or pokes ``.owners`` (two nodes deriving
  different owner sets for one shard is delivery-plane split-brain).
- ``single-writer-overrides`` — only ``cache/rebalance.py`` constructs
  a ``ShardOverrides`` or pokes ``.moves`` (a second decision-maker
  forking the override map forks the effective owner sets the whole
  delivery plane derives from — the same split-brain one layer up).
- ``single-writer-heat`` — only ``cache/mesh_cache.py`` (and the
  defining ``cache/sharding.py``) constructs ``ShardHeat`` or calls
  ``note_insert/note_hit/note_pull`` (a second counter double-counts
  the same traffic and skews the rebalancer signal).
- ``send-seam`` — in ``cache/mesh_cache.py``, no raw ``.send(`` at all,
  and ``.try_send(`` only inside the documented seam methods (sender
  loops, router fan-out, graceful close, the droppable dedicated
  channels).
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, SourceIndex, dotted_name, iter_functions

__all__ = ["SingleWriterChecker"]

# The ONLY MeshCache methods allowed to touch a transport's try_send:
# the two sender-thread loops, the (sender-thread-only) router fan-out,
# the best-effort graceful-close announcement, and the dedicated
# fire-and-forget channels — each short-deadline and droppable by
# contract. (Carried over from the grep lint's ALLOWED_TRY_SEND.)
ALLOWED_TRY_SEND = (
    "_sender_loop",
    "_fan_out_to_routers",
    "close",
    "send_prefetch",
    "send_repair",
    "_owner_sender",
    "send_shard_pull",
)

_MESH = "cache/mesh_cache.py"
_HEAT_NOTES = ("note_insert", "note_hit", "note_pull")


def _contains_state_value(expr: ast.expr) -> int | None:
    """Line of a ``LifecycleState.X`` value USED AS A VALUE inside
    ``expr`` (i.e. not merely compared against); None when the
    expression only reads/compares."""
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(expr):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "LifecycleState"
        ):
            p = parents.get(node)
            inside_compare = False
            while p is not None:
                if isinstance(p, ast.Compare):
                    inside_compare = True
                    break
                p = parents.get(p)
            if not inside_compare:
                return node.lineno
    return None


class SingleWriterChecker:
    id = "single-writer"
    description = (
        "lifecycle state / shard ownership / shard heat each have ONE "
        "writer module (aliases and setattr count as writes); mesh "
        "network sends are confined to the try_send seam methods"
    )
    invariants = (
        "single-writer-lifecycle", "single-writer-ownership",
        "single-writer-overrides", "single-writer-heat", "send-seam",
    )

    def check(self, index: SourceIndex) -> list[Finding]:
        findings: list[Finding] = []
        for mod in index.iter_modules():
            if mod.tree is None or mod.rel.startswith("analysis/"):
                continue
            if mod.rel != "policy/lifecycle.py":
                self._lifecycle(mod.rel, mod.tree, findings)
            if mod.rel != "cache/sharding.py":
                self._ownership(mod.rel, mod.tree, findings)
            if mod.rel != "cache/rebalance.py":
                self._overrides(mod.rel, mod.tree, findings)
            if mod.rel not in ("cache/sharding.py", _MESH):
                self._heat(mod.rel, mod.tree, findings)
            if mod.rel == _MESH:
                self._send_seam(mod.rel, mod.tree, findings)
        return findings

    # ------------------------------------------------------------------
    # lifecycle state
    # ------------------------------------------------------------------

    def _lifecycle(self, rel: str, tree: ast.Module, out: list[Finding]) -> None:
        # Pass 1: every name bound to a LifecycleState value anywhere in
        # the module (``ast.walk`` is breadth-first, so a one-pass scan
        # would miss a store that lexically follows a binding nested in
        # a deeper block). A later attribute-store through such an alias
        # is the grep-invisible second write.
        aliases: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                if node.value is None:
                    continue
                if _contains_state_value(node.value) is not None:
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Name):
                            aliases.add(t.id)
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = node.value
                if value is None:
                    continue
                line = _contains_state_value(value)
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                if line is not None:
                    out.append(Finding(
                        rel, node.lineno, "single-writer-lifecycle",
                        "binds a LifecycleState value outside "
                        "policy/lifecycle.py (single-writer contract: "
                        "ask the plane to transition instead)",
                    ))
                    continue
                # Attribute store THROUGH an alias of a state value.
                if (
                    isinstance(value, ast.Name) and value.id in aliases
                    and any(isinstance(t, ast.Attribute) for t in targets)
                ):
                    out.append(Finding(
                        rel, node.lineno, "single-writer-lifecycle",
                        f"writes lifecycle state through alias "
                        f"{value.id!r} outside policy/lifecycle.py",
                    ))
            elif isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "setattr"
                    and len(node.args) >= 3
                    and (
                        _contains_state_value(node.args[2]) is not None
                        or (
                            isinstance(node.args[2], ast.Name)
                            and node.args[2].id in aliases
                        )
                    )
                ):
                    out.append(Finding(
                        rel, node.lineno, "single-writer-lifecycle",
                        "setattr of a LifecycleState value outside "
                        "policy/lifecycle.py",
                    ))

    # ------------------------------------------------------------------
    # ownership map
    # ------------------------------------------------------------------

    def _ownership(self, rel: str, tree: ast.Module, out: list[Finding]) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name) and node.func.id == "OwnershipMap":
                    out.append(Finding(
                        rel, node.lineno, "single-writer-ownership",
                        "constructs an OwnershipMap outside "
                        "cache/sharding.py — derive through "
                        "build_ownership() and treat the result as "
                        "immutable",
                    ))
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "setattr"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and node.args[1].value == "owners"
                ):
                    out.append(Finding(
                        rel, node.lineno, "single-writer-ownership",
                        "setattr on an ownership map's owner set outside "
                        "cache/sharding.py",
                    ))
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    base = t
                    if isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Attribute) and base.attr == "owners":
                        out.append(Finding(
                            rel, node.lineno, "single-writer-ownership",
                            "mutates an ownership map's .owners outside "
                            "cache/sharding.py (split-brain on the "
                            "delivery plane)",
                        ))
                # Aliasing the constructor is a write waiting to happen.
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "OwnershipMap"
                ):
                    out.append(Finding(
                        rel, node.lineno, "single-writer-ownership",
                        "aliases the OwnershipMap constructor outside "
                        "cache/sharding.py",
                    ))

    # ------------------------------------------------------------------
    # ownership overrides (cache/rebalance.py)
    # ------------------------------------------------------------------

    def _overrides(self, rel: str, tree: ast.Module, out: list[Finding]) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "ShardOverrides"
                ):
                    out.append(Finding(
                        rel, node.lineno, "single-writer-overrides",
                        "constructs a ShardOverrides outside "
                        "cache/rebalance.py — decisions flow through the "
                        "rebalance plane; everything else folds whole "
                        "immutable instances",
                    ))
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "setattr"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and node.args[1].value == "moves"
                ):
                    out.append(Finding(
                        rel, node.lineno, "single-writer-overrides",
                        "setattr on an override map's move set outside "
                        "cache/rebalance.py",
                    ))
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    base = t
                    if isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Attribute) and base.attr == "moves":
                        out.append(Finding(
                            rel, node.lineno, "single-writer-overrides",
                            "mutates an override map's .moves outside "
                            "cache/rebalance.py (forked owner sets on "
                            "the delivery plane)",
                        ))
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "ShardOverrides"
                ):
                    out.append(Finding(
                        rel, node.lineno, "single-writer-overrides",
                        "aliases the ShardOverrides constructor outside "
                        "cache/rebalance.py",
                    ))

    # ------------------------------------------------------------------
    # shard heat
    # ------------------------------------------------------------------

    def _heat(self, rel: str, tree: ast.Module, out: list[Finding]) -> None:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "ShardHeat"
            ):
                out.append(Finding(
                    rel, node.lineno, "single-writer-heat",
                    "constructs a ShardHeat outside cache/mesh_cache.py "
                    "(single-writer contract)",
                ))
            elif isinstance(node, ast.Attribute) and node.attr in _HEAT_NOTES:
                # Any access — a call counts traffic; a bare alias load
                # is the grep-invisible way to smuggle the call out.
                out.append(Finding(
                    rel, node.lineno, "single-writer-heat",
                    f"touches the heat counter {node.attr}() outside "
                    "cache/mesh_cache.py — the same traffic would be "
                    "double-counted",
                ))
            elif (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Name)
                and node.value.id == "ShardHeat"
            ):
                out.append(Finding(
                    rel, node.lineno, "single-writer-heat",
                    "aliases the ShardHeat constructor outside "
                    "cache/mesh_cache.py",
                ))

    # ------------------------------------------------------------------
    # send seam (mesh_cache only)
    # ------------------------------------------------------------------

    def _send_seam(self, rel: str, tree: ast.Module, out: list[Finding]) -> None:
        allowed_spans: list[tuple[int, int]] = []
        for qual, cls, fn in iter_functions(tree):
            if fn.name in ALLOWED_TRY_SEND:
                allowed_spans.append((fn.lineno, fn.end_lineno or fn.lineno))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr == "send":
                out.append(Finding(
                    rel, node.lineno, "send-seam",
                    "raw .send( in mesh_cache.py — a blocking, failure-"
                    "detection-blind network touch; use the bounded "
                    "try_send seam",
                ))
            elif node.func.attr == "try_send":
                if not any(a <= node.lineno <= b for a, b in allowed_spans):
                    out.append(Finding(
                        rel, node.lineno, "send-seam",
                        "try_send outside the allowed seam methods "
                        f"{ALLOWED_TRY_SEND} — route new network writes "
                        "through the sender loop or a documented "
                        "dedicated-channel method",
                    ))
