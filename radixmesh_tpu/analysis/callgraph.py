"""Shared intra-package call graph — the resolution substrate under the
hot-path, thread-root, and guarded-by checkers.

One graph per :class:`~radixmesh_tpu.analysis.core.SourceIndex`, built
once and memoized on the index (``get_callgraph``): every checker that
needs reachability ("which functions can this entry point reach", "which
thread roots can run this function") reads the same edges instead of
re-deriving its own.

Resolution is deliberately name-shaped, the same discipline the
lock-order checker documents: same-module functions, ``self.`` methods,
constructor-typed ``self.x`` / local attributes, imported symbols, and
nested ``def``s (a closure handed to ``threading.Thread`` executes its
enclosing function's resolved calls — ``ast.walk`` already folds the
closure body into the enclosing frame's edge set). Unresolvable calls
(first-class callbacks, computed attributes) simply contribute no edge;
checkers that NEED those edges pin explicit roots/entry points instead
(``hot_path.DEFAULT_ENTRY_POINTS``, ``thread_roots.DECLARED_ROOTS``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import SourceIndex, dotted_name, iter_functions

__all__ = ["Func", "CallGraph", "get_callgraph"]


@dataclass(frozen=True)
class Func:
    rel: str
    qual: str  # "Class.method" or "func"
    cls: str | None
    node: ast.AST

    @property
    def key(self) -> tuple[str, str]:
        return (self.rel, self.qual)


class CallGraph:
    """Functions, classes, imports, constructor-typed attributes, and
    call edges for one parsed package tree."""

    def __init__(self, index: SourceIndex):
        self.index = index
        self.funcs: dict[tuple[str, str], Func] = {}
        self.classes: dict[str, dict[str, str]] = {}  # class name -> {rel}
        self.imports: dict[str, dict[str, str]] = {}
        self.attr_types: dict[tuple[str, str], dict[str, tuple[str, str]]] = {}
        self._build_symbols(index)
        self.edges = self._build_edges(index)

    # ------------------------------------------------------------------
    # symbol tables
    # ------------------------------------------------------------------

    def _build_symbols(self, index: SourceIndex) -> None:
        for mod in index.iter_modules():
            if mod.tree is None:
                continue
            for qual, cls, fn in iter_functions(mod.tree):
                self.funcs[(mod.rel, qual)] = Func(mod.rel, qual, cls, fn)
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    self.classes.setdefault(node.name, {})[mod.rel] = node.name

        # Per-module import map: name -> module rel it came from.
        for mod in index.iter_modules():
            if mod.tree is None:
                continue
            imap: dict[str, str] = {}
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ImportFrom):
                    target = self._resolve_import(mod.rel, node, index)
                    if target is None:
                        continue
                    for alias in node.names:
                        imap[alias.asname or alias.name] = target
                elif isinstance(node, ast.Import):
                    # `import radixmesh_tpu.cache.oplog as oplog_mod`:
                    # the edge matters to module_dependents() (the
                    # --changed scope widener) even though name-shaped
                    # call resolution rarely crosses it.
                    for alias in node.names:
                        if not alias.name.startswith("radixmesh_tpu."):
                            continue
                        parts = alias.name.split(".")[1:]
                        cand = "/".join(parts) + ".py"
                        if cand not in index:
                            cand = "/".join(parts) + "/__init__.py"
                            if cand not in index:
                                continue
                        imap[alias.asname or alias.name] = cand
            self.imports[mod.rel] = imap

        # Constructor-typed self attributes: self.x = ClassName(...) in
        # any method -> (class scope) x: rel-of-ClassName + ClassName.
        for mod in index.iter_modules():
            if mod.tree is None:
                continue
            for qual, cls, fn in iter_functions(mod.tree):
                if cls is None:
                    continue
                for node in ast.walk(fn):
                    if not (
                        isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Name)
                    ):
                        continue
                    cname = node.value.func.id
                    crel = self.class_rel(cname, mod.rel)
                    if crel is None:
                        continue
                    for t in node.targets:
                        name = dotted_name(t)
                        if name and name.startswith("self.") and name.count(".") == 1:
                            self.attr_types.setdefault((mod.rel, cls), {})[
                                name.split(".", 1)[1]
                            ] = (crel, cname)

    def _resolve_import(self, rel: str, node: ast.ImportFrom, index) -> str | None:
        """Map an ImportFrom to a package-relative module path, or None
        for out-of-package imports."""
        if node.level == 0:
            mod = node.module or ""
            if not mod.startswith("radixmesh_tpu"):
                return None
            parts = mod.split(".")[1:]
        else:
            base = rel.split("/")[:-1]
            up = node.level - 1
            parts = (base[: len(base) - up] if up else base) + (
                node.module.split(".") if node.module else []
            )
        cand = "/".join(parts) + ".py"
        if cand in index:
            return cand
        pkg = "/".join(parts) + "/__init__.py"
        if pkg in index:
            return pkg
        return None

    def class_rel(self, cname: str, rel: str) -> str | None:
        """The module a class name resolves to from ``rel`` (definition
        in the same module wins, then the import map, then a package-wide
        unique definition)."""
        rels = self.classes.get(cname)
        if not rels:
            return None
        if rel in rels:
            return rel
        imported_from = self.imports.get(rel, {}).get(cname)
        if imported_from in rels:
            return imported_from
        if len(rels) == 1:
            return next(iter(rels))
        return None

    # ------------------------------------------------------------------
    # call edges
    # ------------------------------------------------------------------

    def _build_edges(self, index: SourceIndex):
        edges: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for (rel, qual), f in self.funcs.items():
            out: set[tuple[str, str]] = set()
            local_types: dict[str, tuple[str, str]] = {}
            for node in ast.walk(f.node):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    # t = Thing(...) -> t.m() resolves one level.
                    if isinstance(node.value.func, ast.Name):
                        cname = node.value.func.id
                        crel = self.class_rel(cname, rel)
                        if crel is not None:
                            for t in node.targets:
                                if isinstance(t, ast.Name):
                                    local_types[t.id] = (crel, cname)
                if not isinstance(node, ast.Call):
                    continue
                for target in self.call_targets(node.func, f, local_types):
                    out.add(target)
            edges[(rel, qual)] = out
        return edges

    def call_targets(self, func_expr: ast.expr, f: Func, local_types=None):
        """Resolve one call (or bare callable reference) expression from
        inside ``f`` to zero or more ``(rel, qual)`` function keys."""
        name = dotted_name(func_expr)
        if name is None:
            return
        local_types = local_types or {}
        rel = f.rel
        parts = name.split(".")
        if len(parts) == 1:
            # bare g() — same module, else an imported function.
            if (rel, parts[0]) in self.funcs:
                yield (rel, parts[0])
            else:
                src = self.imports.get(rel, {}).get(parts[0])
                if src and (src, parts[0]) in self.funcs:
                    yield (src, parts[0])
                # Constructor call: edge into __init__.
                crel = self.class_rel(parts[0], rel)
                if crel and (crel, f"{parts[0]}.__init__") in self.funcs:
                    yield (crel, f"{parts[0]}.__init__")
        elif parts[0] == "self" and f.cls is not None:
            if len(parts) == 2:
                if (rel, f"{f.cls}.{parts[1]}") in self.funcs:
                    yield (rel, f"{f.cls}.{parts[1]}")
            elif len(parts) == 3:
                typed = self.attr_types.get((rel, f.cls), {}).get(parts[1])
                if typed:
                    trel, tcls = typed
                    if (trel, f"{tcls}.{parts[2]}") in self.funcs:
                        yield (trel, f"{tcls}.{parts[2]}")
        elif len(parts) == 2:
            # local constructor-typed var.m().
            typed = local_types.get(parts[0])
            if typed:
                trel, tcls = typed
                if (trel, f"{tcls}.{parts[1]}") in self.funcs:
                    yield (trel, f"{tcls}.{parts[1]}")

    def reach(self, roots):
        """BFS from ``roots`` (function keys). Returns ``(reachable set,
        {key: call chain from its root})`` — missing roots are skipped so
        callers run unmodified over partial fixture trees."""
        chains: dict[tuple[str, str], tuple[str, ...]] = {}
        frontier: list[tuple[str, str]] = []
        for ep in roots:
            if ep in self.funcs and ep not in chains:
                chains[ep] = (f"{ep[0]}:{ep[1]}",)
                frontier.append(ep)
        while frontier:
            cur = frontier.pop()
            for nxt in self.edges.get(cur, ()):
                if nxt in chains:
                    continue
                chains[nxt] = chains[cur] + (f"{nxt[0]}:{nxt[1]}",)
                frontier.append(nxt)
        return set(chains), chains

    def module_dependents(self) -> dict[str, set[str]]:
        """Reverse import map: ``{rel: modules that import rel}`` — the
        scope widener behind ``scripts/meshcheck.py --changed`` (a change
        to a module can invalidate any finding computed in a module that
        calls into it, and name-shaped calls follow imports)."""
        out: dict[str, set[str]] = {rel: set() for rel in self.index.modules}
        for rel, imap in self.imports.items():
            for target in set(imap.values()):
                out.setdefault(target, set()).add(rel)
        return out


def get_callgraph(index: SourceIndex) -> CallGraph:
    """The index's call graph, built once per index instance (checkers
    sharing one ``SourceIndex`` share one graph)."""
    cg = getattr(index, "_callgraph", None)
    if cg is None:
        cg = CallGraph(index)
        index._callgraph = cg
    return cg
