"""radixmesh_tpu — a TPU-native distributed radix prefix cache + serving stack.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of RadixMesh
(reference: /root/reference, see SURVEY.md): a distributed radix-tree prefix
cache whose KV blocks live as ``jax.Array`` pages in TPU HBM, replicated
across prefill/decode nodes via idempotent oplogs over a ring, with
master-free rank-based conflict resolution, distributed duplicate-KV GC, and
a cache-aware router — plus the model runtime the reference left as a seam:
paged-attention Pallas kernels, Llama-3/Qwen2 model families, a continuous
batching scheduler, and tp/dp/sp sharding over a ``jax.sharding.Mesh``.
"""

__version__ = "0.1.0"

from radixmesh_tpu.config import MeshConfig, NodeRole, load_config
from radixmesh_tpu.cache.radix_tree import RadixTree, TreeNode, MatchResult
from radixmesh_tpu.cache.kv_pool import PagedKVPool, SlotAllocator

__all__ = [
    "MeshConfig",
    "NodeRole",
    "load_config",
    "RadixTree",
    "TreeNode",
    "MatchResult",
    "PagedKVPool",
    "SlotAllocator",
    "__version__",
]
