"""radixmesh_tpu — a TPU-native distributed radix prefix cache + serving stack.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of RadixMesh
(reference: /root/reference, see SURVEY.md): a distributed radix-tree prefix
cache whose KV blocks live as ``jax.Array`` pages in TPU HBM, replicated
across prefill/decode nodes via idempotent oplogs over a ring, with
master-free rank-based conflict resolution, distributed duplicate-KV GC, and
a cache-aware router — plus the model runtime the reference left as a seam:
paged-attention Pallas kernels, Llama-3/Qwen2 model families, a continuous
batching scheduler, and tp/dp/sp sharding over a ``jax.sharding.Mesh``.
"""

__version__ = "0.1.0"

from radixmesh_tpu.config import MeshConfig, NodeRole, load_config
from radixmesh_tpu.cache.radix_tree import RadixTree, TreeNode, MatchResult


def __getattr__(name: str):
    # PEP 562 lazy exports: kv_pool imports jax (~5 s cold), which the
    # pure cache/mesh/router surface never needs — a 50-process ringscale
    # sweep on one core must not pay 50 jax imports (scripts/ringscale.py
    # --procs).
    if name in ("PagedKVPool", "SlotAllocator"):
        from radixmesh_tpu.cache import kv_pool

        return getattr(kv_pool, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "MeshConfig",
    "NodeRole",
    "load_config",
    "RadixTree",
    "TreeNode",
    "MatchResult",
    "PagedKVPool",
    "SlotAllocator",
    "__version__",
]
