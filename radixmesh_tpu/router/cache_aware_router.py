"""Cache-aware request routing (reference
``router/cache_aware_router.py:15-39``).

The router node's :class:`MeshCache` replica stores *which rank* wrote
each prefix (rank-only values, no KV) — so routing a request is one
read-only tree walk. Semantics matched to the reference:

- **Warm-up** (``:20-25``): until ``finish_warm_up()`` the router reports
  no match so traffic spreads over the hash ring.
- **Hit** (``:28-34``): matched prefill/decode rank → that node's address.
- **Miss per role** (``:30-37``): consistent hash over that role's nodes.

Net-new beyond the reference: the hash rings are built once and updated
on topology change (not rebuilt per request), and the result carries the
matched prefix length so the serving frontend can report hit-rate —
the north-star metric (``BASELINE.json``).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Sequence

import numpy as np

from radixmesh_tpu.cache.mesh_cache import MeshCache, RouterMatchResult
from radixmesh_tpu.config import MeshConfig
from radixmesh_tpu.obs.metrics import TOKEN_LEN_BUCKETS, get_registry
from radixmesh_tpu.obs.trace_plane import get_recorder
from radixmesh_tpu.router.consistent_hash import ConsistentHash

__all__ = ["CacheAwareRouter", "RouteResult"]


@dataclass
class RouteResult:
    """Where to send a request (reference ``RouteResult``,
    ``cache_aware_router.py:8-11``), plus hit telemetry.

    An address is ``None`` when NO node of that role is currently alive
    (every member left the topology view): the caller should surface
    "no capacity" — queueing or erroring per its policy — rather than
    dialing."""

    prefill_addr: str | None
    decode_addr: str | None
    prefill_cache_hit: bool = False
    decode_cache_hit: bool = False
    match_len: int = 0
    # Crash failover (server/recovery.py): the longest-prefix match
    # pointed at a node the caller excluded (declared dead) — the
    # request was re-placed on a surviving node. ``match_len`` is KEPT
    # on failover: every ring member replicates the prefix, so the
    # survivor serves the same cached tokens the dead writer would have.
    prefill_failover: bool = False
    decode_failover: bool = False


class _LoadTracker:
    """Leaky-bucket in-flight estimate per address: each routed request
    adds one unit; units decay exponentially with ``tau`` seconds (the
    router never sees completions, so decay stands in for them)."""

    def __init__(self, tau_s: float):
        self.tau = tau_s
        # One lock: /route runs on concurrent ThreadingHTTPServer handler
        # threads, and an unlocked read-modify-write would undercount the
        # hot node exactly when shedding matters.
        self._lock = threading.Lock()
        self._load: dict[str, float] = {}
        self._t: dict[str, float] = {}

    def _decayed(self, addr: str, now: float) -> float:
        last = self._t.get(addr)
        if last is None:
            return 0.0
        return self._load[addr] * math.exp(-(now - last) / self.tau)

    def note(self, addr: str) -> None:
        now = time.monotonic()
        with self._lock:
            self._load[addr] = self._decayed(addr, now) + 1.0
            self._t[addr] = now

    def load(self, addr: str) -> float:
        with self._lock:
            return self._decayed(addr, time.monotonic())


class CacheAwareRouter:
    def __init__(
        self,
        mesh_cache: MeshCache,
        config: MeshConfig,
        overload_factor: float | None = 3.0,
        overload_floor: float = 8.0,
        load_tau_s: float = 10.0,
        health_aware: bool = False,
        health_threshold: float = 0.5,
        prefetch_hints: bool = False,
        prefetch_window_s: float = 2.0,
    ):
        if not config.prefill_nodes or not config.decode_nodes:
            raise ValueError("router needs at least one prefill and one decode node")
        self.mesh_cache = mesh_cache
        self.config = config
        self._warm_up = True
        self._prefill_ring = ConsistentHash(config.prefill_nodes)
        self._decode_ring = ConsistentHash(config.decode_nodes)
        # Health-aware demotion (obs/fleet_plane.py; --health-aware-routing):
        # a node whose gossiped health score drops below the threshold —
        # stall watchdog fired, replication badly lagged, eviction storm —
        # is treated as overloaded (cache hits shed past it) AND excluded
        # from the hash-ring fallback, so the router stops selecting it
        # until its digests recover. Scores come from the router replica's
        # FleetView, fed by the ring's DIGEST gossip; unknown ranks score
        # 1.0, so a booting fleet routes normally.
        self.health_aware = health_aware
        self.health_threshold = health_threshold
        self.fleet = mesh_cache.fleet
        self._rank_of_addr = {
            **{a: r for r, a in enumerate(config.prefill_nodes)},
            **{
                a: config.num_prefill + r
                for r, a in enumerate(config.decode_nodes)
            },
        }
        # Static inverse, precomputed once: _lifecycle_sets runs on the
        # routing hot path and must not rebuild this per request.
        self._addr_of_rank = {r: a for a, r in self._rank_of_addr.items()}
        # Hot-prefix overload protection (net-new; the reference always
        # follows the cache): when a cache hit points at a node whose
        # estimated in-flight load exceeds ``overload_factor`` x the
        # role's mean (and at least ``overload_floor`` absolute — light
        # traffic never sheds), the request takes the hash-ring fallback
        # instead: one recomputed prefix beats a convoy on the hot node.
        # ``overload_factor=None`` disables shedding.
        self.overload_factor = overload_factor
        self.overload_floor = overload_floor
        self._loads = _LoadTracker(load_tau_s)
        # Predictive restore hints (cache/kv_transfer.py; launch.py
        # --kv-prefetch-hints): when a cache hit routes to a node, fire a
        # PREFETCH oplog at it so a host-tier prefix starts restoring
        # BEFORE the request arrives. The router cannot see tiers (its
        # replica is rank-only), so it over-approximates — hinting every
        # hit — and the receiver no-ops when the prefix is already in
        # HBM; idempotence makes the over-approximation free. A per-
        # (rank, prefix) dedupe window keeps a hot prefix from spraying
        # one hint per request.
        self.prefetch_hints = prefetch_hints
        self.prefetch_window_s = prefetch_window_s
        self._prefetch_sent: dict[tuple[int, int], float] = {}
        self._prefetch_lock = threading.Lock()
        # Prefix-ownership sharding (cache/sharding.py): the mesh
        # replica is summary-only, routing prefers OWNER replicas for
        # hits, failover, and fallback (the PR 7 invariant "a survivor
        # holds the prefix" holds within the owner set), and a warm hit
        # landing on a non-owner fires a pull-through so the target's
        # replica fills before the traffic pattern repeats.
        self.sharded = bool(getattr(mesh_cache, "sharded", False))
        # Hints leave the ROUTE HOT PATH through this bounded queue and
        # a single daemon sender: the wire send (channel dial, bounded
        # try_send) must never add to a /route response, and drop-on-
        # overflow is exactly the fire-and-forget contract. Pull-through
        # requests ride the same queue (tagged tuples).
        self._prefetch_q: deque = deque(maxlen=256)
        self._prefetch_evt = threading.Event()
        self._prefetch_thread: threading.Thread | None = None
        if prefetch_hints or self.sharded:
            self._prefetch_thread = threading.Thread(
                target=self._prefetch_sender, daemon=True,
                name="router-prefetch",
            )
            self._prefetch_thread.start()
        # Mutated by _on_view_change on the mesh transport-reader thread
        # while /route handler threads read it: guard with a lock (the
        # hash rings guard their own state the same way).
        self._alive_lock = threading.Lock()
        self._alive = {
            "prefill": set(config.prefill_nodes),
            "decode": set(config.decode_nodes),
        }
        reg = get_registry()
        routed = reg.counter(
            "radixmesh_router_requests_total",
            "routing decisions by role and outcome",
            ("role", "outcome"),
        )
        # Pre-resolved children: label resolution must not run (or be
        # measured) inside the per-request timed region.
        self._m_routed = {
            (role, outcome): routed.labels(role=role, outcome=outcome)
            for role in ("prefill", "decode")
            for outcome in ("hit", "fallback", "shed", "withheld", "failover")
        }
        # Membership-lifecycle withholding (policy/lifecycle.py): a
        # BOOTSTRAPPING node's replica is still cold — a cache hit
        # pointing at it would miss on arrival, so hits are withheld
        # (hash-ring fallback serves instead) until its fingerprint
        # converges with its donor and it gossips ACTIVE. DRAINING/LEFT
        # nodes get no new work at all. Always on: lifecycle states only
        # exist when a LifecyclePlane gossips them.
        self.withheld_hits = 0  # lifetime count (chaos-gate telemetry)
        self._m_route_latency = reg.histogram(
            "radixmesh_router_route_seconds", "cache-aware routing decision latency"
        )
        self._m_match_len = reg.histogram(
            "radixmesh_router_match_len_tokens",
            "matched prefix length per routed request (tokens)",
            buckets=TOKEN_LEN_BUCKETS,
        )

    def finish_warm_up(self) -> None:
        """Enable cache-aware decisions (reference ``:20-21``)."""
        self._warm_up = False

    # -- topology changes (net-new: reference lists node add/remove as
    # roadmap, README.md:49-50) --

    def add_node(self, role: str, addr: str) -> None:
        (self._prefill_ring if role == "prefill" else self._decode_ring).add_node(addr)
        with self._alive_lock:
            self._alive[role].add(addr)

    def remove_node(self, role: str, addr: str) -> None:
        ring = self._prefill_ring if role == "prefill" else self._decode_ring
        ring.remove_node(addr)
        with self._alive_lock:
            self._alive[role].discard(addr)

    def watch_topology(self) -> None:
        """Subscribe to the mesh replica's epoch-numbered view changes
        (``policy/topology.py``): dead nodes leave the consistent-hash
        fallback rings, rejoined nodes return — so even cache-miss traffic
        stops landing on nodes the mesh has declared dead."""
        self.mesh_cache.on_view_change.append(self._on_view_change)

    def _on_view_change(self, old, new) -> None:
        for rank in set(old.alive) - set(new.alive):
            self.remove_node(
                "prefill" if self.config.is_prefill_rank(rank) else "decode",
                self.config.addr_of_rank(rank),
            )
        for rank in set(new.alive) - set(old.alive):
            self.add_node(
                "prefill" if self.config.is_prefill_rank(rank) else "decode",
                self.config.addr_of_rank(rank),
            )

    def _sick_addrs(self) -> set[str]:
        """Addresses currently below the health threshold — the demotion
        + hash-ring exclusion set. ONE FleetView.health computation per
        route call (per-address health_score lookups would rebuild the
        whole O(nodes) dict per candidate on the request hot path)."""
        if not self.health_aware:
            return set()
        sick = self.fleet.sick_ranks(self.health_threshold)
        if not sick:
            return set()
        return {a for a, r in self._rank_of_addr.items() if r in sick}

    def _lifecycle_sets(self) -> tuple[set[int], set[str]]:
        """(withheld hit ranks, excluded addrs) from gossiped lifecycle
        states — one FleetView lock hold per route call. BOOTSTRAPPING
        ranks lose only their cache-hit preference (they still take
        hash-ring fallback traffic: the warm-up they are running exists
        to serve exactly that); DRAINING/LEFT nodes are excluded from
        hits AND the fallback rings (no new work on a departing node)."""
        lifecycles = self.fleet.lifecycles()
        withhold: set[int] = set()
        excluded: set[str] = set()
        for rank, state in lifecycles.items():
            if state == "active":
                continue  # the steady-state hot path: no sets built
            if state == "bootstrapping":
                withhold.add(rank)
            elif state in ("draining", "left"):
                withhold.add(rank)
                addr = self._addr_of_rank.get(rank)
                if addr is not None:
                    excluded.add(addr)
        return withhold, excluded

    def _overloaded(self, role: str, addr: str, sick: set[str]) -> bool:
        # Health demotion first: a stalled node must shed even when its
        # load estimate looks light (a wedged engine stops completing,
        # so decayed load is exactly the number that lies here).
        if addr in sick:
            return True
        if self.overload_factor is None:
            return False
        with self._alive_lock:
            alive = set(self._alive[role])  # snapshot vs concurrent view changes
        alive.add(addr)  # the routed target counts even if it just left the view
        if len(alive) <= 1:
            return False  # nowhere to shed to
        target = self._loads.load(addr)
        if target < self.overload_floor:
            return False
        # Compare against the OTHER nodes' mean: including the target in
        # the mean makes the threshold unreachable for factor >= n (with
        # 2 nodes and factor 3 a convoy would never shed). Idle peers
        # (others_mean ~ 0) shed as soon as the floor is crossed.
        others = [self._loads.load(a) for a in alive if a != addr]
        others_mean = sum(others) / len(others)
        return target > self.overload_factor * others_mean

    def _maybe_prefetch(self, key: Sequence[int], match_len: int, rank: int) -> None:
        """Queue one deduped PREFETCH hint for ``key``'s matched prefix.
        Fire-and-forget: the wire send happens on the background sender,
        and failures / dedupe skips / queue overflow cost an overlap
        opportunity, never a routing decision (or a route's latency)."""
        prefix = np.asarray(key[:match_len], dtype=np.int32)
        dedupe = (rank, hash(prefix.tobytes()))
        now = time.monotonic()
        with self._prefetch_lock:
            last = self._prefetch_sent.get(dedupe, 0.0)
            if now - last < self.prefetch_window_s:
                return
            self._prefetch_sent[dedupe] = now
            if len(self._prefetch_sent) > 4096:  # bounded memory
                cutoff = now - self.prefetch_window_s
                self._prefetch_sent = {
                    k: t for k, t in self._prefetch_sent.items() if t >= cutoff
                }
            self._prefetch_q.append(("hint", prefix, rank))
        self._prefetch_evt.set()

    def _prefetch_sender(self) -> None:
        """Daemon drain of the hint queue — the only place router
        prefetches (and sharded pull-throughs) touch a transport."""
        while True:
            with self._prefetch_lock:
                item = self._prefetch_q.popleft() if self._prefetch_q else None
            if item is None:
                self._prefetch_evt.wait(timeout=0.2)
                self._prefetch_evt.clear()
                continue
            try:
                if item[0] == "pull":
                    self.mesh_cache.send_shard_pull(item[1], item[2], item[3])
                else:
                    self.mesh_cache.send_prefetch(item[1], item[2])
            except Exception:  # noqa: BLE001 — hints are droppable by contract
                pass

    def _owner_addrs(self, key: Sequence[int], role: str) -> list[str]:
        """Ordered owner-replica addresses of ``key``'s shard for one
        role (empty when unsharded) — the preferred hit/failover/
        fallback targets under sharding."""
        if not self.sharded:
            return []
        out = []
        for rank in self.mesh_cache.owner_ranks(key):
            if (role == "prefill") != self.config.is_prefill_rank(rank):
                continue
            addr = self._addr_of_rank.get(rank)
            if addr is not None:
                out.append(addr)
        return out

    def _pick(self, role: str, key: Sequence[int], exclude) -> str | None:
        """One fallback choice: owner replicas first (sharded — traffic
        for a subtree concentrates where its inserts land, and failover
        must land on a replica that HOLDS the prefix), then the role's
        consistent-hash ring. Among eligible owner replicas the
        LEAST-LOADED wins: under elastic replication
        (cache/rebalance.py) a hot shard's boosted owner set is exactly
        the fan-out surface — a first-owner-wins pick would re-convoy
        the traffic the boost exists to spread."""
        exclude = exclude or set()
        owners = [
            a for a in self._owner_addrs(key, role) if a not in exclude
        ]
        if owners:
            if len(owners) == 1:
                return owners[0]
            # Ties (an idle fleet) keep the walk order — cold routing
            # stays deterministic at the primary owner.
            return min(
                enumerate(owners),
                key=lambda ia: (self._loads.load(ia[1]), ia[0]),
            )[1]
        ring = self._prefill_ring if role == "prefill" else self._decode_ring
        return ring.get_node(key, exclude=exclude or None)

    def _maybe_pull_through(
        self, key: Sequence[int], match_len: int, addr: str | None
    ) -> None:
        """A warm subtree is being served by a NON-owner (shed/withheld/
        ring fallback): queue a pull-through so an owner re-emits the
        prefix to that node before the pattern repeats. Deduped through
        the same window as prefetch hints."""
        if not self.sharded or addr is None or match_len <= 0:
            return
        target = self._rank_of_addr.get(addr)
        if target is None:
            return
        owners = [
            r for r in self.mesh_cache.owner_ranks(key) if r != target
        ]
        if not owners or target in self.mesh_cache.owner_ranks(key):
            return
        prefix = np.asarray(key[:match_len], dtype=np.int32)
        dedupe = (target, hash(prefix.tobytes()))
        now = time.monotonic()
        with self._prefetch_lock:
            last = self._prefetch_sent.get(dedupe, 0.0)
            if now - last < self.prefetch_window_s:
                return
            self._prefetch_sent[dedupe] = now
            self._prefetch_q.append(("pull", prefix, owners[0], target))
        self._prefetch_evt.set()

    def cache_aware_route(
        self, key: Sequence[int], exclude: Sequence[str] | None = None
    ) -> RouteResult:
        """Route one request's token ids (reference ``:23-39``).

        ``exclude`` (crash failover, ``server/recovery.py``): addresses
        the caller has declared dead — never routed to, as hit or
        fallback. A longest-prefix match pointing at one re-places on a
        surviving node with ``match_len`` preserved (replication means
        the survivor holds the prefix), flagged ``*_failover``."""
        t0 = time.monotonic()
        try:
            res = self._route(key, frozenset(exclude or ()))
        finally:
            dur = time.monotonic() - t0
            self._m_route_latency.observe(dur)
        rec = get_recorder()
        if rec.enabled:
            # Routing leg of the request-flight timeline: the router is
            # its own node, so these land on a "router" lane correlated
            # with engine lanes by wall-clock overlap.
            rec.event(
                "router", "route", t0, dur, cat="router",
                match_len=int(res.match_len),
                prefill_hit=bool(res.prefill_cache_hit),
                decode_hit=bool(res.decode_cache_hit),
            )
        return res

    def _route(
        self, key: Sequence[int], exclude: frozenset = frozenset()
    ) -> RouteResult:
        if self._warm_up:
            match = RouterMatchResult(-1, -1)
        elif self.sharded:
            # Summary-based match: the router holds no tree replica
            # under sharding — per-shard summaries (fingerprints + root
            # depths) gossiped by the owners stand in for it.
            match = self.mesh_cache.shard_route(key)
        else:
            match = self.mesh_cache.match_prefix(key)
            assert isinstance(match, RouterMatchResult), (
                "cache_aware_route requires a ROUTER-mode MeshCache"
            )

        p_out = d_out = None
        p_fo = d_fo = False
        sick = self._sick_addrs()
        withhold, lc_excluded = self._lifecycle_sets()
        # Dead-declared addresses (crash failover) are excluded HARD —
        # unlike sickness, which is advisory, a dead node must never be
        # returned even when it is the only ring member left.
        lc_excluded = lc_excluded | exclude
        avoid = sick | lc_excluded  # never a fallback target either
        if match.prefill_rank >= 0:
            prefill_addr = self.config.prefill_addr(match.prefill_rank)
            p_hit = True
            if prefill_addr in exclude:
                # The longest-prefix writer is DEAD: re-place on a
                # surviving node. match_len is kept — replication means
                # the survivor holds the prefix, which is exactly what
                # makes a resurrected request's re-prefill nearly free.
                # No survivor at all is NOT a failover (nothing was
                # re-placed): plain fallback-to-None, no preserved match.
                # Sharded: owner replicas are tried first — they are the
                # only nodes guaranteed to hold the prefix (RF invariant).
                alt = self._pick(
                    "prefill", key, {prefill_addr} | avoid
                ) or self._pick("prefill", key, exclude)
                p_hit = False
                if alt is not None:
                    prefill_addr, p_out, p_fo = alt, "failover", True
                else:
                    prefill_addr = None
            elif match.prefill_rank in withhold:
                # Cold (bootstrapping) or departing replica: the hit is
                # not servable there — hash-ring fallback instead.
                self.withheld_hits += 1
                alt = self._pick(
                    "prefill", key, {prefill_addr} | avoid
                ) or self._pick("prefill", key, lc_excluded)
                if alt is not None:
                    prefill_addr = alt
                p_hit, p_out = False, "withheld"
            elif self._overloaded("prefill", prefill_addr, sick):
                shed = self._pick("prefill", key, {prefill_addr} | avoid)
                if shed is not None:
                    prefill_addr, p_hit, p_out = shed, False, "shed"
        else:
            # Cache miss: hash-ring fallback, skipping health-demoted
            # and departing nodes. If EVERY node of the role is sick,
            # route anyway (degraded service beats no service) —
            # sickness is advisory; departure/death exclusion yields
            # only when literally nothing else exists (dead addresses
            # stay excluded even then: None means "no capacity").
            prefill_addr = (
                self._pick("prefill", key, avoid)
                or self._pick("prefill", key, lc_excluded)
                or self._pick("prefill", key, exclude)
            )
            p_hit = False
        if match.decode_rank >= 0:
            decode_addr = self.config.decode_addr(match.decode_rank)
            d_hit = True
            if decode_addr in exclude:
                alt = self._pick(
                    "decode", key, {decode_addr} | avoid
                ) or self._pick("decode", key, exclude)
                d_hit = False
                if alt is not None:
                    decode_addr, d_out, d_fo = alt, "failover", True
                else:
                    decode_addr = None
            elif match.decode_rank in withhold:
                self.withheld_hits += 1
                alt = self._pick(
                    "decode", key, {decode_addr} | avoid
                ) or self._pick("decode", key, lc_excluded)
                if alt is not None:
                    decode_addr = alt
                d_hit, d_out = False, "withheld"
            elif self._overloaded("decode", decode_addr, sick):
                shed = self._pick("decode", key, {decode_addr} | avoid)
                if shed is not None:
                    decode_addr, d_hit, d_out = shed, False, "shed"
        else:
            decode_addr = (
                self._pick("decode", key, avoid)
                or self._pick("decode", key, lc_excluded)
                or self._pick("decode", key, exclude)
            )
            d_hit = False
        if self.prefetch_hints and match.match_len > 0:
            # Hint only ranks the request will actually LAND on (a shed
            # hit routes elsewhere — warming the hot node would restore
            # KV nobody is coming for).
            if p_hit and match.prefill_rank >= 0:
                self._maybe_prefetch(key, match.match_len, match.prefill_rank)
            if d_hit and match.decode_rank >= 0:
                self._maybe_prefetch(key, match.match_len, match.decode_rank)
        if self.sharded and match.match_len > 0:
            # A warm subtree landing on a NON-owner (shed, withheld,
            # failover residue, or a role with no owner replica): fill
            # that node's replica from an owner so the next request of
            # this pattern hits locally.
            self._maybe_pull_through(key, match.match_len, prefill_addr)
            self._maybe_pull_through(key, match.match_len, decode_addr)
        if prefill_addr is not None:
            self._loads.note(prefill_addr)
        if decode_addr is not None:
            self._loads.note(decode_addr)
        self._m_routed[("prefill", p_out or ("hit" if p_hit else "fallback"))].inc()
        self._m_routed[("decode", d_out or ("hit" if d_hit else "fallback"))].inc()
        self._m_match_len.observe(
            match.match_len if (p_hit or d_hit or p_fo or d_fo) else 0
        )
        # match_len only counts when a ROUTED address actually holds the
        # match (post-shedding): a shed request lands on a node without
        # the prefix, and reporting cached tokens there would inflate the
        # hit-rate the north-star metric watches. Failover is the
        # exception — replication puts the prefix on the survivor too.
        return RouteResult(
            prefill_addr=prefill_addr,
            decode_addr=decode_addr,
            prefill_cache_hit=p_hit,
            decode_cache_hit=d_hit,
            match_len=match.match_len if (p_hit or d_hit or p_fo or d_fo) else 0,
            prefill_failover=p_fo,
            decode_failover=d_fo,
        )
