"""Cache-aware request routing (reference
``router/cache_aware_router.py:15-39``).

The router node's :class:`MeshCache` replica stores *which rank* wrote
each prefix (rank-only values, no KV) — so routing a request is one
read-only tree walk. Semantics matched to the reference:

- **Warm-up** (``:20-25``): until ``finish_warm_up()`` the router reports
  no match so traffic spreads over the hash ring.
- **Hit** (``:28-34``): matched prefill/decode rank → that node's address.
- **Miss per role** (``:30-37``): consistent hash over that role's nodes.

Net-new beyond the reference: the hash rings are built once and updated
on topology change (not rebuilt per request), and the result carries the
matched prefix length so the serving frontend can report hit-rate —
the north-star metric (``BASELINE.json``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from radixmesh_tpu.cache.mesh_cache import MeshCache, RouterMatchResult
from radixmesh_tpu.config import MeshConfig
from radixmesh_tpu.obs.metrics import TOKEN_LEN_BUCKETS, get_registry
from radixmesh_tpu.router.consistent_hash import ConsistentHash

__all__ = ["CacheAwareRouter", "RouteResult"]


@dataclass
class RouteResult:
    """Where to send a request (reference ``RouteResult``,
    ``cache_aware_router.py:8-11``), plus hit telemetry.

    An address is ``None`` when NO node of that role is currently alive
    (every member left the topology view): the caller should surface
    "no capacity" — queueing or erroring per its policy — rather than
    dialing."""

    prefill_addr: str | None
    decode_addr: str | None
    prefill_cache_hit: bool = False
    decode_cache_hit: bool = False
    match_len: int = 0


class CacheAwareRouter:
    def __init__(self, mesh_cache: MeshCache, config: MeshConfig):
        if not config.prefill_nodes or not config.decode_nodes:
            raise ValueError("router needs at least one prefill and one decode node")
        self.mesh_cache = mesh_cache
        self.config = config
        self._warm_up = True
        self._prefill_ring = ConsistentHash(config.prefill_nodes)
        self._decode_ring = ConsistentHash(config.decode_nodes)
        reg = get_registry()
        routed = reg.counter(
            "router_requests_total",
            "routing decisions by role and outcome",
            ("role", "outcome"),
        )
        # Pre-resolved children: label resolution must not run (or be
        # measured) inside the per-request timed region.
        self._m_routed = {
            (role, outcome): routed.labels(role=role, outcome=outcome)
            for role in ("prefill", "decode")
            for outcome in ("hit", "fallback")
        }
        self._m_route_latency = reg.histogram(
            "router_route_seconds", "cache-aware routing decision latency"
        )
        self._m_match_len = reg.histogram(
            "router_match_len_tokens",
            "matched prefix length per routed request (tokens)",
            buckets=TOKEN_LEN_BUCKETS,
        )

    def finish_warm_up(self) -> None:
        """Enable cache-aware decisions (reference ``:20-21``)."""
        self._warm_up = False

    # -- topology changes (net-new: reference lists node add/remove as
    # roadmap, README.md:49-50) --

    def add_node(self, role: str, addr: str) -> None:
        (self._prefill_ring if role == "prefill" else self._decode_ring).add_node(addr)

    def remove_node(self, role: str, addr: str) -> None:
        ring = self._prefill_ring if role == "prefill" else self._decode_ring
        ring.remove_node(addr)

    def watch_topology(self) -> None:
        """Subscribe to the mesh replica's epoch-numbered view changes
        (``policy/topology.py``): dead nodes leave the consistent-hash
        fallback rings, rejoined nodes return — so even cache-miss traffic
        stops landing on nodes the mesh has declared dead."""
        self.mesh_cache.on_view_change.append(self._on_view_change)

    def _on_view_change(self, old, new) -> None:
        for rank in set(old.alive) - set(new.alive):
            self.remove_node(
                "prefill" if self.config.is_prefill_rank(rank) else "decode",
                self.config.addr_of_rank(rank),
            )
        for rank in set(new.alive) - set(old.alive):
            self.add_node(
                "prefill" if self.config.is_prefill_rank(rank) else "decode",
                self.config.addr_of_rank(rank),
            )

    def cache_aware_route(self, key: Sequence[int]) -> RouteResult:
        """Route one request's token ids (reference ``:23-39``)."""
        with self._m_route_latency.time():
            return self._route(key)

    def _route(self, key: Sequence[int]) -> RouteResult:
        if self._warm_up:
            match = RouterMatchResult(-1, -1)
        else:
            match = self.mesh_cache.match_prefix(key)
            assert isinstance(match, RouterMatchResult), (
                "cache_aware_route requires a ROUTER-mode MeshCache"
            )

        if match.prefill_rank >= 0:
            prefill_addr = self.config.prefill_addr(match.prefill_rank)
            p_hit = True
        else:
            prefill_addr = self._prefill_ring.get_node(key)
            p_hit = False
        if match.decode_rank >= 0:
            decode_addr = self.config.decode_addr(match.decode_rank)
            d_hit = True
        else:
            decode_addr = self._decode_ring.get_node(key)
            d_hit = False
        self._m_routed[("prefill", "hit" if p_hit else "fallback")].inc()
        self._m_routed[("decode", "hit" if d_hit else "fallback")].inc()
        self._m_match_len.observe(match.match_len if (p_hit or d_hit) else 0)
        return RouteResult(
            prefill_addr=prefill_addr,
            decode_addr=decode_addr,
            prefill_cache_hit=p_hit,
            decode_cache_hit=d_hit,
            match_len=match.match_len if match.prefill_rank >= 0 or match.decode_rank >= 0 else 0,
        )
