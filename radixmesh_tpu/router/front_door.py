"""Multi-router front door: client-side failover over N routers.

The router was the last single point of failure the self-healing mesh
could not absorb: PRs 5-7 made node death a latency blip for the RING,
but every request still traversed one rank-only router replica — a
router crash took the whole front door with it. With the single-router
cap lifted (``config.py``), N routers each hold an independently-fed
replica (per-shard summaries and digests already ride the master
fan-out to EVERY router), so any of them can answer any routing
question. What was missing is the CLIENT half: something that notices a
dead router and moves on without losing the request.

:class:`RouterFrontDoor` is that client. It is transport-agnostic (the
same callable-seam design as ``server/recovery.py``): each router edge
is an ``(addr, route_fn)`` pair — in-proc router objects for the chaos
workload, HTTP ``POST /route`` wrappers for a real deployment — and the
front door owns:

- **Sticky preference**: requests ride one router until it fails (its
  load tracker and prefetch dedupe windows stay warm), then the
  preference moves to the survivor.
- **Hedged retry on timeout**: a route hop that exceeds
  ``hop_timeout_s`` fires the NEXT router while the slow leg keeps
  running — first successful answer wins, exactly the tail-latency
  discipline the recovery plane applies to serving hops. A leg that
  raises indicts its router (declared dead, skipped until revived).
- **Retry-After awareness**: a router that sheds with a Retry-After is
  ALIVE — the front door honors the pacing (bounded by
  ``retry_after_cap_s``) and retries instead of declaring it dead;
  failover is for failure, not for flow control.
- **Revival**: a dead router returns to rotation after
  ``revive_after_s`` (a restarted process should not need an operator
  to readmit it), and :meth:`revive` readmits it immediately.

Every seam is injectable (clock, sleep) so the failover logic is
virtual-time testable.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Sequence

from radixmesh_tpu.obs.metrics import get_registry
from radixmesh_tpu.utils.logging import get_logger

__all__ = ["RouterDied", "RetryAfter", "RouterFrontDoor"]


class RouterDied(RuntimeError):
    """A router edge failed in a way that indicts the ROUTER (refused
    connection, hop timeout, chaos kill): declared dead, skipped."""


class RetryAfter(Exception):
    """The router answered with a retriable shed + pacing hint: it is
    alive and flow-controlling. Honor the wait; never declare dead."""

    def __init__(self, seconds: float, message: str = "router shedding"):
        super().__init__(message)
        self.seconds = max(0.0, float(seconds))


class RouterFrontDoor:
    """Client-side failover over an ordered set of router edges.

    ``edges``: ``(addr, route_fn)`` pairs; ``route_fn(*args, **kwargs)``
    returns the routing answer, raises :class:`RetryAfter` on a
    retriable shed, and raises anything else on failure (timeouts the
    transport surfaces, connection errors, chaos kills).

    Thread-safe: ``route`` may run on many request threads; the dead
    set, preference cursor, and counters share one lock. Hedge legs run
    on daemon threads and are never joined — a wedged router's leg
    costs one idle thread, not a stuck request."""

    def __init__(
        self,
        edges: Sequence[tuple[str, Callable]],
        *,
        hop_timeout_s: float = 1.0,
        retry_after_cap_s: float = 2.0,
        max_shed_waits: int = 3,
        revive_after_s: float = 30.0,
        name: str = "frontdoor",
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        if not edges:
            raise ValueError("front door needs at least one router edge")
        self._edges = [(str(a), fn) for a, fn in edges]
        self.hop_timeout_s = float(hop_timeout_s)
        self.retry_after_cap_s = float(retry_after_cap_s)
        self.max_shed_waits = int(max_shed_waits)
        self.revive_after_s = float(revive_after_s)
        self.name = name
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._dead: dict[str, float] = {}  # addr -> declared-at
        self._preferred = 0  # index of the sticky edge
        # Reusable daemon leg workers: the healthy N>=2 path fires one
        # leg per route, and paying a Thread spawn per REQUEST puts
        # ~100us of scheduler churn on the routing hot path. Workers
        # park on an event between jobs; a wedged leg strands exactly
        # one worker (the same cost a spawned thread had) and a fresh
        # one is created on demand. Each slot is [job, wake event].
        self._workers_lock = threading.Lock()
        self._idle_workers: list[list] = []
        # Lifetime counts mirrored off the metric children (chaos-gate
        # telemetry — counters are process-global, these are per-door).
        self.failovers = 0
        self.hedges = 0
        self.shed_waits = 0
        self.log = get_logger(f"router.{name}")
        # Observers of front-door death declarations (addr, cause) —
        # the chaos workload hooks here, mirroring RecoveryCoordinator.
        self.on_router_dead: list = []

        reg = get_registry()
        lbl = {"node": name}
        self._m_failovers = reg.counter(
            "radixmesh_frontdoor_failovers_total",
            "route calls answered by a non-preferred router after the "
            "preferred one failed or timed out",
            ("node",),
        ).labels(**lbl)
        self._m_hedges = reg.counter(
            "radixmesh_frontdoor_hedges_total",
            "route hops duplicated to the next router after exceeding "
            "the hop timeout (first successful answer wins)",
            ("node",),
        ).labels(**lbl)
        self._m_shed_waits = reg.counter(
            "radixmesh_frontdoor_retry_after_waits_total",
            "Retry-After pacing waits honored instead of declaring the "
            "shedding router dead",
            ("node",),
        ).labels(**lbl)

    # -- membership -----------------------------------------------------

    def addrs(self) -> list[str]:
        return [a for a, _ in self._edges]

    def dead_addrs(self) -> set[str]:
        with self._lock:
            self._sweep_revivals_locked()
            return set(self._dead)

    def declare_dead(self, addr: str, cause: str = "died") -> None:
        with self._lock:
            if addr in self._dead:
                return
            self._dead[addr] = self._clock()
            observers = list(self.on_router_dead)
        self.log.warning("declared router %s dead (cause=%s)", addr, cause)
        for fn in observers:
            try:
                fn(addr, cause)
            except Exception:  # noqa: BLE001 — an observer must not break failover
                self.log.exception("on_router_dead observer failed")

    def revive(self, addr: str) -> None:
        with self._lock:
            self._dead.pop(addr, None)

    def _sweep_revivals_locked(self) -> None:
        if self.revive_after_s <= 0:
            return
        now = self._clock()
        for addr in [
            a for a, t in self._dead.items()
            if now - t >= self.revive_after_s
        ]:
            del self._dead[addr]

    def _candidates(self) -> list[tuple[int, str, Callable]]:
        """Live edges in preference order (sticky edge first, then the
        rest of the ring order)."""
        with self._lock:
            self._sweep_revivals_locked()
            dead = set(self._dead)
            start = self._preferred
        n = len(self._edges)
        out = []
        for k in range(n):
            i = (start + k) % n
            addr, fn = self._edges[i]
            if addr not in dead:
                out.append((i, addr, fn))
        return out

    # -- the failover loop ---------------------------------------------

    def route(self, *args, **kwargs):
        """One front-door routing decision, surviving router death.

        Raises :class:`RouterDied` only when EVERY router is dead or
        shedding past the pacing budget — the "front door down" case N
        routers exist to make unreachable."""
        shed_waits = 0
        while True:
            cands = self._candidates()
            if not cands:
                raise RouterDied("no live router edge")
            try:
                if len(cands) == 1:
                    # Sole-live-edge fast path: no hedge is possible,
                    # so the leg runs inline — no per-route thread
                    # spawn. The transport's own timeout is the bound
                    # (route_fns should carry one, as an HTTP edge
                    # does); there is nothing to race it against.
                    idx, addr, result = self._single_leg(
                        cands[0], args, kwargs
                    )
                else:
                    idx, addr, result = self._hedged_round(
                        cands, args, kwargs
                    )
            except RetryAfter as ra:
                shed_waits += 1
                if shed_waits > self.max_shed_waits:
                    raise RouterDied(
                        "every router shedding past the pacing budget"
                    ) from ra
                self._m_shed_waits.inc()
                with self._lock:
                    self.shed_waits += 1
                self._sleep(min(ra.seconds, self.retry_after_cap_s))
                continue
            with self._lock:
                if idx != self._preferred:
                    self._preferred = idx
                    self.failovers += 1
                    self._m_failovers.inc()
            return result

    def _submit_leg(self, job: Callable[[], None]) -> None:
        """Run ``job`` on a reusable daemon worker (pop an idle one or
        start a new one). Jobs never raise — ``leg`` handles its own
        outcomes — so a worker always returns to the idle pool when its
        job completes."""
        with self._workers_lock:
            if self._idle_workers:
                slot = self._idle_workers.pop()
                slot[0] = job
                slot[1].set()
                return
        slot = [job, threading.Event()]

        def _worker_loop(slot=slot):
            while True:
                job = slot[0]
                slot[0] = None
                try:
                    job()
                except Exception:  # noqa: BLE001 — legs handle their own errors
                    self.log.exception("front-door leg worker failed")
                with self._workers_lock:
                    self._idle_workers.append(slot)
                # meshcheck: ok[timeout-audit] idle-pool park: a daemon worker waiting for its next job blocks on purpose; there is no peer to time out on
                slot[1].wait()
                slot[1].clear()

        threading.Thread(
            target=_worker_loop, daemon=True, name="frontdoor-leg"
        ).start()

    def _single_leg(self, cand, args, kwargs) -> tuple[int, str, object]:
        idx, addr, fn = cand
        try:
            return idx, addr, fn(*args, **kwargs)
        except RetryAfter:
            raise  # alive and flow-controlling: route() paces + retries
        except Exception as e:  # noqa: BLE001 — a failed leg indicts its router
            self.declare_dead(
                addr,
                cause="hop_timeout" if isinstance(e, TimeoutError)
                else "died",
            )
            raise RouterDied(
                f"sole live router edge {addr} failed"
            ) from e

    def _hedged_round(self, cands, args, kwargs) -> tuple[int, str, object]:
        """Fire the preferred edge; hedge to each next edge after a hop
        timeout; first successful leg wins. Legs that raise are declared
        dead (except :class:`RetryAfter`). Raises the collected
        RetryAfter (shortest pacing) when every leg shed; RouterDied
        when every leg failed."""
        done = threading.Event()
        lock = threading.Lock()
        state = {"winner": None, "failed": set(), "shed": {}}
        n = len(cands)

        def leg(idx: int, addr: str, fn: Callable):
            try:
                result = fn()
            except RetryAfter as ra:
                with lock:
                    state["shed"][idx] = ra
                done.set()
                return
            except Exception as e:  # noqa: BLE001 — a failed leg indicts its router
                self.declare_dead(
                    addr,
                    cause="hop_timeout" if isinstance(e, TimeoutError)
                    else "died",
                )
                with lock:
                    state["failed"].add(idx)
                done.set()
                return
            with lock:
                if state["winner"] is None:
                    state["winner"] = (idx, addr, result)
            done.set()

        started = 0

        def fire_next() -> bool:
            nonlocal started
            if started >= n:
                return False
            idx, addr, fn = cands[started]
            started += 1
            self._submit_leg(
                lambda i=idx, a=addr, f=fn: leg(
                    i, a, lambda: f(*args, **kwargs)
                )
            )
            return True

        fire_next()
        next_hedge = self._clock() + self.hop_timeout_s
        while True:
            with lock:
                if state["winner"] is not None:
                    return state["winner"]
                failed = set(state["failed"])
                shed = dict(state["shed"])
            resolved = len(failed) + len(shed)
            if resolved >= started and started >= n:
                # Every fired leg resolved without a winner.
                if shed:
                    raise min(shed.values(), key=lambda ra: ra.seconds)
                raise RouterDied("every router edge failed")
            now = self._clock()
            if resolved >= started or now >= next_hedge:
                # The in-flight legs all resolved badly, or the newest
                # leg is straggling past the hop timeout: hedge.
                if fire_next():
                    if now >= next_hedge:
                        self._m_hedges.inc()
                        with self._lock:
                            self.hedges += 1
                    next_hedge = self._clock() + self.hop_timeout_s
                    continue
                # Nothing left to fire: a straggler may still win, but
                # only within one more hop timeout. Only UNRESOLVED
                # legs are declared dead — an edge that answered with
                # RetryAfter is alive and flow-controlling, and its
                # pacing hint wins over the stragglers' silence. The
                # failed/shed sets are keyed by each edge's GLOBAL
                # index (the first tuple element of a cands row, NOT
                # its position — the two differ whenever the sticky
                # preference has moved off edge 0).
                if now >= next_hedge + self.hop_timeout_s:
                    for idx, addr, _fn in cands[:started]:
                        if idx not in failed and idx not in shed:
                            self.declare_dead(addr, cause="hop_timeout")
                    if shed:
                        raise min(
                            shed.values(), key=lambda ra: ra.seconds
                        )
                    raise RouterDied(
                        "every router edge timed out without answering"
                    )
            # Park until the NEXT relevant deadline: the hedge point
            # while edges remain to fire, else the straggler deadline —
            # waiting against an already-passed next_hedge would
            # degrade to 1 ms busy-polling for the whole grace window.
            wake_at = (
                next_hedge
                if started < n
                else next_hedge + self.hop_timeout_s
            )
            done.wait(timeout=max(0.001, min(0.05, wake_at - now)))
            done.clear()

    def __call__(self, *args, **kwargs):
        return self.route(*args, **kwargs)
