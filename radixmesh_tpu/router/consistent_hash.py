"""Consistent-hash ring for cold-start routing.

Capability parity with the reference's ``ConsistentHash``
(``router/cache_aware_router.py:42-121``): virtual nodes, sorted ring,
bisect lookup with wraparound, dynamic add/remove. Differences by design:
blake2b instead of truncated MD5 (faster, no deprecation baggage), and the
ring is built once and mutated incrementally instead of rebuilt per call
(the reference constructs a fresh ring on every miss,
``cache_aware_router.py:30-37`` — O(nodes log nodes) per request).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Iterable, Sequence

__all__ = ["ConsistentHash"]


def _hash32(data: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(data, digest_size=4).digest(), "big")


class ConsistentHash:
    """Ring of node addresses with ``virtual_nodes`` replicas each."""

    def __init__(self, nodes: Iterable[str] = (), virtual_nodes: int = 3):
        self.virtual_nodes = virtual_nodes
        self._ring: list[int] = []  # sorted hash points
        self._owner: dict[int, str] = {}
        # add/remove run on the mesh transport-reader thread (topology view
        # changes) while get_node serves requests on other threads.
        self._lock = threading.Lock()
        for node in nodes:
            self.add_node(node)

    def _points(self, node: str) -> list[int]:
        return [
            _hash32(f"{node}#{i}".encode()) for i in range(self.virtual_nodes)
        ]

    def add_node(self, node: str) -> None:
        with self._lock:
            for h in self._points(node):
                if h in self._owner:  # hash collision: first owner keeps it
                    continue
                bisect.insort(self._ring, h)
                self._owner[h] = node

    def remove_node(self, node: str) -> None:
        with self._lock:
            for h in self._points(node):
                if self._owner.get(h) == node:
                    self._ring.remove(h)
                    del self._owner[h]

    @staticmethod
    def _key_bytes(key: Sequence[int] | bytes | str) -> bytes:
        if isinstance(key, str):
            return key.encode()
        if isinstance(key, bytes):
            return key
        return b",".join(str(int(t)).encode() for t in key)

    def get_node(
        self,
        key: Sequence[int] | bytes | str,
        exclude: set[str] | None = None,
    ) -> str | None:
        """Owner of ``key``: first ring point clockwise from hash(key)
        whose owner is not in ``exclude`` (overload shedding needs the
        next-best owner when the natural one is the node being avoided);
        ``None`` when every owner is excluded."""
        h = _hash32(self._key_bytes(key))
        with self._lock:
            if not self._ring:
                return None
            idx = bisect.bisect_right(self._ring, h)
            for step in range(len(self._ring)):
                owner = self._owner[self._ring[(idx + step) % len(self._ring)]]
                if not exclude or owner not in exclude:
                    return owner
            return None

    def get_nodes(
        self,
        key: Sequence[int] | bytes | str,
        n: int,
        exclude: set[str] | None = None,
    ) -> list[str]:
        """The first ``n`` DISTINCT owners clockwise from hash(key) — the
        replication-factor successor walk (cache/sharding.py): owner sets
        are a deterministic pure function of (ring membership, key), so
        every node derives the same set from the same view with no
        coordination. Wraps around the ring; returns fewer than ``n``
        when the ring holds fewer distinct nodes (the N < RF degeneracy —
        every node owns everything). Walk order is preserved: the first
        entry is the natural single owner (``get_node``'s answer)."""
        if n <= 0:
            return []
        h = _hash32(self._key_bytes(key))
        out: list[str] = []
        seen: set[str] = set()
        with self._lock:
            if not self._ring:
                return []
            idx = bisect.bisect_right(self._ring, h)
            for step in range(len(self._ring)):
                owner = self._owner[self._ring[(idx + step) % len(self._ring)]]
                if owner in seen or (exclude and owner in exclude):
                    continue
                seen.add(owner)
                out.append(owner)
                if len(out) == n:
                    break
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(set(self._owner.values()))
