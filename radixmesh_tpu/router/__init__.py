"""Request routing over the distributed prefix cache (reference L5,
``python/src/router/`` — SURVEY §1).

``CacheAwareRouter`` answers: which prefill node and which decode node
already hold the longest cached prefix of this request's tokens? It reads
the router node's rank-only replica of the mesh tree; on a miss (or during
warm-up) it falls back to consistent hashing so cold traffic still
spreads deterministically.
"""

from radixmesh_tpu.router.cache_aware_router import CacheAwareRouter, RouteResult
from radixmesh_tpu.router.consistent_hash import ConsistentHash

__all__ = ["CacheAwareRouter", "RouteResult", "ConsistentHash"]
