"""ctypes binding to the native C++ DCN transport (``native/transport.cpp``).

The shared library is compiled on demand with ``g++`` (no pybind11; plain C
ABI + ctypes per the environment constraints) and cached next to the source.
Capability parity with the reference's TcpCommunicator
(``communication/communicator.py:138-270``): length-framed ordered delivery,
persistent auto-reconnecting sender, listener thread pool, asymmetric
listen-only / send-only endpoints.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable

from radixmesh_tpu.comm.communicator import Communicator
from radixmesh_tpu.config import DEFAULT_MAX_MSG_BYTES, parse_addr
from radixmesh_tpu.utils.logging import get_logger

__all__ = ["NativeTcpCommunicator", "load_native_lib"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native", "transport.cpp")
_LIB = os.path.join(_HERE, "native", "libtransport.so")

_CALLBACK_T = ctypes.CFUNCTYPE(
    None, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_void_p
)

_lib_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _build() -> None:
    cmd = [
        "g++",
        "-std=c++17",
        "-O3",
        "-shared",
        "-fPIC",
        "-pthread",
        "-o",
        _LIB,
        _SRC,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def load_native_lib() -> ctypes.CDLL:
    """Load (building if needed) the native transport library."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            _build()
        lib = ctypes.CDLL(_LIB)
        lib.rm_listener_create.restype = ctypes.c_void_p
        lib.rm_listener_create.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_uint64,
            _CALLBACK_T,
            ctypes.c_void_p,
        ]
        lib.rm_listener_close.argtypes = [ctypes.c_void_p]
        lib.rm_sender_create.restype = ctypes.c_void_p
        lib.rm_sender_create.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64]
        lib.rm_send.restype = ctypes.c_int
        lib.rm_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.rm_sender_connected.restype = ctypes.c_int
        lib.rm_sender_connected.argtypes = [ctypes.c_void_p]
        lib.rm_sender_flush.argtypes = [ctypes.c_void_p]
        lib.rm_sender_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class NativeTcpCommunicator(Communicator):
    def __init__(
        self,
        bind_addr: str | None,
        target_addr: str | None,
        max_msg_bytes: int = DEFAULT_MAX_MSG_BYTES,
    ):
        self._lib = load_native_lib()
        self._log = get_logger("comm.tcp")
        self._bind = bind_addr
        self._target = target_addr
        self._max_msg = max_msg_bytes
        self._callback: Callable[[bytes], None] | None = None
        self._listener = None
        self._sender = None
        self._closed = False

        # Keep a reference to the ctypes callback trampoline for the life of
        # the listener — if it's collected, the C side calls freed memory.
        def _trampoline(data, length, _user):
            cb = self._callback
            if cb is None:
                return
            try:
                cb(ctypes.string_at(data, length))
            except Exception:  # noqa: BLE001 — receiver bugs must not kill the reader thread
                self._log.exception("receive callback failed")

        self._c_callback = _CALLBACK_T(_trampoline)

        if bind_addr is not None:
            host, port = parse_addr(bind_addr)
            self._listener = self._lib.rm_listener_create(
                host.encode(), port, max_msg_bytes, self._c_callback, None
            )
            if not self._listener:
                raise OSError(f"failed to bind native listener on {bind_addr}")
        if target_addr is not None:
            host, port = parse_addr(target_addr)
            self._sender = self._lib.rm_sender_create(host.encode(), port, max_msg_bytes)
            if not self._sender:
                raise OSError(f"failed to create native sender to {target_addr}")

    def send(self, data: bytes) -> None:
        if self._closed:
            raise RuntimeError("communicator closed")
        if self._sender is None:
            raise RuntimeError("send-only target not configured")
        if len(data) > self._max_msg:
            raise ValueError(
                f"message of {len(data)} bytes exceeds max_msg_bytes={self._max_msg}"
            )
        rc = self._lib.rm_send(self._sender, data, len(data))
        if rc != 0:
            raise RuntimeError(f"native send failed (rc={rc})")

    def try_send(self, data: bytes, timeout_s: float) -> bool:
        """Failure-detection send. The native sender connects LAZILY — its
        background thread only dials once the queue is non-empty — so the
        frame must be enqueued FIRST, then the connection awaited: polling
        ``connected`` before enqueueing would wait on a dial that never
        starts. ``connected`` is a liveness signal, not a per-message
        delivery ack; a frame accepted here is delivered at-least-once by
        the background retry loop if the peer is ever reachable. On False
        the frame stays queued: callers either retarget (dropping the old
        handle and its queue) or back off and let the backlog drain when
        the peer appears."""
        import time as _time

        if self._closed:
            raise RuntimeError("communicator closed")
        if self._sender is None:
            raise RuntimeError("send-only target not configured")
        self.send(data)
        deadline = _time.monotonic() + timeout_s
        while not self._lib.rm_sender_connected(self._sender):
            if self._closed:
                raise RuntimeError("communicator closed")
            if _time.monotonic() >= deadline:
                return False
            # meshcheck: ok[sleep-audit] bounded connect poll against the
            # native library's connected flag (no readiness callback).
            _time.sleep(0.01)
        return True

    def retarget(self, target_addr: str | None) -> None:
        """Swap the native sender for one aimed at the new target. Caller
        (the mesh sender thread) serializes with sends."""
        old, self._sender = self._sender, None
        if old is not None:
            self._lib.rm_sender_close(old)
        self._target = target_addr
        if target_addr is not None:
            host, port = parse_addr(target_addr)
            sender = self._lib.rm_sender_create(host.encode(), port, self._max_msg)
            if not sender:
                raise OSError(f"failed to create native sender to {target_addr}")
            self._sender = sender

    def connected(self) -> bool:
        return self._sender is not None and bool(
            self._lib.rm_sender_connected(self._sender)
        )

    def register_rcv_callback(self, fn: Callable[[bytes], None]) -> None:
        self._callback = fn

    def is_ordered(self) -> bool:
        return True

    def target_address(self) -> str | None:
        return self._target

    def flush(self) -> None:
        if self._sender is not None:
            self._lib.rm_sender_flush(self._sender)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._sender is not None:
            self._lib.rm_sender_close(self._sender)
            self._sender = None
        if self._listener is not None:
            self._lib.rm_listener_close(self._listener)
            self._listener = None
