// Native DCN ring transport for radixmesh_tpu.
//
// C++ replacement for the reference's Python TcpCommunicator
// (communication/communicator.py:138-270) and the role its incomplete
// mooncake RDMA integration was meant to play (communicator.py:32-130):
// a length-framed, ordered, asynchronous point-to-point byte transport for
// oplog replication between TPU hosts over DCN. Intra-slice KV movement
// rides XLA collectives over ICI instead (see parallel/); this module only
// carries control-plane oplogs and cross-slice KV-page payloads.
//
// Wire format: [4-byte big-endian length][payload], identical framing to
// the reference (README.md:76-81) so the protocol survives a mixed
// deployment with the pure-Python fallback transport.
//
// Exposed as a plain C ABI consumed from Python via ctypes
// (comm/tcp_native.py). No pybind11 dependency.
//
// Threading model:
//   listener: one accept thread + one reader thread per accepted
//             connection; each complete frame invokes the registered
//             callback (ctypes releases/acquires the GIL around it).
//   sender:   one background thread draining a bounded FIFO queue,
//             (re)connecting with retry; rm_send() enqueues and applies
//             backpressure when the queue is full, mirroring the
//             blocking-sendall semantics of the reference
//             (communicator.py:183-210) without stalling the caller on
//             the network itself.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <poll.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <chrono>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kMaxQueueBytes = 64ull * 1024 * 1024;
// Frames already queued are coalesced into one wire write up to this
// many bytes: under replication load (every ring hop re-sends every
// oplog) this collapses N send() syscalls into one without changing the
// wire format — the stream stays a sequence of length-prefixed frames.
constexpr uint64_t kCoalesceBytes = 256ull * 1024;
constexpr int kConnectRetryMs = 100;
constexpr int kConnectTimeoutMs = 5000;

// Non-blocking connect with poll: a blocked target (SYN black hole) can
// otherwise pin the sender thread inside connect() for the kernel's ~2min
// SYN-retry budget, which rm_sender_close's shutdown_fd() cannot interrupt
// because the fd is not yet published. Polls in kConnectRetryMs slices,
// aborting early when `stop` is set.
int connect_to(const std::string& host, int port,
               const std::atomic<bool>* stop) {
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0) return -1;
  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc != 0 && errno == EINPROGRESS) {
      struct pollfd pfd;
      pfd.fd = fd;
      pfd.events = POLLOUT;
      pfd.revents = 0;
      int waited = 0;
      while (waited < kConnectTimeoutMs && !(stop && stop->load())) {
        int pr = poll(&pfd, 1, kConnectRetryMs);
        if (pr > 0 || (pr < 0 && errno != EINTR)) break;
        waited += kConnectRetryMs;
      }
      int err = 0;
      socklen_t elen = sizeof(err);
      if (!(pfd.revents & POLLOUT) ||
          getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) != 0 || err != 0) {
        close(fd);
        fd = -1;
        continue;
      }
      rc = 0;
    }
    if (rc == 0) {
      fcntl(fd, F_SETFL, flags);
      break;
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  return fd;
}

bool send_all(int fd, const uint8_t* data, uint64_t len) {
  uint64_t off = 0;
  while (off < len) {
    ssize_t n = ::send(fd, data + off, len - off, MSG_NOSIGNAL);
    if (n <= 0) return false;
    off += static_cast<uint64_t>(n);
  }
  return true;
}

bool recv_all(int fd, uint8_t* data, uint64_t len) {
  uint64_t off = 0;
  while (off < len) {
    ssize_t n = ::recv(fd, data + off, len - off, 0);
    if (n <= 0) return false;  // peer closed or error
    off += static_cast<uint64_t>(n);
  }
  return true;
}

}  // namespace

extern "C" {

typedef void (*rm_callback)(const uint8_t* data, uint64_t len, void* user);

// ---------------------------------------------------------------------------
// Listener
// ---------------------------------------------------------------------------

struct RmListener {
  int listen_fd = -1;
  rm_callback cb = nullptr;
  void* user = nullptr;
  uint64_t max_msg = 0;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  std::mutex conn_mu;
  std::vector<int> conn_fds;
  std::vector<std::thread> conn_threads;

  void handle_conn(int fd) {
    std::vector<uint8_t> buf;
    uint8_t hdr[4];
    while (!stopping.load(std::memory_order_relaxed)) {
      if (!recv_all(fd, hdr, 4)) break;
      uint64_t len = (uint64_t(hdr[0]) << 24) | (uint64_t(hdr[1]) << 16) |
                     (uint64_t(hdr[2]) << 8) | uint64_t(hdr[3]);
      if (len == 0 || len > max_msg) break;  // protocol violation: drop conn
      buf.resize(len);
      if (!recv_all(fd, buf.data(), len)) break;
      if (cb != nullptr) cb(buf.data(), len, user);
    }
    close(fd);
  }

  void accept_loop() {
    while (!stopping.load(std::memory_order_relaxed)) {
      int fd = accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stopping.load(std::memory_order_relaxed)) return;
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(conn_mu);
      conn_fds.push_back(fd);
      conn_threads.emplace_back([this, fd] { handle_conn(fd); });
    }
  }
};

void* rm_listener_create(const char* host, int port, uint64_t max_msg,
                         rm_callback cb, void* user) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (std::strcmp(host, "0.0.0.0") == 0 || std::strcmp(host, "") == 0) {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    // Resolve hostnames like "localhost".
    struct addrinfo hints;
    std::memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    if (getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr) {
      close(fd);
      return nullptr;
    }
    addr.sin_addr = reinterpret_cast<struct sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  auto* l = new RmListener();
  l->listen_fd = fd;
  l->cb = cb;
  l->user = user;
  l->max_msg = max_msg;
  l->accept_thread = std::thread([l] { l->accept_loop(); });
  return l;
}

void rm_listener_close(void* handle) {
  auto* l = static_cast<RmListener*>(handle);
  if (l == nullptr) return;
  l->stopping.store(true);
  shutdown(l->listen_fd, SHUT_RDWR);
  close(l->listen_fd);
  if (l->accept_thread.joinable()) l->accept_thread.join();
  {
    std::lock_guard<std::mutex> lk(l->conn_mu);
    for (int fd : l->conn_fds) shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : l->conn_threads) {
    if (t.joinable()) t.join();
  }
  delete l;
}

// ---------------------------------------------------------------------------
// Sender
// ---------------------------------------------------------------------------

struct RmSender {
  std::string host;
  int port = 0;
  uint64_t max_msg = 0;
  std::atomic<bool> stopping{false};
  std::atomic<bool> connected{false};
  std::mutex mu;
  std::condition_variable cv_push;  // signalled when queue drains
  std::condition_variable cv_pop;   // signalled when data arrives
  std::deque<std::vector<uint8_t>> queue;
  uint64_t queued_bytes = 0;
  std::thread send_thread;
  std::atomic<bool> done{false};
  // fd_mu guards the fd's lifecycle so rm_sender_close's shutdown() can
  // never race drop_connection()'s close() onto a reused descriptor.
  std::mutex fd_mu;
  int fd = -1;

  bool ensure_connected() {
    {
      std::lock_guard<std::mutex> lk(fd_mu);
      if (fd >= 0) return true;
    }
    int f = connect_to(host, port, &stopping);
    std::lock_guard<std::mutex> lk(fd_mu);
    fd = f;
    connected.store(fd >= 0);
    return fd >= 0;
  }

  void drop_connection() {
    std::lock_guard<std::mutex> lk(fd_mu);
    if (fd >= 0) close(fd);
    fd = -1;
    connected.store(false);
  }

  void shutdown_fd() {
    std::lock_guard<std::mutex> lk(fd_mu);
    if (fd >= 0) shutdown(fd, SHUT_RDWR);
  }

  void run() {
    while (true) {
      if (stopping.load() && queue.empty()) { done.store(true); return; }
      // Drain EVERY already-queued frame (bounded by kCoalesceBytes)
      // into one contiguous wire buffer of [len][payload] frames: one
      // send() per burst instead of one per oplog.
      std::vector<uint8_t> wire;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_pop.wait(lk, [this] { return stopping.load() || !queue.empty(); });
        if (stopping.load() && queue.empty()) { lk.unlock(); done.store(true); return; }
        while (!queue.empty() && wire.size() < kCoalesceBytes) {
          const std::vector<uint8_t>& msg = queue.front();
          uint8_t hdr[4] = {static_cast<uint8_t>(msg.size() >> 24),
                            static_cast<uint8_t>(msg.size() >> 16),
                            static_cast<uint8_t>(msg.size() >> 8),
                            static_cast<uint8_t>(msg.size())};
          wire.insert(wire.end(), hdr, hdr + 4);
          wire.insert(wire.end(), msg.begin(), msg.end());
          queued_bytes -= msg.size();
          queue.pop_front();
        }
        cv_push.notify_all();
      }
      // Retry (reconnecting) until delivered or the sender is closed.
      // Silently dropping a frame after bounded retries — what the
      // reference does (communicator.py:192-208) — diverges the ring
      // unrecoverably, since receivers have no gap detection. At-least-once
      // + per-link FIFO keeps replicas convergent; a permanently dead peer
      // back-pressures this queue, which failure detection (topology epoch
      // changes) is the cure for, not frame loss. A reconnect mid-burst
      // re-sends the WHOLE burst: frames the peer already applied re-apply
      // idempotently (the ring's at-least-once model).
      while (!stopping.load()) {
        while (!ensure_connected()) {
          if (stopping.load()) { done.store(true); return; }
          std::this_thread::sleep_for(std::chrono::milliseconds(kConnectRetryMs));
        }
        if (stopping.load()) break;  // close() may have fired mid-reconnect
        int f;
        {
          std::lock_guard<std::mutex> lk(fd_mu);
          f = fd;
        }
        if (f >= 0 && send_all(f, wire.data(), wire.size()))
          break;
        drop_connection();
      }
    }
  }
};

void* rm_sender_create(const char* host, int port, uint64_t max_msg) {
  auto* s = new RmSender();
  s->host = host;
  s->port = port;
  s->max_msg = max_msg;
  s->send_thread = std::thread([s] { s->run(); });
  return s;
}

// Enqueue a message. Returns 0 on success, -1 if closed/oversized.
// Blocks (backpressure) while the queue holds more than kMaxQueueBytes.
int rm_send(void* handle, const uint8_t* data, uint64_t len) {
  auto* s = static_cast<RmSender*>(handle);
  if (s == nullptr || len == 0 || len > s->max_msg) return -1;
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv_push.wait(lk, [s] {
    return s->stopping.load() || s->queued_bytes < kMaxQueueBytes;
  });
  if (s->stopping.load()) return -1;
  s->queue.emplace_back(data, data + len);
  s->queued_bytes += len;
  s->cv_pop.notify_one();
  return 0;
}

int rm_sender_connected(void* handle) {
  auto* s = static_cast<RmSender*>(handle);
  return (s != nullptr && s->connected.load()) ? 1 : 0;
}

// Block until the queue is empty (for tests / graceful shutdown).
void rm_sender_flush(void* handle) {
  auto* s = static_cast<RmSender*>(handle);
  if (s == nullptr) return;
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv_push.wait(lk, [s] { return s->queue.empty() || s->stopping.load(); });
}

void rm_sender_close(void* handle) {
  auto* s = static_cast<RmSender*>(handle);
  if (s == nullptr) return;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->stopping.store(true);
  }
  s->cv_pop.notify_all();
  s->cv_push.notify_all();
  // Unblock a send_all() stalled on a wedged peer (full TCP buffer):
  // shutdown makes the in-flight ::send fail immediately so the thread can
  // observe `stopping`. Retried because the sender may be mid-reconnect at
  // the moment of the first shutdown (fd == -1) and connect afterwards.
  for (int i = 0; i < 500 && !s->done.load(); ++i) {
    s->shutdown_fd();
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  if (s->send_thread.joinable()) s->send_thread.join();
  s->drop_connection();
  delete s;
}

}  // extern "C"
