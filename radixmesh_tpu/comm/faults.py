"""Deterministic chaos/fault-injection plane for the oplog transports.

Jepsen-style adversarial conditions — frame loss, delay/jitter,
duplication, reordering, scheduled one-way/symmetric partitions, and
channel crashes — injected at the :func:`create_communicator` seam
(``comm/communicator.py``), so ANY test, workload, or soak run can
subject the ring, spine, router fan-out, and prefetch/repair channels to
faults **without touching product code**: the mesh sees an ordinary
:class:`Communicator` that happens to misbehave.

Design constraints (satellite "deflake guard" + the repair plane's
acceptance test both depend on them):

- **Seeded and deterministic.** Every edge (src addr → dst addr) derives
  its own ``numpy`` RNG from ``FaultPlan.seed`` and the edge name, so a
  given plan produces the same drop/dup/delay decisions for the same
  per-edge send sequence on every run — chaos failures reproduce from
  the seed.
- **Virtual-time friendly.** Scheduled faults (partitions, drop
  windows) read a relative clock started at :func:`install` time; tests
  can inject ``now_fn`` to drive schedules without real sleeps.
- **Sender-side only.** Faults apply where the frame leaves the node
  (the only place a real network loses it); inbound delivery is
  untouched, so receiver-side logic is exactly production code.

Fault semantics:

- *drop*: the send reports success but the frame is never delivered —
  the silent loss mode that permanently diverges replicas (what the
  anti-entropy repair plane exists to heal).
- *partition*: ``try_send`` blocks (bounded by its timeout) while the
  window is open, exactly like a blackholed TCP peer — so the mesh's
  failure detection sees the same signal it would in production.
- *delay/jitter/reorder/duplicate*: frames detour through a scheduler
  thread and land late / twice / out of order.
- *crash_after_sends*: the edge dies permanently after its Nth send
  (subsequent sends raise), simulating a connection torn mid-stream.
- *kill (process-level)*: :meth:`FaultPlan.kill` declares a NODE dead —
  not one channel. Every edge INTO the killed address blackholes
  (``try_send`` blocks out its timeout, like a peer whose process
  stopped acking), and every edge OUT of it raises (a dead process
  sends nothing) — permanently, with no scheduled end. This is the
  unclean-death mode the request-recovery plane
  (``server/recovery.py``) exists to survive; a partition ends, a kill
  does not.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import zlib
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from radixmesh_tpu.comm.communicator import Communicator

__all__ = [
    "PartitionSpec",
    "FaultPlan",
    "FaultyCommunicator",
    "install",
    "uninstall",
    "injected",
    "rebase",
    "active_plan",
    "maybe_wrap",
]


@dataclass(frozen=True)
class PartitionSpec:
    """One scheduled partition: traffic involving ``addrs`` is cut while
    ``start_s <= rel_now < end_s``. ``one_way=True`` cuts only traffic
    INTO ``addrs`` (the asymmetric-partition case where a node can talk
    but not hear); symmetric cuts both directions."""

    start_s: float
    end_s: float
    addrs: tuple[str, ...]
    one_way: bool = False

    def to_dict(self) -> dict:
        return {
            "start_s": self.start_s,
            "end_s": self.end_s,
            "addrs": list(self.addrs),
            "one_way": self.one_way,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PartitionSpec":
        return cls(
            start_s=float(d["start_s"]),
            end_s=float(d["end_s"]),
            addrs=tuple(d.get("addrs", ())),
            one_way=bool(d.get("one_way", False)),
        )


@dataclass
class FaultPlan:
    """A complete seeded fault schedule (JSON-serializable: the
    ``launch.py --chaos-plan`` file format is ``to_dict()``'s output).

    ``targets`` (when set) restricts probabilistic faults — drop / delay
    / dup / reorder — to edges whose destination is listed; partitions
    and crashes always name their own addresses."""

    seed: int = 0
    drop_p: float = 0.0
    drop_start_s: float = 0.0
    drop_end_s: float = float("inf")
    delay_s: float = 0.0
    jitter_s: float = 0.0
    dup_p: float = 0.0
    reorder_p: float = 0.0
    reorder_delay_s: float = 0.02
    partitions: tuple[PartitionSpec, ...] = ()
    # dst addr → edge dies permanently after this many sends to it.
    crash_after_sends: dict = field(default_factory=dict)
    # Addresses whose PROCESS is dead (``kill``): inbound edges
    # blackhole, outbound edges raise, forever. A set so a workload can
    # kill mid-run; every wrapped edge shares this object.
    killed: set = field(default_factory=set)
    targets: tuple[str, ...] | None = None
    # Observability for tests/workloads (not serialized): per-outcome
    # frame counts across every wrapped edge.
    counters: dict = field(default_factory=dict, repr=False, compare=False)

    def count(self, what: str, n: int = 1) -> None:
        self.counters[what] = self.counters.get(what, 0) + n

    def kill(self, addr: str) -> None:
        """Process-level kill: ``addr`` stops serving AND stops acking
        from this instant — permanent, unscheduled, unlike a partition.
        Takes effect immediately on every already-wrapped edge (they all
        share this plan object)."""
        self.killed.add(addr)
        self.count("kills")

    def is_killed(self, addr: str | None) -> bool:
        return addr is not None and addr in self.killed

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "drop_p": self.drop_p,
            "drop_start_s": self.drop_start_s,
            "drop_end_s": (
                None if self.drop_end_s == float("inf") else self.drop_end_s
            ),
            "delay_s": self.delay_s,
            "jitter_s": self.jitter_s,
            "dup_p": self.dup_p,
            "reorder_p": self.reorder_p,
            "reorder_delay_s": self.reorder_delay_s,
            "partitions": [p.to_dict() for p in self.partitions],
            "crash_after_sends": dict(self.crash_after_sends),
            "killed": sorted(self.killed),
            "targets": None if self.targets is None else list(self.targets),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        end = d.get("drop_end_s")
        return cls(
            seed=int(d.get("seed", 0)),
            drop_p=float(d.get("drop_p", 0.0)),
            drop_start_s=float(d.get("drop_start_s", 0.0)),
            drop_end_s=float("inf") if end is None else float(end),
            delay_s=float(d.get("delay_s", 0.0)),
            jitter_s=float(d.get("jitter_s", 0.0)),
            dup_p=float(d.get("dup_p", 0.0)),
            reorder_p=float(d.get("reorder_p", 0.0)),
            reorder_delay_s=float(d.get("reorder_delay_s", 0.02)),
            partitions=tuple(
                PartitionSpec.from_dict(p) for p in d.get("partitions", ())
            ),
            crash_after_sends=dict(d.get("crash_after_sends", {})),
            killed=set(d.get("killed", ())),
            targets=(
                None
                if d.get("targets") is None
                else tuple(d["targets"])
            ),
        )


# ---------------------------------------------------------------------------
# module install state (the create_communicator seam reads it)
# ---------------------------------------------------------------------------

class _Clock:
    """Shared schedule clock: every edge wrapped under one install reads
    the SAME relative time, and :func:`rebase` restarts the schedule for
    all of them at once (a workload builds its cluster first, then
    starts the fault window when traffic begins)."""

    def __init__(self, now_fn: Callable[[], float]):
        self.now_fn = now_fn
        self.t0 = now_fn()

    def rel(self) -> float:
        return self.now_fn() - self.t0


_state_lock = threading.Lock()
_plan: FaultPlan | None = None
_clock: _Clock | None = None


def install(plan: FaultPlan, now_fn: Callable[[], float] | None = None) -> None:
    """Arm ``plan``: every communicator created from now on is wrapped.
    Schedules (partitions, drop windows) are relative to this instant —
    or to the last :func:`rebase` call."""
    global _plan, _clock
    with _state_lock:
        _clock = _Clock(now_fn or time.monotonic)
        _plan = plan


def rebase() -> None:
    """Restart the armed plan's schedule clock at 'now' — already-
    wrapped edges follow along (they share the clock object)."""
    with _state_lock:
        if _clock is not None:
            _clock.t0 = _clock.now_fn()


def uninstall() -> None:
    global _plan
    with _state_lock:
        _plan = None


def active_plan() -> FaultPlan | None:
    with _state_lock:
        return _plan


@contextmanager
def injected(plan: FaultPlan, now_fn: Callable[[], float] | None = None):
    """Scoped install — the test/workload idiom. Already-created
    communicators are unaffected; communicators created inside the scope
    keep their faults for their lifetime (a node outliving the scope
    keeps misbehaving until closed — close the cluster inside)."""
    install(plan, now_fn)
    try:
        yield plan
    finally:
        uninstall()


def maybe_wrap(
    comm: Communicator, src: str | None, dst: str | None
) -> Communicator:
    """The :func:`create_communicator` hook: identity when no plan is
    armed (one lock-free-ish branch on the happy path), else a
    :class:`FaultyCommunicator` bound to the armed plan + clock."""
    if _plan is None:
        return comm
    with _state_lock:
        plan, clock = _plan, _clock
    if plan is None or clock is None:
        return comm
    return FaultyCommunicator(comm, plan, src=src, dst=dst, clock=clock)


# ---------------------------------------------------------------------------
# delayed-delivery scheduler (one daemon thread, shared by every edge)
# ---------------------------------------------------------------------------


class _Scheduler:
    _default: "_Scheduler | None" = None
    _default_lock = threading.Lock()

    def __init__(self):
        self._heap: list = []
        self._seq = itertools.count()
        self._cond = threading.Condition()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="chaos-scheduler"
        )
        self._thread.start()

    @classmethod
    def default(cls) -> "_Scheduler":
        with cls._default_lock:
            if cls._default is None:
                cls._default = cls()
            return cls._default

    def submit(self, delay_s: float, fn) -> None:
        due = time.monotonic() + max(0.0, delay_s)
        with self._cond:
            heapq.heappush(self._heap, (due, next(self._seq), fn))
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._heap:
                    # meshcheck: ok[timeout-audit] chaos-scheduler
                    # condition, notified on every submit; exists only
                    # under an armed fault plan, never on a serving path.
                    self._cond.wait()
                due, _, fn = self._heap[0]
                wait = due - time.monotonic()
                if wait > 0:
                    self._cond.wait(timeout=wait)
                    continue
                heapq.heappop(self._heap)
            try:
                fn()
            except Exception:  # noqa: BLE001 — a dead edge must not kill the clock
                pass


class FaultyCommunicator(Communicator):
    """A :class:`Communicator` that misbehaves per an armed
    :class:`FaultPlan`. Wraps the real transport; every non-fault path
    delegates, so behavior with an all-zero plan is bit-identical."""

    def __init__(
        self,
        inner: Communicator,
        plan: FaultPlan,
        src: str | None,
        dst: str | None,
        clock: _Clock,
    ):
        self._inner = inner
        self._plan = plan
        self._src = src
        self._dst = dst
        self._clock = clock
        # Per-edge deterministic stream: same plan seed + same edge name
        # + same send sequence → same decisions, every run.
        edge = f"{src or '?'}→{dst or '?'}"
        self._rng = np.random.default_rng(
            (plan.seed << 32) ^ zlib.crc32(edge.encode())
        )
        self._sent = 0
        self._crashed = False

    # -- schedule reads -------------------------------------------------

    def _rel(self) -> float:
        return self._clock.rel()

    def _dst_now(self) -> str | None:
        # retarget() may move the edge; faults follow the CURRENT target.
        t = self._inner.target_address()
        return t if t is not None else self._dst

    def _partitioned(self, rel: float) -> bool:
        dst = self._dst_now()
        for p in self._plan.partitions:
            if not p.start_s <= rel < p.end_s:
                continue
            if dst is not None and dst in p.addrs:
                return True  # traffic INTO the isolated set
            if not p.one_way and self._src is not None and self._src in p.addrs:
                return True  # symmetric: traffic OUT of it too
        return False

    def _in_scope(self) -> bool:
        t = self._plan.targets
        if t is None:
            return True
        dst = self._dst_now()
        return dst is not None and dst in t

    def _check_crash(self) -> None:
        if self._crashed:
            raise RuntimeError("chaos: channel crashed")
        if self._plan.is_killed(self._src):
            # A dead process sends nothing: outbound edges raise.
            self._plan.count("killed_send")
            raise RuntimeError(f"chaos: process {self._src} is killed")
        dst = self._dst_now()
        n = self._plan.crash_after_sends.get(dst)
        if n is not None and self._sent >= int(n):
            self._crashed = True
            self._plan.count("crashes")
            raise RuntimeError(f"chaos: channel to {dst} crashed on send {self._sent}")

    # -- faulted delivery ----------------------------------------------

    def _deliver(self, data: bytes) -> None:
        """Post-decision delivery: apply delay/jitter/reorder/duplicate,
        then hand to the real transport."""
        plan, rng = self._plan, self._rng
        delay = 0.0
        if plan.delay_s > 0.0 or plan.jitter_s > 0.0:
            delay = plan.delay_s + plan.jitter_s * float(rng.random())
        if plan.reorder_p > 0.0 and rng.random() < plan.reorder_p:
            # Hold this frame long enough for a successor to overtake it.
            delay += plan.reorder_delay_s
            plan.count("reordered")
        copies = 1
        if plan.dup_p > 0.0 and rng.random() < plan.dup_p:
            copies = 2
            plan.count("duplicated")
        for _ in range(copies):
            if delay > 0.0:
                plan.count("delayed")
                inner = self._inner
                _Scheduler.default().submit(
                    delay, lambda d=bytes(data): _quiet_send(inner, d)
                )
            else:
                self._inner.send(data)

    def send(self, data: bytes) -> None:
        self._check_crash()
        rel = self._rel()
        self._sent += 1
        if self._plan.is_killed(self._dst_now()):
            self._plan.count("killed_blocked")
            raise RuntimeError("chaos: peer process is killed")
        if self._partitioned(rel):
            self._plan.count("partition_blocked")
            raise RuntimeError("chaos: partitioned")
        if self._should_drop(rel):
            return
        self._deliver(data)

    def try_send(self, data: bytes, timeout_s: float) -> bool:
        self._check_crash()
        self._sent += 1
        deadline = time.monotonic() + timeout_s
        # A partition — or a KILLED peer — behaves like a blackholed
        # process that stopped acking: the send BLOCKS (bounded by the
        # caller's timeout) — the same signal real failure detection
        # keys on — and succeeds iff the window closes before the
        # deadline. A kill never closes.
        while self._partitioned(self._rel()) or self._plan.is_killed(
            self._dst_now()
        ):
            if time.monotonic() >= deadline:
                self._plan.count(
                    "killed_blocked"
                    if self._plan.is_killed(self._dst_now())
                    else "partition_blocked"
                )
                return False
            # meshcheck: ok[sleep-audit] partition-blocked backoff inside
            # the fault injector's bounded deadline loop (chaos only).
            time.sleep(0.002)
        if self._should_drop(self._rel()):
            return True  # silent loss: the sender believes it delivered
        remaining = max(0.0, deadline - time.monotonic())
        self._deliver_or_try(data, remaining)
        return True

    def _deliver_or_try(self, data: bytes, timeout_s: float) -> None:
        plan = self._plan
        if plan.delay_s > 0.0 or plan.jitter_s > 0.0 or plan.reorder_p > 0.0 \
                or plan.dup_p > 0.0:
            self._deliver(data)
            return
        if not self._inner.try_send(data, timeout_s):
            # The REAL transport timed out (not a fault): surface it.
            raise RuntimeError("chaos: inner transport timed out")

    def _should_drop(self, rel: float) -> bool:
        plan = self._plan
        if (
            plan.drop_p > 0.0
            and self._in_scope()
            and plan.drop_start_s <= rel < plan.drop_end_s
            and self._rng.random() < plan.drop_p
        ):
            plan.count("dropped")
            return True
        plan.count("delivered")
        return False

    # -- passthrough ----------------------------------------------------

    def retarget(self, target_addr: str | None) -> None:
        self._inner.retarget(target_addr)

    def connected(self) -> bool:
        return self._inner.connected()

    def register_rcv_callback(self, fn: Callable[[bytes], None]) -> None:
        self._inner.register_rcv_callback(fn)

    def is_ordered(self) -> bool:
        return self._inner.is_ordered()

    def target_address(self) -> str | None:
        return self._inner.target_address()

    def close(self) -> None:
        self._inner.close()


def _quiet_send(inner: Communicator, data: bytes) -> None:
    """Delayed-delivery landing: by the time the scheduler fires, the
    edge may be closed/retargeted — a late frame into a dead channel is
    just a lost frame, exactly like the real network."""
    try:
        inner.send(data)
    except Exception:  # noqa: BLE001
        pass
