"""Pure-Python TCP transport — fallback + wire-compat cross-check for the
native transport.

Same framing as the native module and the reference
(``[4-byte big-endian length][payload]``, reference ``README.md:76-81``,
``communicator.py:190``): the two implementations interoperate, which the
transport tests verify.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable

from radixmesh_tpu.comm.communicator import Communicator
from radixmesh_tpu.config import DEFAULT_MAX_MSG_BYTES, parse_addr
from radixmesh_tpu.utils.logging import get_logger

__all__ = ["PyTcpCommunicator"]

_LEN = struct.Struct(">I")


class PyTcpCommunicator(Communicator):
    def __init__(
        self,
        bind_addr: str | None,
        target_addr: str | None,
        max_msg_bytes: int = DEFAULT_MAX_MSG_BYTES,
    ):
        self._log = get_logger("comm.tcp_py")
        self._bind = bind_addr
        self._target = target_addr
        self._max_msg = max_msg_bytes
        self._callback: Callable[[bytes], None] | None = None
        self._closed = threading.Event()
        self._send_lock = threading.Lock()
        self._send_sock: socket.socket | None = None
        self._listen_sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []

        if bind_addr is not None:
            host, port = parse_addr(bind_addr)
            self._listen_sock = socket.create_server((host, port), backlog=64)
            t = threading.Thread(target=self._accept_loop, daemon=True)
            t.start()
            self._threads.append(t)

    # ---- receive path (reference communicator.py:212-257) ----

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._listen_sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            t = threading.Thread(target=self._handle_conn, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                hdr = self._recv_all(conn, 4)
                if hdr is None:
                    return
                (length,) = _LEN.unpack(hdr)
                if length == 0 or length > self._max_msg:
                    self._log.error("dropping conn: bad frame length %d", length)
                    return
                payload = self._recv_all(conn, length)
                if payload is None:
                    return
                cb = self._callback
                if cb is not None:
                    try:
                        cb(payload)
                    except Exception:  # noqa: BLE001
                        self._log.exception("receive callback failed")
        finally:
            conn.close()

    @staticmethod
    def _recv_all(conn: socket.socket, n: int) -> bytes | None:
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            try:
                r = conn.recv_into(view[got:], n - got)
            except OSError:
                return None
            if r == 0:
                return None
            got += r
        return bytes(buf)

    # ---- send path (reference communicator.py:162-210) ----

    def _connect(self, deadline: float | None = None) -> socket.socket | None:
        host, port = parse_addr(self._target)
        while not self._closed.is_set():
            if deadline is not None and time.monotonic() >= deadline:
                return None
            try:
                timeout = 5.0
                if deadline is not None:
                    timeout = min(timeout, max(0.05, deadline - time.monotonic()))
                s = socket.create_connection((host, port), timeout=timeout)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.settimeout(None)
                return s
            except OSError:
                # meshcheck: ok[sleep-audit] reconnect backoff between
                # bounded create_connection attempts (peer not up yet).
                time.sleep(0.1)
        raise RuntimeError("communicator closed while connecting")

    def send(self, data: bytes) -> None:
        # Retry (reconnecting) until delivered or closed — a silently
        # dropped frame diverges ring replicas unrecoverably (receivers
        # have no gap detection), so at-least-once beats fail-fast here.
        if not self._send_impl(data, deadline=None):
            raise RuntimeError("communicator closed while sending")

    def try_send(self, data: bytes, timeout_s: float) -> bool:
        return self._send_impl(data, deadline=time.monotonic() + timeout_s)

    def _send_impl(self, data: bytes, deadline: float | None) -> bool:
        if self._closed.is_set():
            raise RuntimeError("communicator closed")
        if self._target is None:
            raise RuntimeError("send-only target not configured")
        if len(data) > self._max_msg:
            raise ValueError(
                f"message of {len(data)} bytes exceeds max_msg_bytes={self._max_msg}"
            )
        frame = _LEN.pack(len(data)) + data
        with self._send_lock:
            while not self._closed.is_set():
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                try:
                    if self._send_sock is None:
                        self._send_sock = self._connect(deadline)
                        if self._send_sock is None:
                            return False  # deadline hit while connecting
                    self._send_sock.sendall(frame)
                    return True
                except OSError:
                    if self._send_sock is not None:
                        self._send_sock.close()
                        self._send_sock = None
                    # meshcheck: ok[sleep-audit] reconnect backoff after a
                    # send failure; the outer loop is deadline-bounded.
                    time.sleep(0.05)
            if deadline is None:
                raise RuntimeError("communicator closed while sending")
            return False

    def retarget(self, target_addr: str | None) -> None:
        """Switch the send channel; the next send connects to the new
        target. Caller (the mesh sender thread) serializes with sends."""
        with self._send_lock:
            if self._send_sock is not None:
                self._send_sock.close()
                self._send_sock = None
            self._target = target_addr

    def connected(self) -> bool:
        return self._send_sock is not None

    def register_rcv_callback(self, fn: Callable[[bytes], None]) -> None:
        self._callback = fn

    def is_ordered(self) -> bool:
        return True

    def target_address(self) -> str | None:
        return self._target

    def close(self) -> None:
        self._closed.set()
        if self._send_sock is not None:
            self._send_sock.close()
        if self._listen_sock is not None:
            self._listen_sock.close()
        for c in self._conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()
