"""In-process transport hub for unit tests.

The reference's "fake backend" is real TCP on loopback (SURVEY §4); that
pattern is kept in ``tests/test_multiprocess.py``, but unit tests of the
mesh-cache logic want a transport with no sockets, no ports, and
deterministic delivery. Messages are delivered on a single per-hub worker
thread, preserving per-link FIFO order like TCP does.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

from radixmesh_tpu.comm.communicator import Communicator

__all__ = ["InprocCommunicator", "InprocHub"]


class InprocHub:
    """Shared registry of listening endpoints + one delivery thread."""

    _default: "InprocHub | None" = None
    _default_lock = threading.Lock()

    def __init__(self):
        self._listeners: dict[str, InprocCommunicator] = {}
        self._q: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    @classmethod
    def default(cls) -> "InprocHub":
        with cls._default_lock:
            if cls._default is None:
                cls._default = cls()
            return cls._default

    @classmethod
    def reset_default(cls) -> None:
        with cls._default_lock:
            hub, cls._default = cls._default, None
        if hub is not None:
            hub._q.put(None)

    def register(self, addr: str, comm: "InprocCommunicator") -> None:
        with self._lock:
            if addr in self._listeners:
                raise ValueError(f"address {addr!r} already bound")
            self._listeners[addr] = comm

    def unregister(self, addr: str) -> None:
        with self._lock:
            self._listeners.pop(addr, None)

    def post(self, target: str, data: bytes) -> None:
        self._q.put((target, data))

    def has_listener(self, addr: str) -> bool:
        with self._lock:
            return addr in self._listeners

    def _run(self) -> None:
        while True:
            # meshcheck: ok[timeout-audit] the hub's delivery pump blocks
            # on its OWN queue and is woken by a None shutdown sentinel —
            # no peer is involved, so there is nothing to deadline.
            item = self._q.get()
            if item is None:
                return
            target, data = item
            with self._lock:
                comm = self._listeners.get(target)
            if comm is not None and comm._callback is not None:
                try:
                    comm._callback(data)
                except Exception:  # noqa: BLE001 — a bad callback must not kill delivery
                    import logging

                    logging.getLogger("radixmesh_tpu.comm").exception(
                        "inproc receive callback failed"
                    )


class InprocCommunicator(Communicator):
    def __init__(self, bind_addr: str | None, target_addr: str | None, hub: InprocHub | None = None):
        self._hub = hub or InprocHub.default()
        self._bind = bind_addr
        self._target = target_addr
        self._callback: Callable[[bytes], None] | None = None
        self._closed = False
        if bind_addr is not None:
            self._hub.register(bind_addr, self)

    def send(self, data: bytes) -> None:
        if self._closed:
            raise RuntimeError("communicator closed")
        if self._target is None:
            raise RuntimeError("send-only target not configured")
        self._hub.post(self._target, bytes(data))

    def try_send(self, data: bytes, timeout_s: float) -> bool:
        """Delivery fails if the target has no live listener (the inproc
        analog of a dead TCP endpoint), after polling for ``timeout_s``."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while not self._closed:
            if self._hub.has_listener(self._target):
                self._hub.post(self._target, bytes(data))
                return True
            if _time.monotonic() >= deadline:
                return False
            # meshcheck: ok[sleep-audit] bounded listener-appearance poll
            # inside a deadline loop; the hub has no registration event.
            _time.sleep(0.005)
        raise RuntimeError("communicator closed")

    def retarget(self, target_addr: str | None) -> None:
        self._target = target_addr

    def connected(self) -> bool:
        return self._target is not None and self._hub.has_listener(self._target)

    def register_rcv_callback(self, fn: Callable[[bytes], None]) -> None:
        self._callback = fn

    def is_ordered(self) -> bool:
        return True

    def target_address(self) -> str | None:
        return self._target

    def close(self) -> None:
        self._closed = True
        if self._bind is not None:
            self._hub.unregister(self._bind)
