from radixmesh_tpu.comm.communicator import Communicator, create_communicator

__all__ = ["Communicator", "create_communicator"]
