"""Transport abstraction for the oplog ring.

Capability parity with the reference's ``communication/communicator.py:14-29``
``Communicator`` ABC (``send``, ``register_rcv_callback``, ``is_ordered``,
``target_address``) and its ``create_communicator`` factory
(``communicator.py:273-276``) — with two deliberate fixes:

- Transports carry **opaque bytes**; oplog serialization lives in
  ``cache/oplog.py``. (The reference couples the JSON serializer into the
  transport, inheriting its GC-field-dropping bug.)
- Protocol names are honest: ``tcp`` is the native C++ transport, ``tcp-py``
  the pure-Python fallback, ``inproc`` the in-process test hub. (The
  reference routes every protocol except the literal string ``'test'`` to
  the half-implemented mooncake RDMA path, including its own default
  ``'tcp'`` — ``communicator.py:273-276`` vs ``cache_config.py:14``.)

Asymmetric endpoints are allowed exactly as in the reference
(``communicator.py:146-157``): a node may listen without a send target
(router) or send without listening.
"""

from __future__ import annotations

import abc
from typing import Callable

from radixmesh_tpu.config import DEFAULT_MAX_MSG_BYTES

__all__ = ["Communicator", "create_communicator"]


class Communicator(abc.ABC):
    """One directed edge of the replication topology: this node's inbound
    listener plus (optionally) a persistent channel to one target node."""

    @abc.abstractmethod
    def send(self, data: bytes) -> None:
        """Queue ``data`` for delivery to the target (async, ordered)."""

    def try_send(self, data: bytes, timeout_s: float) -> bool:
        """Attempt delivery, giving up after ``timeout_s``. Returns False
        on timeout — the failure-detection primitive: a ring predecessor
        is the only node positioned to observe its successor's death
        (``policy/topology.py``). Default: delegate to :meth:`send`."""
        self.send(data)
        return True

    def retarget(self, target_addr: str | None) -> None:
        """Atomically switch the send channel to a new target (ring
        re-formation after a view change). Default: unsupported."""
        raise NotImplementedError(f"{type(self).__name__} cannot retarget")

    def connected(self) -> bool:
        """Best-effort: is the send channel currently live? Failure
        detection only *suspects* peers it has seen connected at least
        once — a slow-starting peer must never be declared dead before
        first contact. Default: True (transports without the signal)."""
        return True

    @abc.abstractmethod
    def register_rcv_callback(self, fn: Callable[[bytes], None]) -> None:
        """Register the function invoked with each received message's
        payload. Must be called before messages arrive."""

    @abc.abstractmethod
    def is_ordered(self) -> bool:
        """True if the transport preserves per-link FIFO order (the ring
        replication protocol assumes it — reference ``radix_mesh.py:404-409``)."""

    @abc.abstractmethod
    def target_address(self) -> str | None: ...

    @abc.abstractmethod
    def close(self) -> None: ...


def create_communicator(
    protocol: str,
    bind_addr: str | None,
    target_addr: str | None,
    max_msg_bytes: int = DEFAULT_MAX_MSG_BYTES,
    src_hint: str | None = None,
) -> Communicator:
    """Build a transport endpoint. ``bind_addr=None`` → send-only;
    ``target_addr=None`` → listen-only.

    This factory is ALSO the chaos seam (``comm/faults.py``): when a
    :class:`~radixmesh_tpu.comm.faults.FaultPlan` is installed, the
    returned endpoint is wrapped in a ``FaultyCommunicator`` that drops,
    delays, duplicates, reorders, partitions, or crashes sends per the
    plan's seeded schedule — product code above this seam never knows.
    ``src_hint`` names the owning node for send-only channels (whose
    ``bind_addr`` is None), so symmetric partitions cut their outbound
    traffic too; it has no effect without an armed plan."""
    if protocol == "inproc":
        from radixmesh_tpu.comm.inproc import InprocCommunicator

        comm: Communicator = InprocCommunicator(bind_addr, target_addr)
    elif protocol == "tcp-py":
        from radixmesh_tpu.comm.tcp_py import PyTcpCommunicator

        comm = PyTcpCommunicator(bind_addr, target_addr, max_msg_bytes)
    elif protocol == "tcp":
        from radixmesh_tpu.comm.tcp_native import NativeTcpCommunicator

        comm = NativeTcpCommunicator(bind_addr, target_addr, max_msg_bytes)
    else:
        raise ValueError(
            f"unknown protocol {protocol!r}; known: inproc, tcp, tcp-py"
        )
    from radixmesh_tpu.comm import faults

    return faults.maybe_wrap(comm, src=bind_addr or src_hint, dst=target_addr)
