"""Two-level hierarchical replication topology (groups + leader spine).

The reference's open roadmap question — "better topo if nodes over some
number (like 50?)" (``/root/reference/README.md:57``) — made concrete. On
the flat ring every oplog takes O(N) *serial* hops to propagate
(``RINGSCALE_r04.json``: lap p50 grows 12x from N=6 to N=50). Here the
static ring ranks are partitioned into contiguous **groups** of
``group_size``; each group runs its own small ring, and the **leaders**
(lowest alive rank per group) form a second ring — the **spine** — that
bridges groups. Propagation becomes

    origin --group lap--> leader --spine--> remote leaders --group laps-->

a critical path of O(group_size + N/group_size) serial hops, minimized at
``group_size ~ sqrt(N)`` (the crossover analysis lives in
ARCHITECTURE.md's ring-scale section).

Circulation rules (enforced by ``MeshCache._circulate``):

- An op originates on its **group ring** (scope GROUP, TTL = one group
  lap, so it returns to the origin like the flat ring's lap).
- The origin group's **leader**, on seeing a GROUP op originated in its
  own group, re-emits it on the **spine** (scope SPINE, TTL = one spine
  lap). A leader-origin emits both scopes directly.
- A leader receiving a SPINE op from another group forwards it along the
  spine and **injects** a GROUP copy into its own ring (TTL = one group
  lap, dying back at the injector by TTL — the injector is not the
  origin, so the origin-drop rule cannot terminate it).
- A SPINE op arriving at a leader whose group *contains the origin* has
  completed its spine lap and is dropped.

Every node applies each op at least once (idempotence tolerates the
leaders' double-copy overlap); total frames stay O(N) per op — the win is
the serial critical path, not byte volume.

All functions derive from (static config ranks, current alive set), so
elastic membership composes: view changes reshuffle leaders/successors
exactly like they reshuffle the flat ring's successor, and a dead leader
is succeeded by the next-lowest alive rank of its group.

Groups are STATIC partitions of the configured rank space — membership
holes (dead ranks) shrink a group but never re-partition it, so two nodes
always agree on ``group_of`` regardless of view skew.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Iterable, Sequence

__all__ = ["HierPlan", "auto_group_size"]


def auto_group_size(ring_size: int) -> int:
    """sqrt(N) balances the two serial segments (group lap + spine lap)."""
    return max(2, int(round(math.sqrt(max(1, ring_size)))))


@dataclass(frozen=True)
class HierPlan:
    """Pure partition math for the two-level topology. ``ring_size`` is the
    STATIC ring member count (P+D); ``alive`` arguments are the current
    view's alive ranks (any iterable of ints)."""

    ring_size: int
    group_size: int

    def __post_init__(self):
        if self.group_size < 2:
            raise ValueError(f"group_size must be >= 2, got {self.group_size}")
        if self.ring_size < 1:
            raise ValueError("ring_size must be >= 1")

    # ---- static partition ----

    @property
    def n_static_groups(self) -> int:
        return (self.ring_size + self.group_size - 1) // self.group_size

    def group_of(self, rank: int) -> int:
        if not 0 <= rank < self.ring_size:
            raise ValueError(f"rank {rank} outside ring [0, {self.ring_size})")
        return rank // self.group_size

    def same_group(self, a: int, b: int) -> bool:
        return self.group_of(a) == self.group_of(b)

    def group_ranks(self, g: int) -> range:
        return range(
            g * self.group_size, min((g + 1) * self.group_size, self.ring_size)
        )

    # ---- alive-set-dependent structure ----

    def group_alive(self, g: int, alive: Iterable[int]) -> list[int]:
        lo, hi = g * self.group_size, min((g + 1) * self.group_size, self.ring_size)
        return sorted(r for r in alive if lo <= r < hi)

    def leader_of(self, g: int, alive: Iterable[int]) -> int | None:
        members = self.group_alive(g, alive)
        return members[0] if members else None

    def is_leader(self, rank: int, alive: Iterable[int]) -> bool:
        return self.leader_of(self.group_of(rank), alive) == rank

    def nonempty_groups(self, alive: Iterable[int]) -> list[int]:
        alive = list(alive)
        return [g for g in range(self.n_static_groups) if self.group_alive(g, alive)]

    def group_successor(self, rank: int, alive: Iterable[int]) -> int | None:
        """Next alive rank within ``rank``'s group, cyclic. None when alone
        (a sole member has nobody to ring — its leader duties still bridge
        the op onto the spine)."""
        members = self.group_alive(self.group_of(rank), alive)
        others = [r for r in members if r != rank]
        if not others:
            return None
        for r in others:
            if r > rank:
                return r
        return others[0]

    def spine_successor(self, rank: int, alive: Iterable[int]) -> int | None:
        """Leader of the next nonempty group, cyclic over groups. None when
        this group is the only nonempty one (degenerate: flat semantics)."""
        alive = list(alive)
        g = self.group_of(rank)
        groups = self.nonempty_groups(alive)
        nxt = [x for x in groups if x > g] + [x for x in groups if x < g]
        if not nxt:
            return None
        return self.leader_of(nxt[0], alive)

    # ---- TTLs (hops at each level) ----

    def group_ttl(self, rank: int, alive: Iterable[int]) -> int:
        """One full lap of ``rank``'s group ring (returns to the sender)."""
        return max(1, len(self.group_alive(self.group_of(rank), alive)))

    def spine_ttl(self, alive: Iterable[int]) -> int:
        """One full lap of the leader spine."""
        return max(1, len(self.nonempty_groups(alive)))

    # ---- diagnostics ----

    def describe(self, alive: Sequence[int]) -> str:
        parts = []
        for g in self.nonempty_groups(alive):
            members = self.group_alive(g, alive)
            parts.append(f"g{g}[{members[0]}*{',' if len(members) > 1 else ''}"
                         f"{','.join(str(r) for r in members[1:])}]")
        return " ".join(parts)
