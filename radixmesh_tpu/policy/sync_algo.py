"""Ring-sync topology policy: who sends to whom, and how long oplogs live.

Capability parity with the reference's ``policy/sync_algo.py``:

- The replication topology is a **unidirectional ring of prefill + decode
  nodes** (prefill ranks first, then decode), successor = ``(rank+1) % N``
  (``sync_algo.py:57-75``). Routers sit *outside* the ring and receive a
  fan-out copy of every oplog from the **master** (global rank 0, the first
  prefill node — ``sync_algo.py:54-55``, ``radix_mesh.py:344-347``).
- TTLs count ring hops: data oplogs live one full lap (``ttl = N``), ticks
  live two laps for two-round topology verification (``sync_algo.py:98-104``,
  reference ``README.md:91-93``), GC queries one lap so unanimity can be
  counted at the origin (``sync_algo.py:106-107``).
- Send/receive permissions: prefill + decode send, everyone receives,
  routers never send (``sync_algo.py:80-96``). The tick originator is the
  first decode node, falling back to the master when there are no decode
  nodes (the reference has no fallback, ``sync_algo.py:109-110``).

This layer is transport-agnostic: it only names addresses; the actual wire
lives in ``comm/``. On TPU pods the same policy drives the DCN oplog ring
between hosts, while KV-page payloads move over ICI via collectives
(SURVEY §5 "Distributed communication backend").
"""

from __future__ import annotations

import abc
from dataclasses import dataclass

from radixmesh_tpu.config import MeshConfig, NodeRole

__all__ = ["TopoResult", "BaseSyncAlgo", "RingSyncAlgo", "get_sync_algo"]


@dataclass
class TopoResult:
    """This node's view of the topology (reference ``sync_algo.py:10-14``)."""

    next_node: str | None  # ring successor address (None for routers)
    routers: list[str]  # router addresses to fan out to (master only)
    bind_addr: str  # address this node listens on


class BaseSyncAlgo(abc.ABC):
    """Strategy interface (reference ``sync_algo.py:16-47``)."""

    @abc.abstractmethod
    def topo(self, cfg: MeshConfig) -> TopoResult: ...

    @abc.abstractmethod
    def master_rank(self, cfg: MeshConfig) -> int: ...

    @abc.abstractmethod
    def ring(self, cfg: MeshConfig) -> list[str]: ...

    @abc.abstractmethod
    def can_send(self, cfg: MeshConfig) -> bool: ...

    @abc.abstractmethod
    def can_recv(self, cfg: MeshConfig) -> bool: ...

    def view_tick_origin(self, cfg: MeshConfig, alive) -> int:
        """Tick origin for a RUNTIME membership view (``alive`` = iterable
        of alive global ranks). Defaults to the static origin; algos
        override to fail origination over when it dies."""
        return self.tick_origin_rank(cfg)

    @abc.abstractmethod
    def tick_origin_rank(self, cfg: MeshConfig) -> int:
        """Global rank of the node that originates heartbeat ticks — the
        rank every node's startup barrier watches for."""

    @abc.abstractmethod
    def data_ttl(self, cfg: MeshConfig) -> int: ...

    @abc.abstractmethod
    def tick_ttl(self, cfg: MeshConfig) -> int: ...

    @abc.abstractmethod
    def gc_ttl(self, cfg: MeshConfig) -> int: ...


class RingSyncAlgo(BaseSyncAlgo):
    """The sole production policy (reference ``sync_algo.py:50-110``)."""

    def ring(self, cfg: MeshConfig) -> list[str]:
        return list(cfg.prefill_nodes) + list(cfg.decode_nodes)

    def master_rank(self, cfg: MeshConfig) -> int:
        return 0  # first prefill node (sync_algo.py:54-55)

    def topo(self, cfg: MeshConfig) -> TopoResult:
        role, rank, _ = cfg.local_identity()
        if role is NodeRole.ROUTER:
            return TopoResult(next_node=None, routers=[], bind_addr=cfg.local_addr)
        ring = self.ring(cfg)
        successor = ring[(rank + 1) % len(ring)]
        routers = (
            list(cfg.router_nodes) if rank == self.master_rank(cfg) else []
        )  # only the master feeds routers (sync_algo.py:63-66)
        return TopoResult(next_node=successor, routers=routers, bind_addr=cfg.local_addr)

    def can_send(self, cfg: MeshConfig) -> bool:
        return cfg.local_role in (NodeRole.PREFILL, NodeRole.DECODE)

    def can_recv(self, cfg: MeshConfig) -> bool:
        return True

    def tick_origin_rank(self, cfg: MeshConfig) -> int:
        # INITIAL tick origin: the first decode node (sync_algo.py:109-110),
        # falling back to the master when the cluster has no decode nodes.
        return cfg.num_prefill if cfg.num_decode > 0 else self.master_rank(cfg)

    def view_tick_origin(self, cfg: MeshConfig, alive) -> int:
        # Runtime origination follows the view so a dead origin fails
        # over: lowest alive decode rank, else lowest alive rank. On the
        # initial full view this equals ``tick_origin_rank``.
        alive = list(alive)
        decode = [r for r in alive if cfg.is_decode_rank(r)]
        pool = decode or alive
        return min(pool) if pool else self.tick_origin_rank(cfg)

    def data_ttl(self, cfg: MeshConfig) -> int:
        return cfg.num_ring  # one full lap (sync_algo.py:98-101)

    def tick_ttl(self, cfg: MeshConfig) -> int:
        return 2 * cfg.num_ring  # two-round verification (sync_algo.py:103-104)

    def gc_ttl(self, cfg: MeshConfig) -> int:
        return cfg.num_ring  # unanimity over one lap (sync_algo.py:106-107)


_ALGOS = {"ring": RingSyncAlgo}


def get_sync_algo(name: str = "ring") -> BaseSyncAlgo:
    """Factory (reference ``sync_algo.py:113-114``)."""
    try:
        return _ALGOS[name]()
    except KeyError:
        raise ValueError(f"unknown sync algo {name!r}; known: {sorted(_ALGOS)}")
