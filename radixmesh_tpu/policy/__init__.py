from radixmesh_tpu.policy.conflict import NodeRankConflictResolver
from radixmesh_tpu.policy.lifecycle import (
    AutoscalePolicy,
    LifecyclePlane,
    LifecycleState,
)
from radixmesh_tpu.policy.sync_algo import BaseSyncAlgo, RingSyncAlgo, TopoResult, get_sync_algo

__all__ = [
    "NodeRankConflictResolver",
    "AutoscalePolicy",
    "LifecyclePlane",
    "LifecycleState",
    "BaseSyncAlgo",
    "RingSyncAlgo",
    "TopoResult",
    "get_sync_algo",
]
