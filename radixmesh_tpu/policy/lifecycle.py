"""Membership lifecycle plane: warm join, graceful drain, autoscale policy.

Production traffic is diurnal, so the fleet must grow and shrink LIVE —
and a planned topology change is not a failure. Before this module the
mesh had every ingredient (gossiped ``FleetView`` health, the
fingerprint-driven repair plane, streamed KV movement, seeded fault
injection) but composed none of them: a new node started with a cold
replica and served misses for minutes while gossip trickled in, and a
departing node simply died — stranding parked restores and in-flight
decodes until failure detection and anti-entropy cleaned up after the
fact. This module makes scale-out/scale-in a first-class state machine::

    BOOTSTRAPPING ──► ACTIVE ──► DRAINING ──► LEFT
          └───────────────────────┘ (drain during bootstrap)

- **Warm join** (``BOOTSTRAPPING``): the node announces ``JOIN`` as
  always, but additionally opens a *bulk repair session* against a
  healthy donor chosen from the ``FleetView`` (the anti-entropy
  probe/summary/re-emit protocol of ``cache/repair_plane.py`` with
  raised per-session bucket/key budgets over a dedicated bootstrap
  channel), and gossips its state in the ``NodeDigest`` so the router
  withholds cache-hit routing to it — hash-ring fallback only — until
  its tree fingerprint converges with the donor's.
- **Graceful drain** (``DRAINING`` → ``LEFT``): admission closes (the
  SLO runner sheds new work with a retriable 503 + Retry-After pointing
  back at the router), in-flight decodes run to completion under a
  deadline, parked ``RESTORING`` requests are cancelled-and-flagged for
  requeue at the router, hot prefixes are written back to the host tier
  through the fused write-back lane, and a ``LEAVE`` oplog
  (``cache/oplog.py``) lets peers drop the node from the view without
  tripping ``_declare_successor_dead``'s failure path or poisoning
  ``FleetView`` convergence/min-score.
- **Autoscale recommender**: :class:`AutoscalePolicy` is PURE policy —
  it consumes ``FleetView`` health scores, queue depth, and the SLO
  degradation tier and emits add/remove recommendations (surfaced on
  ``GET /cluster/health``; consumed by the workload driver — no actual
  process spawning here).

**Single-writer contract** (lint-pinned by ``tests/test_mesh_lint.py``):
this module is the ONLY place lifecycle state is assigned. Everything
else — router, fleet plane, frontends, the engine — only *reads* it
(via ``LifecyclePlane.state`` / the gossiped digest field). A plane that
anyone could flip to ``ACTIVE`` mid-bootstrap would silently re-enable
cold hit-routing.

**Deflake contract**: every timer (bootstrap convergence wait, drain
deadline, the plane's tick) runs on an injectable clock + wait seam,
like ``comm/faults.py`` — tests drive lifecycle logic in virtual time,
and no wait is unbounded.

Import-light on purpose (stdlib + the obs registry — no jax): router
nodes and the chaos workload use it without pulling in a backend.
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass

__all__ = [
    "LifecycleState",
    "LifecycleError",
    "LifecycleConfig",
    "LifecyclePlane",
    "AutoscaleConfig",
    "AutoscalePolicy",
    "lifecycle_code",
    "lifecycle_from_code",
]



class LifecycleState(enum.Enum):
    """One node's membership lifecycle (ARCHITECTURE.md "Membership
    lifecycle"). String values are the wire/gossip vocabulary — the
    digest, ``/cluster/health``, and the router compare these strings so
    readers never need to import this module."""

    BOOTSTRAPPING = "bootstrapping"
    ACTIVE = "active"
    DRAINING = "draining"
    LEFT = "left"


# Compact digest encoding (rides the NodeDigest tier byte's high nibble,
# obs/fleet_plane.py): code 0 == ACTIVE so every pre-lifecycle encoder —
# which writes 0 there — reads back as the state it factually was in.
_STATE_CODES = {
    LifecycleState.ACTIVE: 0,
    LifecycleState.BOOTSTRAPPING: 1,
    LifecycleState.DRAINING: 2,
    LifecycleState.LEFT: 3,
}
_CODE_STATES = {v: k for k, v in _STATE_CODES.items()}


def lifecycle_code(state: str) -> int:
    """State string → 4-bit wire code (unknown → ACTIVE's 0)."""
    try:
        return _STATE_CODES[LifecycleState(state)]
    except ValueError:
        return 0


def lifecycle_from_code(code: int) -> str:
    """4-bit wire code → state string (unknown → "active": a NEWER
    peer's state must degrade to normal routing, not an error)."""
    return _CODE_STATES.get(int(code), LifecycleState.ACTIVE).value


# Imported AFTER the state enum + wire-code helpers on purpose: obs
# (fleet_plane) imports those helpers back from this module, so they
# must exist before this import re-enters us mid-initialization —
# otherwise the first import of radixmesh_tpu.policy.* from a cold
# process dies on the cycle.
from radixmesh_tpu.obs.metrics import get_registry  # noqa: E402
from radixmesh_tpu.obs.trace_plane import get_recorder  # noqa: E402
from radixmesh_tpu.utils.logging import get_logger  # noqa: E402

# The legal transition edges. Anything else is a bug in the caller —
# e.g. LEFT is terminal (a rejoin is a NEW plane on a NEW MeshCache),
# and nothing un-drains.
_VALID_TRANSITIONS = {
    (LifecycleState.BOOTSTRAPPING, LifecycleState.ACTIVE),
    (LifecycleState.BOOTSTRAPPING, LifecycleState.DRAINING),
    (LifecycleState.ACTIVE, LifecycleState.DRAINING),
    (LifecycleState.DRAINING, LifecycleState.LEFT),
}


class LifecycleError(RuntimeError):
    """Illegal lifecycle transition or re-entrant drain."""


@dataclass
class LifecycleConfig:
    """Timers + budgets. Production-cadence defaults; tests and the
    chaos workload shrink them (all waits run on the plane's injectable
    clock, so quick tests can also drive them in virtual time)."""

    # How long a BOOTSTRAPPING node waits for a donor candidate (any
    # ACTIVE peer digest) before concluding there is nothing to learn
    # from and going ACTIVE — a cold cluster boot must not withhold
    # every node forever. Must exceed the digest interval (and any
    # partition a chaos drill runs across the join).
    bootstrap_grace_s: float = 15.0
    # Hard ceiling on the whole bootstrap: past it the node goes ACTIVE
    # cold (serving misses beats never serving) with a warning.
    bootstrap_deadline_s: float = 120.0
    # Pacing between bulk-repair probe rounds against the donor.
    bootstrap_probe_interval_s: float = 0.5
    # The join chaos gate: a bootstrap must converge within this many
    # probe rounds (the bulk budgets are sized so a full replica moves
    # in a handful of rounds).
    bootstrap_round_budget: int = 16
    # Drain: how long in-flight decodes get to run to completion before
    # the stragglers are cancelled. launch.py --drain-timeout overrides.
    drain_timeout_s: float = 30.0
    # Retry-After handed to shed clients during a drain (they re-route
    # via the router immediately; the hint bounds dumb retry loops).
    drain_retry_after_s: float = 1.0
    # How many times the LEAVE announcement is re-broadcast if this
    # node does not observe its own exclusion (a lossy wire can eat the
    # frame; re-announcing is idempotent — the view is epoch-guarded).
    leave_retries: int = 3
    leave_confirm_s: float = 1.0
    # The plane thread's scan cadence while BOOTSTRAPPING.
    tick_interval_s: float = 0.25


class LifecyclePlane:
    """Per-node owner of the lifecycle state machine.

    Seams (all optional — the chaos workload runs mesh-only nodes, the
    serving path wires everything):

    - ``repair``: the node's :class:`~radixmesh_tpu.cache.repair_plane.
      RepairPlane`; warm bootstrap drives bulk sessions through it.
    - ``runner``: the node's ``EngineRunner``/``SLORunner``; drain
      closes admission, requeues parked restores, waits out decodes,
      and flushes hot prefixes through it.
    - ``fleet_plane``: the node's digest publisher; state changes
      publish immediately so routers react within one fold, not one
      gossip interval.
    - ``requeue_fn`` / ``writeback_fn``: engine-less stand-ins for the
      drain's requeue and hot-prefix flush steps (the mesh-level chaos
      workload supplies these; with a ``runner`` they are ignored).
    - ``blackbox``: the node's :class:`~radixmesh_tpu.obs.blackbox.
      BlackBox`; the drain sequence flushes it (step 5c) once in-flight
      work has settled, so every planned departure leaves a complete
      post-mortem artifact behind.
    - ``clock`` / ``wait``: virtual-time injection (deflake contract).
    """

    def __init__(
        self,
        mesh,
        repair=None,
        runner=None,
        fleet_plane=None,
        cfg: LifecycleConfig | None = None,
        bootstrap: bool = False,
        requeue_fn=None,
        writeback_fn=None,
        blackbox=None,
        clock=time.monotonic,
        wait=None,
    ):
        self.mesh = mesh
        self.repair = repair
        self.runner = runner
        self.fleet_plane = fleet_plane
        self.blackbox = blackbox
        self.cfg = cfg or LifecycleConfig()
        self.requeue_fn = requeue_fn
        self.writeback_fn = writeback_fn
        self.clock = clock
        self._stop = threading.Event()
        # Injectable wait: default parks on the stop event so close()
        # interrupts sleeps; virtual-time tests pass their own.
        self._wait = wait or (lambda t: self._stop.wait(t))
        self.log = get_logger(f"lifecycle.{mesh._node_label}")
        self._lock = threading.Lock()
        self._state = (
            LifecycleState.BOOTSTRAPPING if bootstrap else LifecycleState.ACTIVE
        )
        self._t_enter = self.clock()
        self._thread: threading.Thread | None = None
        self._drain_thread: threading.Thread | None = None
        # Exactly-one-drain claim, taken under the lock: request_drain's
        # thread and a direct drain() call (SIGTERM exit path) can race,
        # and both passing an unlocked state check would double-run the
        # sequence — the loser's illegal DRAINING→DRAINING transition
        # would abort the graceful exit mid-way.
        self._drain_claimed = False
        self._next_probe = 0.0
        # Bootstrap accounting (the join chaos gates read these).
        self.bootstrap_donor: int | None = None
        self.bootstrap_rounds = 0
        self.bootstrap_converge_s: float | None = None
        self.drain_stats: dict | None = None

        reg = get_registry()
        node = mesh._node_label
        self._g_state = reg.gauge(
            "radixmesh_lifecycle_state",
            "membership lifecycle state code (0=active, 1=bootstrapping, "
            "2=draining, 3=left)",
            ("node",),
        ).labels(node=node)
        trans = reg.counter(
            "radixmesh_lifecycle_transitions_total",
            "lifecycle state transitions, by entered state",
            ("node", "state"),
        )
        self._m_trans = {
            s: trans.labels(node=node, state=s.value) for s in LifecycleState
        }
        self._g_state.set(float(_STATE_CODES[self._state]))
        # Register as the mesh's (read-only to everyone else) lifecycle
        # source: the fleet plane folds .state into the digest, the
        # receive path consults is_departing, frontends snapshot status.
        mesh.lifecycle = self

    # -- state machine (the ONLY writer — see module docstring) ---------

    @property
    def state(self) -> LifecycleState:
        return self._state

    @property
    def is_departing(self) -> bool:
        """True once the node is on its way out (DRAINING or LEFT): the
        mesh receive path uses this to suppress the falsely-declared-
        dead auto-rejoin — a planned exclusion view is not a false
        declaration — and the housekeeper suppresses self-assertion
        JOINs the same way."""
        return self._state in (LifecycleState.DRAINING, LifecycleState.LEFT)

    def _transition(self, new: LifecycleState) -> None:
        with self._lock:
            cur = self._state
            if (cur, new) not in _VALID_TRANSITIONS:
                raise LifecycleError(
                    f"illegal lifecycle transition {cur.value} -> {new.value}"
                )
            self._state = new
            t_prev, self._t_enter = self._t_enter, self.clock()
        self._g_state.set(float(_STATE_CODES[new]))
        self._m_trans[new].inc()
        rec = get_recorder()
        if rec.enabled:
            # One span per state dwelled in, on this node's lifecycle
            # lane — scale events line up against request timelines.
            rec.event(
                f"lifecycle:{self.mesh._node_label}", cur.value,
                t_prev, max(0.0, self.clock() - t_prev),
                cat="lifecycle", to=new.value,
            )
        self.log.info("lifecycle %s -> %s", cur.value, new.value)
        if new is not LifecycleState.LEFT:
            # LEFT is announced by the LEAVE oplog, not a digest: peers
            # FORGET a departed node's telemetry, and a final "left"
            # digest racing the LEAVE would just be refused (FleetView
            # fold guard) or, worse on old receivers, re-pin a frozen
            # fingerprint in the convergence audit.
            self._publish_state()

    def _publish_state(self) -> None:
        """Gossip the new state NOW (one extra digest frame) so routers
        react within a fold instead of a full digest interval."""
        if self.fleet_plane is None:
            return
        try:
            self.fleet_plane.publish_once()
        except Exception:  # noqa: BLE001 — gossip lag degrades, never blocks
            self.log.exception("lifecycle digest publish failed")

    # -- warm bootstrap -------------------------------------------------

    def choose_donor(self) -> int | None:
        """The healthiest ACTIVE peer the FleetView knows (ties → the
        freshest digest, then the lowest rank). Health-aware on purpose:
        during a join-under-partition drill the partitioned peer's
        digest goes stale, its score drops, and the joiner bootstraps
        from a reachable donor instead of wedging on a dead one."""
        fleet = self.mesh.fleet
        health = fleet.health()
        best_rank, best_key = None, None
        for rank, d in fleet.digests().items():
            if rank == self.mesh.rank or d.role == "router":
                continue
            if d.lifecycle != LifecycleState.ACTIVE.value:
                continue
            score = health.get(rank, {}).get("score", 0.0)
            key = (score, d.ts, -rank)
            if best_key is None or key > best_key:
                best_rank, best_key = rank, key
        return best_rank

    def bootstrap_status(self) -> dict:
        return {
            "state": self._state.value,
            "donor_rank": self.bootstrap_donor,
            "rounds": self.bootstrap_rounds,
            "round_budget": self.cfg.bootstrap_round_budget,
            "converge_s": self.bootstrap_converge_s,
        }

    def tick(self) -> None:
        """One bootstrap scan (the plane thread calls this on its timer;
        tests drive it directly, in virtual time when they want). ACTIVE/
        DRAINING/LEFT ticks are no-ops."""
        if self._state is not LifecycleState.BOOTSTRAPPING:
            return
        now = self.clock()
        mesh = self.mesh
        donor = self.choose_donor()
        if donor is None:
            # No ACTIVE peer to learn from. If every KNOWN peer replica
            # already equals ours, there is nothing to pull — the cold-
            # cluster case, where every node boots BOOTSTRAPPING at the
            # same instant and a donor requirement would deadlock them
            # all into the full grace window for no benefit (an empty
            # fleet has no hits to withhold). Otherwise gossip may still
            # be in flight: wait out the grace window, then serve.
            # (Convergence is the mesh's call — scalar fingerprints full
            # replica, per-co-owned-shard under sharding.)
            peers = mesh.convergence_peers()
            if peers and all(
                mesh.bootstrap_converged_with(r) for r in peers
            ):
                self.log.info(
                    "bootstrap: all %d known peers already converged with "
                    "this replica — going active", len(peers),
                )
                self._become_active(now)
                return
            if now - self._t_enter >= self.cfg.bootstrap_grace_s:
                self.log.info(
                    "bootstrap: no donor after %.1fs grace — going active",
                    now - self._t_enter,
                )
                self._become_active(now)
            return
        self.bootstrap_donor = donor
        if mesh.bootstrap_converged_with(donor):
            self.log.info(
                "bootstrap: converged with donor rank %d after %d rounds",
                donor, self.bootstrap_rounds,
            )
            self._become_active(now)
            return
        if now - self._t_enter > self.cfg.bootstrap_deadline_s:
            self.log.warning(
                "bootstrap deadline (%.0fs) exceeded after %d rounds — "
                "going active COLD (steady-state repair will finish the "
                "fill)", self.cfg.bootstrap_deadline_s, self.bootstrap_rounds,
            )
            self._become_active(now)
            return
        if self.repair is not None and now >= self._next_probe:
            self._next_probe = now + self.cfg.bootstrap_probe_interval_s
            if self.repair.bootstrap_probe(donor):
                self.bootstrap_rounds += 1

    def _become_active(self, now: float) -> None:
        self.bootstrap_converge_s = max(0.0, now - self._t_enter)
        self._transition(LifecycleState.ACTIVE)

    # -- graceful drain -------------------------------------------------

    def request_drain(self, deadline_s: float | None = None) -> bool:
        """Kick an asynchronous drain (the ``POST /admin/drain`` entry
        point — the HTTP handler must not block for the full deadline).
        Returns False when a drain is already running/complete."""
        with self._lock:
            if (
                self._drain_thread is not None
                or self._drain_claimed
                or self._state is LifecycleState.LEFT
            ):
                return False
            self._drain_thread = threading.Thread(
                target=self._drain_guarded, args=(deadline_s,),
                daemon=True, name="lifecycle-drain",
            )
        self._drain_thread.start()
        return True

    def _drain_guarded(self, deadline_s: float | None) -> None:
        try:
            self.drain(deadline_s)
        except Exception:  # noqa: BLE001 — a drain bug must not kill the node silently
            self.log.exception("drain failed")

    def drain(self, deadline_s: float | None = None) -> dict:
        """The full drain sequence, synchronously. Idempotent once LEFT
        (returns the recorded stats); raises :class:`LifecycleError` if
        called re-entrantly mid-drain from a second thread."""
        deadline_s = (
            self.cfg.drain_timeout_s if deadline_s is None else float(deadline_s)
        )
        # Claim the drain under the lock: exactly one caller runs the
        # sequence; a racing caller (SIGTERM exit vs an accepted
        # /admin/drain) WAITS for the winner instead of truncating the
        # graceful exit with an illegal double transition.
        with self._lock:
            if self._state is LifecycleState.LEFT:
                return dict(self.drain_stats or {})
            if self._drain_claimed:
                waited = self._drain_thread
                if waited is None or waited is threading.current_thread():
                    raise LifecycleError("drain already in progress")
            else:
                self._drain_claimed = True
                waited = None
        if waited is not None:
            waited.join(timeout=deadline_s + 10.0)
            return dict(self.drain_stats or {})
        try:
            return self._drain_sequence(deadline_s)
        except BaseException:
            # Release the claim so a RETRY is possible: a failed drain
            # wedged in DRAINING with the claim held would leave the
            # node permanently out of rotation (routers shed it) with
            # no way to finish leaving short of a kill — exactly the
            # failure-detection exit the drain exists to avoid. The
            # state stays DRAINING (nothing un-drains); a retried
            # drain() resumes from there.
            with self._lock:
                self._drain_claimed = False
                self._drain_thread = None
            raise

    def _drain_sequence(self, deadline_s: float) -> dict:
        t0 = self.clock()
        # 1. DRAINING is visible first: the state gossips immediately
        #    (publish in _transition), so the router stops handing this
        #    node NEW work before anything below runs. A RETRY after a
        #    failed attempt is already DRAINING and skips the transition.
        if self._state is not LifecycleState.DRAINING:
            self._transition(LifecycleState.DRAINING)
        stats: dict = {
            "requeued": 0,
            "completed_in_flight": True,
            "writeback_tokens": 0,
            "writeback_flushed": False,
        }
        # 2. Close local admission: new submits shed retriably (503 +
        #    Retry-After; the body names the router to retry through).
        runner = self.runner
        if runner is not None:
            runner.begin_drain(self.cfg.drain_retry_after_s)
        # 2b. Quiesce this node's repair plane: a departing replica must
        #     neither originate probes nor keep feeding peers entries
        #     that are about to leave the fleet. Peers' in-flight
        #     sessions against us abort cleanly on their side — the
        #     LEAVE drops us from their fleet view, and their next scan
        #     prunes the peer state (backoff, budgets) with it.
        if self.repair is not None:
            self.repair.close()
        # 3. Cancel-and-requeue queued + parked-RESTORING requests: they
        #    have produced nothing, so bouncing them to the router loses
        #    no work — while in-flight decodes are left to finish.
        if runner is not None:
            stats["requeued"] = runner.drain_requeue()
        elif self.requeue_fn is not None:
            stats["requeued"] = int(self.requeue_fn() or 0)
        # 4. In-flight decodes run to completion under the deadline
        #    (stragglers are cancelled — partial output returns, flagged).
        if runner is not None:
            stats["completed_in_flight"] = runner.drain_wait_idle(deadline_s)
        # 5. Hot prefixes → host tier through the fused write-back lane,
        #    so a warm rejoin (or a sibling's restore) finds them.
        #    flushed reports the WRITE BARRIER's verdict, not intent: a
        #    timed-out or failed arena write must not read as durably
        #    flushed on /debug/state or in the chaos drain gate.
        if runner is not None:
            tokens, flushed = runner.drain_flush()
            stats["writeback_tokens"] = tokens
            stats["writeback_flushed"] = bool(flushed)
        elif self.writeback_fn is not None:
            stats["writeback_tokens"] = int(self.writeback_fn() or 0)
            stats["writeback_flushed"] = True
        # 5a. Hot subtrees → DISK (cache/kv_tier.py): the host flush
        #     above only survives this process; forcing the arena's
        #     working set into checksummed extents makes the departure
        #     survivable even if the whole cell later loses power
        #     before anyone rejoins. committed reports the spill
        #     commits' verdict (the write-back discipline of step 5).
        #     No-op (0, True) on runners without a disk tier.
        if runner is not None and hasattr(runner, "drain_flush_disk"):
            try:
                spilled, committed = runner.drain_flush_disk()
                stats["disk_spill_nodes"] = int(spilled)
                stats["disk_spill_committed"] = bool(committed)
            except Exception:  # noqa: BLE001 — a tier bug must not wedge the drain
                self.log.exception("disk-tier drain flush failed")
                stats["disk_spill_nodes"] = 0
                stats["disk_spill_committed"] = False
        # 5b. Sharded ownership transfer (cache/sharding.py): hand each
        #     owned shard's entries to the ranks that BECOME owners once
        #     this node leaves — the RF invariant must survive the
        #     departure without waiting out anti-entropy. No-op on a
        #     full-replica mesh (everyone already has everything).
        mesh = self.mesh
        if getattr(mesh, "sharded", False):
            try:
                stats["shard_transfer"] = mesh.handoff_owned_shards()
                mesh.flush_outbound(self.cfg.leave_confirm_s)
            except Exception:  # noqa: BLE001 — a transfer bug must not wedge the drain
                self.log.exception("shard handoff failed")
                stats["shard_transfer"] = {"shards": 0, "entries": 0,
                                           "targets": 0}
        # 5c. Black-box flush (obs/blackbox.py): in-flight work has
        #     settled and the write-back verdict is known — record the
        #     full telemetry history + findings + state NOW, while the
        #     node can still write. A flush failure must not block the
        #     LEAVE (the dump is evidence, not a durability barrier).
        if self.blackbox is not None:
            try:
                stats["blackbox"] = self.blackbox.flush("drain")["path"]
            except Exception:  # noqa: BLE001 — a dump bug must not wedge the drain
                self.log.exception("black-box drain flush failed")
                stats["blackbox"] = None
        # 6. LEAVE: peers drop this node from the view as a PLANNED
        #    departure (cause="left" — failure detection never fires,
        #    FleetView state is forgotten, not left to rot). The frame
        #    is droppable like any oplog — and once the FIRST copy lands
        #    anywhere, peers retarget AWAY from this node, so no
        #    confirmation can ever flow back. Redundant spaced
        #    announcements stand in for an ack: each carries the same
        #    exclusion view (epoch-guarded — duplicates are exact
        #    no-ops on peers that already adopted it), so surviving any
        #    ONE of them suffices, and tick-piggybacked view gossip
        #    spreads it from there.
        retries = max(1, self.cfg.leave_retries)
        for i in range(retries):
            mesh.broadcast_leave()
            mesh.flush_outbound(self.cfg.leave_confirm_s)
            if i + 1 < retries:
                self._wait(self.cfg.leave_confirm_s)
        stats["leave_announcements"] = retries
        self._transition(LifecycleState.LEFT)
        stats["drain_s"] = max(0.0, self.clock() - t0)
        self.drain_stats = stats
        return stats

    # -- misc -----------------------------------------------------------

    def router_hint(self) -> str | None:
        """Where shed clients should retry: the cluster's router node
        (cache address; its serving API derives from it)."""
        nodes = getattr(self.mesh.cfg, "router_nodes", None)
        return nodes[0] if nodes else None

    def status(self) -> dict:
        """The ``/debug/state`` lifecycle block."""
        out = {"state": self._state.value, "is_departing": self.is_departing}
        if self._state is LifecycleState.BOOTSTRAPPING or self.bootstrap_donor is not None:
            out["bootstrap"] = self.bootstrap_status()
        if self.drain_stats is not None:
            out["drain"] = dict(self.drain_stats)
        return out

    # -- thread ---------------------------------------------------------

    def start(self) -> "LifecyclePlane":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="lifecycle-plane"
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        dt = self._drain_thread
        if dt is not None:
            dt.join(timeout=2)
        # Detach ONLY when not departing: the mesh keeps receiving for a
        # beat after close() on the exit path, and clearing the
        # reference would drop the is_departing guard — a straggling
        # exclusion view would then re-trigger the falsely-declared-dead
        # auto-rejoin JOIN moments before the process exits, forcing
        # peers into the failure-detection churn the drain avoided.
        if (
            getattr(self.mesh, "lifecycle", None) is self
            and not self.is_departing
        ):
            self.mesh.lifecycle = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — lifecycle must not kill the node
                self.log.exception("lifecycle tick failed")
            if self._state is not LifecycleState.BOOTSTRAPPING:
                # Nothing periodic to do outside bootstrap; park until
                # close (drains run on their own thread).
                self._stop.wait(max(1.0, self.cfg.tick_interval_s))
            else:
                self._wait(self.cfg.tick_interval_s)


# ---------------------------------------------------------------------------
# autoscale recommender (pure policy — no threads, no process spawning)
# ---------------------------------------------------------------------------


@dataclass
class AutoscaleConfig:
    """Thresholds for the recommender. Deliberately coarse: autoscaling
    reacts in minutes, so hair-trigger thresholds just flap."""

    min_nodes: int = 2
    max_nodes: int = 64
    # Add capacity when queued demand per HEALTHY serving node exceeds
    # this, or when any node sits at/above the SLO degradation tier.
    scale_up_waiting_per_node: float = 8.0
    scale_up_slo_tier: int = 2
    # Remove capacity only when the fleet is comfortably idle.
    scale_down_waiting_per_node: float = 1.0
    scale_down_occupancy: float = 0.3
    # A node scoring below this does not count as capacity.
    healthy_threshold: float = 0.5


class AutoscalePolicy:
    """Pure-policy add/remove recommendations from fleet telemetry.

    ``recommend`` consumes a :class:`~radixmesh_tpu.obs.fleet_plane.
    FleetView` (health scores, per-node queue depth, SLO tiers — all
    already gossiped) and returns a verdict dict. It never spawns or
    kills anything: the workload driver (or an operator reading
    ``/cluster/health``) acts on it, typically by joining a warm node
    (``LifecyclePlane(bootstrap=True)``) or draining the named
    candidate (``POST /admin/drain``)."""

    def __init__(self, cfg: AutoscaleConfig | None = None):
        self.cfg = cfg or AutoscaleConfig()

    def recommend(self, fleet, alive_ring: int | None = None) -> dict:
        cfg = self.cfg
        health = fleet.health()
        serving = {
            r: d
            for r, d in fleet.digests().items()
            if d.role != "router"
            and d.lifecycle in ("active", "bootstrapping")
        }
        n = len(serving) if serving else int(alive_ring or 0)
        healthy = [
            r for r in serving
            if health.get(r, {}).get("score", 0.0) >= cfg.healthy_threshold
        ]
        waiting = sum(d.waiting for d in serving.values())
        occupancy = (
            sum(d.batch_occupancy for d in serving.values()) / n if n else 0.0
        )
        tier = max((d.slo_tier for d in serving.values()), default=0)
        waiting_per_healthy = waiting / max(1, len(healthy))
        signals = {
            "serving_nodes": n,
            "healthy_nodes": len(healthy),
            "waiting": waiting,
            "waiting_per_healthy_node": round(waiting_per_healthy, 3),
            "mean_batch_occupancy": round(occupancy, 3),
            "max_slo_tier": tier,
        }

        def verdict(action: str, reason: str, remove_candidate=None) -> dict:
            return {
                "action": action,
                "reason": reason,
                "remove_candidate": remove_candidate,
                "signals": signals,
            }

        if not serving:
            # No serving digests at all (gossip disabled, or none folded
            # yet): the policy has NO signal — recommending anything
            # would scale a healthy fleet on noise. Hold until telemetry
            # exists.
            return verdict("hold", "no_telemetry")
        if n < cfg.min_nodes:
            return verdict("add", "below_min_nodes")
        if n < cfg.max_nodes:
            if len(healthy) < max(cfg.min_nodes, (n + 1) // 2):
                return verdict("add", "unhealthy_majority")
            if tier >= cfg.scale_up_slo_tier:
                return verdict("add", "slo_degraded")
            if waiting_per_healthy > cfg.scale_up_waiting_per_node:
                return verdict("add", "queue_depth")
        if (
            n > cfg.min_nodes
            and tier == 0
            and len(healthy) == n
            and waiting_per_healthy < cfg.scale_down_waiting_per_node
            and occupancy < cfg.scale_down_occupancy
        ):
            # Drain the least-loaded healthy node (ties → highest rank,
            # so the rank space stays dense at the bottom).
            candidate = max(
                healthy,
                key=lambda r: (-serving[r].waiting, -serving[r].batch_occupancy, r),
            )
            return verdict("remove", "idle_capacity", remove_candidate=candidate)
        return verdict("hold", "steady")
