"""Epoch-numbered topology views for elastic ring membership.

The reference lists node-failure detection and dynamic add/remove as
roadmap (``README.md:49-50``) and marks the missing topology-check thread
with a TODO (``radix_mesh.py:143-146``). Here membership is first-class:

- A :class:`TopologyView` is ``(epoch, alive ranks)``. Every node holds
  one; all TTLs and GC unanimity counts derive from the *current* view's
  ring size, not the static config.
- **Detection is sender-side**: the ring is unidirectional, so the only
  node that can reliably observe a death is the dead node's predecessor —
  its transmit channel stops delivering. After ``failure_timeout_s`` of
  undeliverable sends, the predecessor declares the successor dead, adopts
  ``(epoch+1, alive − dead)``, reconnects to the next alive rank, and
  rings a TOPO oplog announcing the view.
- **Higher epoch wins** on receipt. Concurrent detections (two failures,
  two detectors, same epoch, different alive sets) merge by adopting the
  intersection at ``epoch+1`` — monotonically shrinking, so it converges.
- **Rejoin**: a restarted node rings JOIN; the surviving view-master (the
  lowest alive rank) answers with a fresh view that re-includes it.

Views travel as oplogs (see ``cache/oplog.py``), so routers learn them via
the master fan-out like everything else, and use them to retire/restore
hash-ring members (``router/cache_aware_router.py``).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from radixmesh_tpu.config import MeshConfig

__all__ = ["TopologyView", "encode_view", "decode_view", "membership_gauges"]


@dataclass(frozen=True)
class TopologyView:
    """Immutable membership view: which P/D global ranks are alive."""

    epoch: int
    alive: tuple[int, ...]  # sorted global ranks of live ring members

    @classmethod
    def initial(cls, cfg: MeshConfig) -> "TopologyView":
        return cls(epoch=0, alive=tuple(range(cfg.num_ring)))

    @property
    def ring_size(self) -> int:
        return len(self.alive)

    def contains(self, rank: int) -> bool:
        return rank in self.alive

    def successor_of(self, rank: int) -> int | None:
        """Next alive rank after ``rank`` in ring order (ascending global
        rank with wraparound — the reference's prefill-then-decode order,
        ``sync_algo.py:57-75``). None if no *other* member is alive."""
        others = [r for r in self.alive if r != rank]
        if not others:
            return None
        for r in others:
            if r > rank:
                return r
        return others[0]

    def master_rank(self) -> int | None:
        """View master: the lowest alive rank (generalizes the reference's
        rank-0 master, ``sync_algo.py:54-55``, to survive rank 0 dying)."""
        return self.alive[0] if self.alive else None

    def without(self, rank: int) -> "TopologyView":
        return TopologyView(
            epoch=self.epoch + 1,
            alive=tuple(r for r in self.alive if r != rank),
        )

    def including(self, rank: int) -> "TopologyView":
        return TopologyView(
            epoch=self.epoch + 1,
            alive=tuple(sorted(set(self.alive) | {rank})),
        )

    def merged_with(self, other: "TopologyView") -> "TopologyView":
        """Deterministic resolution of an equal-epoch conflict: adopt the
        intersection one epoch up (both detectors' removals take effect)."""
        return TopologyView(
            epoch=self.epoch + 1,
            alive=tuple(sorted(set(self.alive) & set(other.alive))),
        )


def encode_view(view: TopologyView) -> np.ndarray:
    """Pack a view into an oplog value array: ``[epoch, *alive]``."""
    return np.asarray([view.epoch, *view.alive], dtype=np.int32)


def decode_view(value: np.ndarray) -> TopologyView:
    a = np.asarray(value, dtype=np.int32)
    if a.size < 1:
        raise ValueError("empty TOPO payload")
    return TopologyView(epoch=int(a[0]), alive=tuple(int(r) for r in a[1:]))


def membership_gauges(
    view: TopologyView,
    rank: int,
    *,
    alive: tuple[int, ...] | None = None,
    hier=None,
    succ_rank: int | None = None,
) -> dict[str, float]:
    """Gauge values for this node's membership state — failover and hier
    re-election were previously visible only in logs; ``MeshCache``
    exports these on ``/metrics`` (suffix-matched to the metric names it
    registers). ``hier`` is the node's :class:`~radixmesh_tpu.policy.
    hierarchy.HierPlan` (None = flat ring, where "leader" means the view
    master); ``alive`` defaults to the view's alive set."""
    a = view.alive if alive is None else alive
    if hier is not None:
        leader = bool(hier.is_leader(rank, a))
        spine = len(hier.nonempty_groups(a))
    else:
        leader = view.master_rank() == rank
        spine = 0
    return {
        "view_epoch": float(view.epoch),
        "alive_nodes": float(len(view.alive)),
        "leader_flag": 1.0 if leader else 0.0,
        "spine_nodes": float(spine),
        "successor_rank": float(-1 if succ_rank is None else succ_rank),
    }
