"""Retry, hedging, and deadline-budget policy for crash-tolerant serving.

Pure policy (no I/O, no threads): the serving edge's request-recovery
plane (``server/recovery.py``) composes these pieces into the actual
failover machinery; the router, the SLO shed paths, and the chaos
workload all read the same knobs so backoff behavior cannot drift
between layers.

Three pieces:

- :class:`RetryPolicy` — per-hop timeouts, capped exponential backoff
  with bounded jitter, a retry cap, and the optional tail-latency
  hedging threshold (duplicate a straggling hop to a second node,
  first-writer-wins).
- :class:`DeadlineBudget` — a request's end-to-end deadline, stamped at
  admission and THREADED through every subsequent hop: no hop (route,
  prefill, decode wait, retry backoff, hedge wait) may wait longer than
  the remaining budget, so a crash-recovery sequence can overshoot the
  admission deadline by at most one already-started backoff — never by
  an unbounded retry tail.
- :class:`RecoveryRecord` — everything needed to resurrect a request on
  a surviving node: the prompt ids, every token delivered so far (the
  byte-exact SSE prefix the client already holds), and the sampling
  params + seed (so a seeded replay redraws the same continuation).
  ``resume_key()`` is ``prompt + delivered`` — exactly the prefix the
  replicated radix tree makes a near-pure cache hit on re-prefill.

:func:`jittered_retry_after` is the shared Retry-After spreader: every
``Retry-After`` the stack emits (SLO sheds, drain 503s, recovery retry
hints) passes through it so synchronized clients cannot form a retry
storm against a recovering fleet.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "RetryPolicy",
    "DeadlineBudget",
    "RecoveryRecord",
    "jittered_retry_after",
]


def jittered_retry_after(
    base_s: float,
    rng: np.random.Generator | None = None,
    frac: float = 0.25,
) -> float:
    """``base_s`` spread uniformly over ``[base*(1-frac), base*(1+frac)]``.

    Bounded (never more than ``frac`` away from the advertised base, so
    SLO math stays honest) and strictly positive. A shared default RNG
    is deliberately NOT seeded: in production the whole point is that
    two clients shed in the same instant come back at different ones;
    tests that need determinism pass their own generator."""
    if base_s <= 0:
        return base_s
    if rng is None:
        # The shared default generator is hit from concurrent HTTP
        # handler threads (every shed response) and numpy Generators are
        # not thread-safe — an unguarded race can hand two "jittered"
        # sheds the identical draw, exactly the synchronization this
        # function exists to break.
        with _default_rng_lock:
            u = _default_rng.random()
    else:
        u = rng.random()
    return float(base_s * (1.0 + frac * (2.0 * u - 1.0)))


_default_rng = np.random.default_rng()
_default_rng_lock = threading.Lock()


@dataclass(frozen=True)
class RetryPolicy:
    """Router-side retry/hedging knobs for one class of traffic.

    ``hop_timeout_s`` is the failure-detection trigger the edge owns: a
    hop that produces no progress for this long is declared dead —
    independent of (and usually far faster than) the mesh's
    ``failure_timeout_s`` ring detection, whose ``cause=dead`` view
    transition is the other resurrection trigger."""

    hop_timeout_s: float = 2.0
    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 2.0
    jitter_frac: float = 0.25
    # Tail-latency hedging: a hop still unfinished after this long gets
    # duplicated to a second node, first-writer-wins. None = off.
    hedge_after_s: float | None = None

    def __post_init__(self):
        if self.hop_timeout_s <= 0:
            raise ValueError("hop_timeout_s must be > 0")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.backoff_factor < 1.0:
            raise ValueError("backoff_factor must be >= 1")
        if not 0.0 <= self.jitter_frac <= 1.0:
            raise ValueError("jitter_frac must be in [0, 1]")

    def backoff_s(
        self, attempt: int, rng: np.random.Generator | None = None
    ) -> float:
        """Backoff before retry ``attempt`` (1-based): capped exponential
        with bounded jitter — the jitter keeps a fleet of edges that all
        saw the same node die from re-converging on the survivor in one
        synchronized wave."""
        base = min(
            self.backoff_max_s,
            self.backoff_base_s * self.backoff_factor ** max(0, attempt - 1),
        )
        return jittered_retry_after(base, rng, self.jitter_frac)


class DeadlineBudget:
    """End-to-end deadline budget, stamped once at admission.

    Threaded (by reference) from admission through routing, prefill,
    decode, disagg handoff, and every recovery hop: callers clamp each
    wait with :meth:`clamp` so no single hop can spend time the request
    no longer has. ``total_s=None`` means no deadline (every clamp
    passes through, ``expired()`` is always False)."""

    def __init__(
        self,
        total_s: float | None,
        clock=time.monotonic,
        start: float | None = None,
    ):
        self._clock = clock
        self.total_s = total_s
        self.admitted_at = clock() if start is None else start

    def elapsed(self) -> float:
        return self._clock() - self.admitted_at

    def remaining(self) -> float:
        if self.total_s is None:
            return float("inf")
        return self.total_s - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def clamp(self, want_s: float) -> float:
        """``want_s`` bounded by the remaining budget (never negative).
        THE hop rule: every wait in the recovery path goes through
        here."""
        return max(0.0, min(want_s, self.remaining()))

    def overrun_s(self) -> float:
        """Seconds past the admission deadline (0 while inside it)."""
        if self.total_s is None:
            return 0.0
        return max(0.0, -self.remaining())


@dataclass
class RecoveryRecord:
    """Everything the serving edge needs to resurrect one request.

    Kept at the edge from admission until the final token: the prompt,
    the tokens already delivered to the client (appended as they
    stream — this list IS the byte-exact prefix a resumed stream must
    never re-emit or contradict), the sampling params + seed, and the
    deadline budget. ``addr`` tracks the node currently serving the
    request so failure detection can find every request pinned to a
    dead node."""

    rid: int
    prompt: np.ndarray  # int32 token ids
    sampling: object = None  # SamplingParams (opaque here: policy layer)
    seed: int | None = None
    budget: DeadlineBudget = field(
        default_factory=lambda: DeadlineBudget(None)
    )
    delivered: list[int] = field(default_factory=list)
    addr: str | None = None  # node currently serving this request
    # Cross-node trace stitching (PR 9, obs/trace_plane.py): the 64-bit
    # trace id every hop of this request — including resume/hedge
    # re-routes — carries, so the whole multi-node journey lands under
    # ONE id in the stitched Perfetto view. 0 = tracing off.
    trace_id: int = 0
    # -- recovery telemetry (the chaos gates read these) --
    retries: int = 0
    resurrections: int = 0
    hedges: int = 0
    max_backoff_s: float = 0.0
    failed: bool = False
    done: bool = False

    def deliver(self, token: int) -> None:
        self.delivered.append(int(token))

    def resume_key(self) -> np.ndarray:
        """``prompt + delivered`` — the resurrection routing/replay key.
        Surviving replicas hold (a prefix of) exactly this sequence, so
        re-prefill is a near-pure cache hit."""
        if not self.delivered:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.delivered, dtype=np.int32)]
        )

    def overrun_within_one_backoff(self) -> bool:
        """The budget gate the chaos artifact pins: a recovered request
        may overshoot its admission deadline by AT MOST one retry
        backoff (the one that was already sleeping when the budget ran
        out) — never by an unbounded retry tail."""
        return self.budget.overrun_s() <= self.max_backoff_s + 1e-9
