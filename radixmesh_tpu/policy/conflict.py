"""Master-free multi-writer conflict resolution.

Reference ``policy/conflict_resolve.py:1-6``: when two nodes insert
different KV values for the same token prefix, every node deterministically
keeps the value whose *origin rank* is lowest — no coordination required,
and all replicas converge because the rule is a total order independent of
arrival order.
"""

from __future__ import annotations


class NodeRankConflictResolver:
    """Keep the existing value iff its origin rank is <= the new value's."""

    @staticmethod
    def keep(existing_rank: int, new_rank: int) -> bool:
        return existing_rank <= new_rank
