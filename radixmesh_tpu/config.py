"""Cluster + cache configuration.

Capability parity with the reference's ``config/cache_config.py``:
``ServerArgs`` holds the prefill/decode/router address lists plus this node's
address, derives the node's single role and global/local rank from its
position in those lists (``cache_config.py:20-35,50-75``), enforces exactly
one membership and at most one router (``cache_config.py:47-48``), and every
node in a cluster must share an identical config except ``local_addr``
(reference ``README.md:122-124``).

Extensions for the TPU stack (absent in the reference, which has no model
runtime): a ``model`` section and a ``mesh`` section describing the
``jax.sharding.Mesh`` axes each node uses for its local model replica.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any

import yaml

DEFAULT_MAX_MSG_BYTES = 16 * 1024 * 1024  # mirror of reference cache_config.py:12


class NodeRole(enum.Enum):
    """Node roles (reference ``radix/core_enum.py:4-7`` RadixMode)."""

    PREFILL = "prefill"
    DECODE = "decode"
    ROUTER = "router"


@dataclass
class MeshConfig:
    """Topology + cache sizing for one node of the cache mesh.

    Global rank space mirrors the reference (``cache_config.py:20-28``):
    prefill nodes occupy ranks ``[0, P)``, decode ``[P, P+D)``, routers
    ``[P+D, ...)``.
    """

    prefill_nodes: list[str] = field(default_factory=list)
    decode_nodes: list[str] = field(default_factory=list)
    router_nodes: list[str] = field(default_factory=list)
    local_addr: str = ""
    # Max serialized oplog size; also the transport buffer size
    # (reference cache_config.py:12-14 couples these the same way).
    max_msg_bytes: int = DEFAULT_MAX_MSG_BYTES
    protocol: str = "tcp"  # "tcp" (C++ native) | "tcp-py" | "inproc"
    page_size: int = 1
    # Replication topology: "ring" (the reference's flat ring) or "hier"
    # (two-level groups + leader spine, policy/hierarchy.py — the
    # reference's open roadmap item for >50-node meshes, README.md:57).
    topology: str = "ring"
    # Group size for topology="hier"; 0 = auto (~sqrt(ring size)).
    group_size: int = 0
    # Prefix-ownership sharding (cache/sharding.py): bounded replication
    # factor. 0 = full replication (every insert circulates the whole
    # ring — the documented compatibility mode, bit-for-bit the old
    # wire); N >= 1 = each subtree shard is owned by min(N, ring size)
    # consistent-hash successors and inserts are delivered point-to-
    # point to the owner set only (bytes-per-insert O(RF), not O(N)).
    replication_factor: int = 0
    # Shard-summary gossip cadence under sharding (the router's routing
    # table + co-owner convergence feed). 0 = the tick interval.
    shard_summary_interval_s: float = 0.0
    # Cache sizing: number of KV slots (tokens) the paged pool holds.
    num_kv_slots: int = 65536
    # Replica-size bound (tokens) for the mesh tree. Serving inserts every
    # sequence ever published into every replica (router included); without
    # a bound a long-running deployment leaks linearly in tokens served.
    # Exceeding it triggers a LOCAL LRU trim (not replicated — a trimmed
    # replica just re-misses; the reference's mesh evict is a no-op TODO,
    # radix_mesh.py:349-351). 0 disables.
    mesh_max_tokens: int = 1 << 20
    # Mesh GC / heartbeat cadence (seconds). Reference hardcodes 10s
    # (radix_mesh.py:133,166); configurable here so tests run fast.
    gc_interval_s: float = 10.0
    tick_interval_s: float = 10.0
    # How long a ring successor may be unreachable before its predecessor
    # declares it dead and re-forms the ring (policy/topology.py). The
    # reference has no failure detection at all (roadmap, README.md:49-50).
    failure_timeout_s: float = 10.0
    # Patience for a successor that has NEVER been seen connected (cluster
    # boot: peers may still be binding; a restart may also target an
    # already-dead successor, which must eventually be ringed around).
    # None → max(30s, 3 × failure_timeout_s).
    startup_grace_s: float | None = None
    # Fleet telemetry plane (obs/fleet_plane.py): how often each ring
    # node gossips its NodeDigest (tree fingerprint, fill, health
    # signals) as one oplog frame. 0 disables digest origination;
    # receive-side folding is always on. launch.py
    # --fleet-digest-interval overrides.
    digest_interval_s: float = 0.0
    # Replica-entry TTL (seconds): mesh-tree entries untouched this long
    # are swept by the housekeeper (cause "ttl" on the eviction
    # counters). 0 disables — cache semantics tolerate either choice;
    # TTL bounds staleness rather than size (mesh_max_tokens does that).
    mesh_ttl_s: float = 0.0
    # Anti-entropy repair plane (cache/repair_plane.py): scan cadence
    # for comparing this node's tree fingerprint against the fleet's
    # gossiped digests and opening bounded repair sessions with stale-
    # diverged peers. 0 disables the plane (divergence is then only
    # DETECTED, the PR 3 behavior). Requires digest gossip
    # (digest_interval_s / --fleet-digest-interval) to see peers.
    repair_interval_s: float = 0.0
    # How long a pairwise divergence must persist before a probe fires
    # (transients heal via live replication; probing them is waste).
    repair_age_threshold_s: float = 10.0
    # Per-session storm-control bounds: entries re-replicated per
    # summary, and the exponential-backoff base between rounds against
    # one peer (doubles per round, capped at 30x the base).
    repair_key_budget: int = 256
    repair_backoff_s: float = 2.0
    # Chaos/fault-injection plane (comm/faults.py): a FaultPlan spec
    # (``FaultPlan.from_dict`` schema) installed at the transport seam
    # before this node opens any channel. Empty = no faults — the only
    # sane production value; populated ONLY by tests, soaks, and
    # chaos drills. launch.py --chaos-plan FILE overrides.
    chaos: dict[str, Any] = field(default_factory=dict)
    # Async KV-movement plane (cache/kv_transfer.py): serving nodes
    # stage host-tier restores / eviction write-backs / disagg handoff
    # placement off the scheduling thread. Off = the synchronous seed
    # behavior. launch.py --kv-transfer-async overrides.
    kv_transfer_async: bool = False
    # Restore staging granularity (tokens per chunk): smaller chunks
    # interleave with decode more finely at more dispatch overhead.
    kv_transfer_chunk_tokens: int = 512
    # Restores shorter than this take the synchronous in-admission path
    # (parking a tiny restore costs more than it hides). 0 = always
    # staged when the plane is on.
    kv_transfer_min_restore_tokens: int = 0
    # Durable KV spill tier (cache/kv_tier.py): directory for
    # checksummed fsynced extent files — the third tier below HBM and
    # host RAM. Setting it arms the async KV plane (disk I/O is
    # staged-only) and enables cold-cell resurrection at boot. None =
    # the tier stack ends at host RAM (the pre-PR-15 behavior).
    # launch.py --kv-tier-dir overrides.
    kv_tier_dir: str | None = None
    # Disk budget for the extent store; oldest extents are dropped past
    # it (cache semantics: a dangling ref degrades to a recompute).
    kv_tier_capacity_bytes: int = 1 << 30
    # Mid-decode publish cadence (crash recovery, server/recovery.py):
    # every N generated tokens a request's grown prefix publishes to the
    # tree AND the ring, so a node death costs a resurrected request at
    # most N tokens of cache hit. 0 = publish only at finish/preempt.
    stream_publish_tokens: int = 0
    # Heat-driven shard rebalancing (cache/rebalance.py): decision
    # cadence for the view master's RebalancePlane — per-shard ownership
    # overrides (elastic RF boost/shrink under a hysteresis band,
    # bounded moves per round) gossiped like the view. 0 disables the
    # decider; folding received REBALANCE frames is always on. Requires
    # replication_factor > 0. launch.py --rebalance-interval overrides.
    rebalance_interval_s: float = 0.0
    # Per-shard heat decay half-life (cache/sharding.py::ShardHeat).
    # 0 = the library default (30 s). Short half-lives make the skew
    # signal track traffic shifts faster — drills and rebalance benches
    # use seconds; production keeps the default.
    heat_half_life_s: float = 0.0
    # Fleet telemetry aggregation (obs/aggregator.py): router nodes
    # cursor-pull every ring node's /debug/timeseries at this cadence
    # into one node-labeled fleet store — GET /cluster/timeseries, true
    # cross-node percentiles on GET /cluster/slo, and the fleet doctor
    # rules (straggler_node / fleet_burn_slope / telemetry_gap) ride on
    # it. 0 disables the collector; serving nodes ignore the key.
    # launch.py --agg-interval overrides.
    agg_interval_s: float = 0.0

    @property
    def effective_startup_grace_s(self) -> float:
        if self.startup_grace_s is not None:
            return self.startup_grace_s
        return max(30.0, 3.0 * self.failure_timeout_s)
    # Optional model/mesh sections for serving nodes.
    model: dict[str, Any] = field(default_factory=dict)
    mesh_axes: dict[str, int] = field(default_factory=dict)  # e.g. {"dp":2,"tp":4}
    # Serving HTTP port of a P/D node = its cache port + this offset.
    # Derived (not listed per-node) so the reference's identical-config
    # invariant (README.md:122-124) holds for the serving tier too.
    serve_port_offset: int = 1000

    def serve_addr(self, cache_addr: str | None) -> str | None:
        """Map a node's cache-mesh address to its serving-HTTP address.
        ``None`` for portless addresses (inproc test hubs have no HTTP)."""
        if cache_addr is None:
            return None
        try:
            host, port = parse_addr(cache_addr)
        except ValueError:
            return None
        return f"{host}:{port + self.serve_port_offset}"

    # ---- derived rank space (reference cache_config.py:20-35) ----

    @property
    def num_prefill(self) -> int:
        return len(self.prefill_nodes)

    @property
    def num_decode(self) -> int:
        return len(self.decode_nodes)

    @property
    def num_ring(self) -> int:
        """Ring members = prefill + decode nodes (routers stay outside,
        reference ``sync_algo.py:57-75``)."""
        return self.num_prefill + self.num_decode

    @property
    def num_total(self) -> int:
        """The whole global rank space: ring members plus EVERY router.
        The one definition every rank-bound check derives from, so the
        multi-router front door cannot drift out of the ring accounting
        (two call sites computing ``num_ring + len(router_nodes)`` by
        hand is how an off-by-one ships)."""
        return self.num_ring + len(self.router_nodes)

    def is_prefill_rank(self, rank: int) -> bool:
        return 0 <= rank < self.num_prefill

    def is_decode_rank(self, rank: int) -> bool:
        return self.num_prefill <= rank < self.num_ring

    def is_router_rank(self, rank: int) -> bool:
        return rank >= self.num_ring

    def role_of_rank(self, rank: int) -> NodeRole:
        if self.is_prefill_rank(rank):
            return NodeRole.PREFILL
        if self.is_decode_rank(rank):
            return NodeRole.DECODE
        return NodeRole.ROUTER

    def addr_of_rank(self, rank: int) -> str:
        all_nodes = self.prefill_nodes + self.decode_nodes + self.router_nodes
        return all_nodes[rank]

    def prefill_addr(self, prefill_rank: int) -> str:
        """Address of prefill node by global rank (reference
        ``radix_mesh.py:447-451``)."""
        return self.prefill_nodes[prefill_rank]

    def decode_addr(self, decode_rank: int) -> str:
        """Address of decode node by global rank (reference
        ``radix_mesh.py:453-457``)."""
        return self.decode_nodes[decode_rank - self.num_prefill]

    # ---- this node's identity ----

    def local_identity(self) -> tuple[NodeRole, int, int]:
        """Return (role, global_rank, local_rank) for ``local_addr``.

        Enforces exactly-one-membership like the reference
        (``cache_config.py:50-75``).
        """
        memberships = []
        for role, nodes, base in (
            (NodeRole.PREFILL, self.prefill_nodes, 0),
            (NodeRole.DECODE, self.decode_nodes, self.num_prefill),
            (NodeRole.ROUTER, self.router_nodes, self.num_ring),
        ):
            for i, addr in enumerate(nodes):
                if addr == self.local_addr:
                    memberships.append((role, base + i, i))
        if len(memberships) != 1:
            raise ValueError(
                f"local_addr {self.local_addr!r} must appear in exactly one "
                f"node list, found {len(memberships)} memberships"
            )
        return memberships[0]

    @property
    def local_role(self) -> NodeRole:
        return self.local_identity()[0]

    @property
    def global_rank(self) -> int:
        """This node's rank in the global rank space (distinct from the
        within-role local rank, ``local_identity()[2]``)."""
        return self.local_identity()[1]

    def validate(self) -> None:
        # Multi-router front door: N routers are first-class (the
        # reference's single-router restriction, cache_config.py:47-48,
        # is gone — every router rides the master fan-out and the
        # global rank space already accounts for the whole list). What
        # remains is REAL validation: distinct addresses (the global
        # rank space is positional — a duplicate would alias two ranks)
        # and non-empty entries.
        if len(set(self.router_nodes)) != len(self.router_nodes):
            raise ValueError("router_nodes must be distinct addresses")
        if any(not a for a in self.router_nodes):
            raise ValueError("router_nodes entries must be non-empty")
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        if self.topology not in ("ring", "hier"):
            raise ValueError(
                f"unknown topology {self.topology!r}; known: ring, hier"
            )
        if self.group_size < 0:
            raise ValueError("group_size must be >= 0 (0 = auto)")
        if self.topology == "ring" and self.group_size:
            raise ValueError("group_size is only meaningful with topology: hier")
        if self.topology == "hier" and self.group_size == 1:
            # HierPlan requires >= 2; reject at config load like the other
            # topology constraints, not later at MeshCache construction.
            raise ValueError("group_size must be >= 2 (or 0 = auto) for hier")
        all_nodes = self.prefill_nodes + self.decode_nodes + self.router_nodes
        if len(set(all_nodes)) != len(all_nodes):
            raise ValueError("node addresses must be unique across roles")
        if self.replication_factor < 0:
            raise ValueError("replication_factor must be >= 0 (0 = full replica)")
        if self.shard_summary_interval_s < 0:
            raise ValueError("shard_summary_interval_s must be >= 0")
        if self.replication_factor > 0 and self.topology == "hier":
            # The hierarchy exists to shorten the full-replica lap; the
            # owner-addressed plane replaces the lap entirely. Composing
            # them would mean two delivery topologies for one insert.
            raise ValueError(
                "replication_factor > 0 requires topology: ring "
                "(sharded delivery replaces the hier lap)"
            )
        if self.repair_interval_s < 0 or self.repair_age_threshold_s < 0:
            raise ValueError("repair timers must be >= 0")
        if self.repair_key_budget < 1:
            raise ValueError("repair_key_budget must be >= 1")
        if self.repair_backoff_s <= 0:
            # A non-positive backoff disables the exponential round
            # pacing entirely — the probe storm the plane's storm-control
            # invariants exist to prevent.
            raise ValueError("repair_backoff_s must be > 0")
        if self.rebalance_interval_s < 0 or self.heat_half_life_s < 0:
            raise ValueError("rebalance/heat timers must be >= 0")
        if self.agg_interval_s < 0:
            raise ValueError("agg_interval_s must be >= 0")
        if self.rebalance_interval_s > 0 and self.replication_factor == 0:
            # The rebalancer moves OWNERSHIP; a full replica has none.
            raise ValueError(
                "rebalance_interval_s > 0 requires replication_factor > 0 "
                "(ownership overrides are meaningless on a full replica)"
            )
        if self.model:
            # Serving deployments derive each P/D node's HTTP port as
            # cache port + offset: both must be bindable and disjoint
            # from every cache port (same-host topologies collide).
            cache_ports = {}
            for addr in self.prefill_nodes + self.decode_nodes:
                try:
                    host, port = parse_addr(addr)
                except ValueError:
                    continue  # portless inproc address: no HTTP tier
                cache_ports.setdefault(host, set()).add(port)
            for host, ports in cache_ports.items():
                for port in ports:
                    serve = port + self.serve_port_offset
                    if not (0 < serve <= 65535):
                        raise ValueError(
                            f"serve port {serve} for {host}:{port} out of range; "
                            "adjust serve_port_offset"
                        )
                    if serve in ports:
                        raise ValueError(
                            f"serve port {serve} for {host}:{port} collides "
                            "with another node's cache port on the same host"
                        )
        self.local_identity()  # raises on bad membership


def load_config(
    path: str,
    router_nodes: list[str] | None = None,
    replication_factor: int | None = None,
    rebalance_interval_s: float | None = None,
) -> MeshConfig:
    """Load a YAML config file into a validated :class:`MeshConfig`
    (reference ``load_server_args``, ``cache_config.py:38-76``).

    The keyword arguments are the CLI overrides (``--router-nodes`` /
    ``--replication-factor`` / ``--rebalance-interval``), replacing the
    file's values BEFORE validation — a router added by flag must be
    able to find its own membership, and the rebalance/replication
    cross-field check must judge the values the node will actually run
    with; post-validation patching can give neither."""
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    if router_nodes is not None:
        raw["router_nodes"] = list(router_nodes)
    if replication_factor is not None:
        raw["replication_factor"] = int(replication_factor)
    if rebalance_interval_s is not None:
        raw["rebalance_interval_s"] = float(rebalance_interval_s)
    known = {
        "prefill_nodes",
        "decode_nodes",
        "router_nodes",
        "local_addr",
        "max_msg_bytes",
        "protocol",
        "page_size",
        "topology",
        "group_size",
        "replication_factor",
        "shard_summary_interval_s",
        "num_kv_slots",
        "mesh_max_tokens",
        "gc_interval_s",
        "tick_interval_s",
        "failure_timeout_s",
        "startup_grace_s",
        "repair_interval_s",
        "repair_age_threshold_s",
        "repair_key_budget",
        "repair_backoff_s",
        "chaos",
        "kv_transfer_async",
        "kv_transfer_chunk_tokens",
        "kv_transfer_min_restore_tokens",
        "kv_tier_dir",
        "kv_tier_capacity_bytes",
        "stream_publish_tokens",
        "rebalance_interval_s",
        "heat_half_life_s",
        "agg_interval_s",
        "model",
        "mesh_axes",
        "serve_port_offset",
    }
    unknown = set(raw) - known
    if unknown:
        # Every node must share an identical config (reference
        # README.md:122-124); a typo'd key must fail fast, not silently
        # default one node into a different rank space.
        raise ValueError(f"unknown config keys: {sorted(unknown)}")
    cfg = MeshConfig(
        prefill_nodes=list(raw.get("prefill_nodes", [])),
        decode_nodes=list(raw.get("decode_nodes", [])),
        router_nodes=list(raw.get("router_nodes", [])),
        local_addr=raw.get("local_addr", ""),
        max_msg_bytes=int(raw.get("max_msg_bytes", DEFAULT_MAX_MSG_BYTES)),
        protocol=raw.get("protocol", "tcp"),
        page_size=int(raw.get("page_size", 1)),
        topology=raw.get("topology", "ring"),
        group_size=int(raw.get("group_size", 0)),
        replication_factor=int(raw.get("replication_factor", 0)),
        shard_summary_interval_s=float(
            raw.get("shard_summary_interval_s", 0.0)
        ),
        num_kv_slots=int(raw.get("num_kv_slots", 65536)),
        mesh_max_tokens=int(raw.get("mesh_max_tokens", 1 << 20)),
        gc_interval_s=float(raw.get("gc_interval_s", 10.0)),
        tick_interval_s=float(raw.get("tick_interval_s", 10.0)),
        failure_timeout_s=float(raw.get("failure_timeout_s", 10.0)),
        startup_grace_s=(
            None
            if raw.get("startup_grace_s") is None
            else float(raw["startup_grace_s"])
        ),
        repair_interval_s=float(raw.get("repair_interval_s", 0.0)),
        repair_age_threshold_s=float(raw.get("repair_age_threshold_s", 10.0)),
        repair_key_budget=int(raw.get("repair_key_budget", 256)),
        repair_backoff_s=float(raw.get("repair_backoff_s", 2.0)),
        chaos=dict(raw.get("chaos", {}) or {}),
        kv_transfer_async=bool(raw.get("kv_transfer_async", False)),
        kv_transfer_chunk_tokens=int(raw.get("kv_transfer_chunk_tokens", 512)),
        kv_transfer_min_restore_tokens=int(
            raw.get("kv_transfer_min_restore_tokens", 0)
        ),
        kv_tier_dir=raw.get("kv_tier_dir"),
        kv_tier_capacity_bytes=int(
            raw.get("kv_tier_capacity_bytes", 1 << 30)
        ),
        stream_publish_tokens=int(raw.get("stream_publish_tokens", 0)),
        rebalance_interval_s=float(raw.get("rebalance_interval_s", 0.0)),
        heat_half_life_s=float(raw.get("heat_half_life_s", 0.0)),
        agg_interval_s=float(raw.get("agg_interval_s", 0.0)),
        model=dict(raw.get("model", {})),
        mesh_axes=dict(raw.get("mesh_axes", {})),
        serve_port_offset=int(raw.get("serve_port_offset", 1000)),
    )
    cfg.validate()
    return cfg


def parse_addr(addr: str) -> tuple[str, int]:
    """``"host:port"`` → ``(host, port)`` (reference ``communicator.py:133-135``)."""
    host, _, port = addr.rpartition(":")
    return host, int(port)
