"""Pipeline-parallel SERVING: prefill chunks and decode steps over a
``(pp, tp)`` mesh.

VERDICT round-2 weak #4: ``parallel/pipeline.py`` proved GPipe numerics
but nothing in the serving stack could use a ``pp`` axis — the
Qwen2-72B/v5p gate (``BASELINE.md`` last row) realistically needs pp×tp.
This module is that path, shaped so the Engine's scheduler, radix tree,
page tables, and publish logic run UNCHANGED:

- The param pytree keeps its stacked ``[L, ...]`` layer leaves and the KV
  pool keeps its ``[2, L, Hkv, slots, D]`` layout — pp is purely a
  *sharding* of the existing layer axis (``shard_map`` hands each stage
  its contiguous ``L/pp`` block), tp a sharding of the head/ffn axes.
  No reshapes, no second checkpoint format.
- One function serves both phases: a decode step is a prefill chunk with
  ``C = 1`` (same page-table attention, same pool scatter), so the pp
  schedule exists in exactly one place.

Schedule: GPipe microbatches over the BATCH axis (rows are independent in
serving, so microbatching is free): ``n_micro`` row-groups enter stage 0
one tick apart, activations ``ppermute`` stage-to-stage, and each stage's
chunk-KV is collected per tick and scattered into the pool shard AFTER
the tick scan — keeping the pool out of the scan carry (the same
materialization bug ``prefill_chunk_paged`` documents). Weights never
move; activations ``[mb, C, H]`` are the only inter-stage traffic — the
layout that makes pp the memory-fit axis for models tp alone can't hold.

Tensor parallelism inside each stage is manual Megatron inside the same
``shard_map``: column-parallel wq/wk/wv/w_gate/w_up, row-parallel
wo/w_down, exactly two ``psum``s per block over the ``tp`` axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from radixmesh_tpu.models.llama import ModelConfig, _logits, _PREC
from radixmesh_tpu.ops.attention import attend_chunk_hybrid
from radixmesh_tpu.ops.norm import rms_norm
from radixmesh_tpu.ops.rope import apply_rope, rope_frequencies

__all__ = [
    "make_pp_serving_mesh",
    "pp_layer_specs",
    "pp_pool_spec",
    "shard_params_pp",
    "pp_forward_chunk",
]


def make_pp_serving_mesh(pp: int, tp: int = 1, devices=None) -> Mesh:
    """A ``(pp, tp)`` mesh over the first ``pp*tp`` devices (tp innermost:
    its two psums per block are the bandwidth-hungry traffic and belong on
    the fastest ICI wraparound)."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    if pp * tp > len(devices):
        raise ValueError(f"pp={pp} x tp={tp} exceeds {len(devices)} devices")
    arr = np.asarray(devices[: pp * tp]).reshape(pp, tp)
    return Mesh(arr, axis_names=("pp", "tp"))


def pp_layer_specs() -> dict:
    """PartitionSpec per stacked-layer leaf: layer axis over ``pp``, head
    and ffn axes over ``tp`` (Megatron column/row split)."""
    return {
        "attn_norm": P("pp", None),
        "mlp_norm": P("pp", None),
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
        "w_gate": P("pp", None, "tp"),
        "w_up": P("pp", None, "tp"),
        "w_down": P("pp", "tp", None),
        "bq": P("pp", "tp"),
        "bk": P("pp", "tp"),
        "bv": P("pp", "tp"),
    }


def pp_pool_spec() -> P:
    """KV pool ``[2, L, Hkv, slots, D]``: layers over pp, kv heads over tp
    — each stage holds only its own layers' KV, each tp chip its heads."""
    return P(None, "pp", "tp", None, None)


def shard_params_pp(params: dict, cfg: ModelConfig, mesh: Mesh) -> dict:
    """Place the UNCHANGED param pytree onto a ``(pp, tp)`` mesh."""
    specs = pp_layer_specs()
    out = dict(params)
    out["layers"] = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params["layers"].items()
    }
    repl = NamedSharding(mesh, P())
    out["embed"] = jax.device_put(params["embed"], repl)
    out["final_norm"] = jax.device_put(params["final_norm"], repl)
    if "lm_head" in params:
        out["lm_head"] = jax.device_put(
            params["lm_head"], NamedSharding(mesh, P(None, "tp"))
        )
    return out


@partial(
    jax.jit,
    static_argnames=("cfg", "page_size", "kv_block_pages", "mesh", "n_micro"),
    donate_argnames=("kv_pool",),
)
def pp_forward_chunk(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, C] chunk tokens (C=1 for a decode step)
    positions: jnp.ndarray,  # [B, C] absolute positions
    kv_pool: jnp.ndarray,  # [2, L, Hkv, slots, D] sharded pp_pool_spec()
    slots: jnp.ndarray,  # [B, C] pool slot per token (pad → scratch)
    page_table: jnp.ndarray,  # [B, max_pages]
    kv_lengths: jnp.ndarray,  # [B] valid context incl. this chunk
    *,
    page_size: int = 16,
    kv_block_pages: int = 32,
    mesh: Mesh,
    n_micro: int = 1,
):
    """Logits + updated pool for one chunk through the layer pipeline.

    ``B`` must divide into ``n_micro`` microbatches. Returns
    ``(logits [B, C, V], kv_pool)`` with logits replicated.
    """
    pp = mesh.shape["pp"]
    tp = mesh.shape.get("tp", 1)
    L = cfg.n_layers
    if L % pp:
        raise ValueError(f"n_layers={L} not divisible by pp={pp}")
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError("head counts must divide tp")
    B, C = tokens.shape
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
    mb = B // n_micro
    n_ticks = n_micro + pp - 1
    hq_loc = cfg.n_heads // tp
    hkv_loc = cfg.n_kv_heads // tp
    D = cfg.head_dim
    num_slots = kv_pool.shape[3]
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)

    # Embed outside the shard_map (table replicated); group rows into
    # microbatches. Aux arrays get the same [n_micro, mb, ...] grouping.
    x_all = params["embed"][tokens].reshape(n_micro, mb, C, cfg.hidden)
    pos_all = positions.reshape(n_micro, mb, C)
    slots_all = slots.reshape(n_micro, mb, C)
    pt_all = page_table.reshape(n_micro, mb, -1)
    kvlen_all = kv_lengths.reshape(n_micro, mb)

    layer_specs = {
        k: v for k, v in pp_layer_specs().items() if k in params["layers"]
    }

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(layer_specs, pp_pool_spec(), P(), P(), P(), P(), P()),
        out_specs=(P(), pp_pool_spec()),
        check_vma=False,
    )
    def run(layers, pool, x_all, pos_all, slots_all, pt_all, kvlen_all):
        # Per-device views: layers leaves [L/pp, ...] head-sliced; pool
        # [2, L/pp, Hkv/tp, slots, D].
        idx = jax.lax.axis_index("pp")
        l_loc = pool.shape[1]
        pages = pool.reshape(
            2, l_loc, hkv_loc, num_slots // page_size, page_size, D
        )

        def stage(h, pos, pt, kvlen):
            """This stage's L/pp layers over one microbatch's chunk.
            Returns (h, (k_stack, v_stack)) with the chunk K/V of every
            local layer — scattered into the pool AFTER the tick scan."""
            prior = jnp.minimum(pos[:, 0], kvlen)

            def body(h, xs):
                l_idx, lp = xs
                hn = rms_norm(h, lp["attn_norm"], cfg.rms_eps)
                q = jnp.einsum("bsh,hd->bsd", hn, lp["wq"], precision=_PREC)
                k = jnp.einsum("bsh,hd->bsd", hn, lp["wk"], precision=_PREC)
                v = jnp.einsum("bsh,hd->bsd", hn, lp["wv"], precision=_PREC)
                if cfg.qkv_bias:
                    q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
                q = q.reshape(mb, C, hq_loc, D)
                k = k.reshape(mb, C, hkv_loc, D)
                v = v.reshape(mb, C, hkv_loc, D)
                q = apply_rope(q, pos, inv_freq)
                k = apply_rope(k, pos, inv_freq)
                attn = attend_chunk_hybrid(
                    q, k, v, pages, pt, pos, prior, kvlen, l_idx,
                    kv_block_pages=kv_block_pages,
                )
                o = jnp.einsum(
                    "bsqd,qdh->bsh",
                    attn.reshape(mb, C, hq_loc, D),
                    lp["wo"].reshape(hq_loc, D, cfg.hidden),
                    precision=_PREC,
                )
                h = h + jax.lax.psum(o, "tp")
                h2 = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
                gate = jax.nn.silu(
                    jnp.einsum("bsh,hi->bsi", h2, lp["w_gate"], precision=_PREC)
                )
                up = jnp.einsum("bsh,hi->bsi", h2, lp["w_up"], precision=_PREC)
                down = jnp.einsum(
                    "bsi,ih->bsh", gate * up, lp["w_down"], precision=_PREC
                )
                h = h + jax.lax.psum(down, "tp")
                return h, (k.astype(pool.dtype), v.astype(pool.dtype))

            return jax.lax.scan(
                body, h, (jnp.arange(l_loc), layers)
            )

        last = pp - 1

        def tick(carry, t):
            buf, outs = carry
            # Stage `idx` processes microbatch m = t - idx this tick (the
            # activation that entered stage 0 at tick m). Out-of-range m
            # is warm-up/drain garbage: computed (lockstep SPMD), masked
            # out of `outs` and out of the KV scatter below.
            m = t - idx
            safe_m = jnp.clip(m, 0, n_micro - 1)
            feed = jax.lax.dynamic_index_in_dim(
                x_all, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            inp = jnp.where(idx == 0, feed, buf)
            pos = jax.lax.dynamic_index_in_dim(pos_all, safe_m, 0, keepdims=False)
            pt = jax.lax.dynamic_index_in_dim(pt_all, safe_m, 0, keepdims=False)
            kvlen = jax.lax.dynamic_index_in_dim(
                kvlen_all, safe_m, 0, keepdims=False
            )
            y, kv_new = stage(inp, pos, pt, kvlen)
            done = y  # last stage's finished hidden for microbatch m
            cur = jax.lax.dynamic_index_in_dim(outs, safe_m, 0, keepdims=False)
            keep = jnp.logical_and(idx == last, jnp.logical_and(m >= 0, m < n_micro))
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(keep, done, cur), safe_m, 0
            )
            buf = jax.lax.ppermute(
                y, "pp", [(i, i + 1) for i in range(pp - 1)]
            )
            return (buf, outs), kv_new

        buf0 = jnp.zeros((mb, C, cfg.hidden), x_all.dtype)
        outs0 = jnp.zeros((n_micro, mb, C, cfg.hidden), x_all.dtype)
        (_, outs), (k_ticks, v_ticks) = jax.lax.scan(
            tick, (buf0, outs0), jnp.arange(n_ticks)
        )
        # k_ticks/v_ticks: [ticks, L/pp, mb, C, Hkv/tp, D]. Scatter each
        # valid tick's microbatch-KV into the local pool shard; invalid
        # (warm-up/drain) ticks re-write the existing values (no-op).
        for t in range(n_ticks):
            m = t - idx
            safe_m = jnp.clip(m, 0, n_micro - 1)
            valid = jnp.logical_and(m >= 0, m < n_micro)
            sl = jax.lax.dynamic_index_in_dim(
                slots_all, safe_m, 0, keepdims=False
            )  # [mb, C]
            # [L/pp, mb, C, Hkv/tp, D] → pool target [2, L/pp, Hkv/tp, mb, C, D]
            new = jnp.stack([k_ticks[t], v_ticks[t]]).transpose(0, 1, 4, 2, 3, 5)
            old = pool[:, :, :, sl]
            pool = pool.at[:, :, :, sl].set(jnp.where(valid, new, old))
        # Finished activations live on the last stage; psum replicates
        # them over pp (other stages contribute zeros). tp is already
        # uniform (both block psums precede every write into `outs`).
        hidden = jax.lax.psum(
            jnp.where(idx == last, outs.astype(jnp.float32), 0.0), "pp"
        ).astype(x_all.dtype)
        return hidden, pool

    hidden, kv_pool = run(
        params["layers"], kv_pool, x_all, pos_all, slots_all, pt_all, kvlen_all
    )
    logits = _logits(params, cfg, hidden.reshape(B, C, cfg.hidden))
    return logits, kv_pool
