"""Pipeline-parallel SERVING: prefill chunks and decode steps over a
``(pp, tp)`` mesh.

VERDICT round-2 weak #4: ``parallel/pipeline.py`` proved GPipe numerics
but nothing in the serving stack could use a ``pp`` axis — the
Qwen2-72B/v5p gate (``BASELINE.md`` last row) realistically needs pp×tp.
This module is that path, shaped so the Engine's scheduler, radix tree,
page tables, and publish logic run UNCHANGED:

- The param pytree keeps its stacked ``[L, ...]`` layer leaves and the KV
  pool keeps its ``[2, L, Hkv, slots, D]`` layout — pp is purely a
  *sharding* of the existing layer axis (``shard_map`` hands each stage
  its contiguous ``L/pp`` block), tp a sharding of the head/ffn axes.
  No reshapes, no second checkpoint format.
- One function serves both phases: a decode step is a prefill chunk with
  ``C = 1`` (same page-table attention, same pool scatter), so the pp
  schedule exists in exactly one place.

Schedule: GPipe microbatches over the BATCH axis (rows are independent in
serving, so microbatching is free): ``n_micro`` row-groups enter stage 0
one tick apart, activations ``ppermute`` stage-to-stage, and each stage's
chunk-KV is collected per tick and scattered into the pool shard AFTER
the tick scan — keeping the pool out of the scan carry (the same
materialization bug ``prefill_chunk_paged`` documents). Weights never
move; activations ``[mb, C, H]`` are the only inter-stage traffic — the
layout that makes pp the memory-fit axis for models tp alone can't hold.

Tensor parallelism inside each stage is manual Megatron inside the same
``shard_map``: column-parallel wq/wk/wv/w_gate/w_up, row-parallel
wo/w_down, exactly two ``psum``s per block over the ``tp`` axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from radixmesh_tpu.models.llama import (
    ModelConfig,
    _embed_lookup,
    _logits,
    _wmm,
    _PREC,
)
from radixmesh_tpu.ops.attention import (
    default_use_kernel,
    paged_chunk_attention,
)
from radixmesh_tpu.ops.norm import rms_norm
from radixmesh_tpu.ops.rope import apply_rope, rope_frequencies

__all__ = [
    "make_pp_serving_mesh",
    "pp_layer_specs",
    "pp_pool_spec",
    "shard_params_pp",
    "pp_forward_chunk",
    "pp_decode_multi",
]


def make_pp_serving_mesh(pp: int, tp: int = 1, devices=None) -> Mesh:
    """A ``(pp, tp)`` mesh over the first ``pp*tp`` devices (tp innermost:
    its two psums per block are the bandwidth-hungry traffic and belong on
    the fastest ICI wraparound)."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    if pp * tp > len(devices):
        raise ValueError(f"pp={pp} x tp={tp} exceeds {len(devices)} devices")
    arr = np.asarray(devices[: pp * tp]).reshape(pp, tp)
    return Mesh(arr, axis_names=("pp", "tp"))


def pp_layer_specs() -> dict:
    """PartitionSpec per stacked-layer leaf: layer axis over ``pp``, head
    and ffn axes over ``tp`` (Megatron column/row split)."""
    return {
        "attn_norm": P("pp", None),
        "mlp_norm": P("pp", None),
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
        "w_gate": P("pp", None, "tp"),
        "w_up": P("pp", None, "tp"),
        "w_down": P("pp", "tp", None),
        "bq": P("pp", "tp"),
        "bk": P("pp", "tp"),
        "bv": P("pp", "tp"),
        # W8A16 scale leaves (ops/wquant.py): per-out-channel, so they
        # shard like their weight's OUTPUT axis — column-split weights'
        # scales over tp, row-split weights' (wo, w_down) replicated.
        "wq_s": P("pp", "tp"),
        "wk_s": P("pp", "tp"),
        "wv_s": P("pp", "tp"),
        "wo_s": P("pp", None),
        "w_gate_s": P("pp", "tp"),
        "w_up_s": P("pp", "tp"),
        "w_down_s": P("pp", None),
    }


def pp_pool_spec() -> P:
    """KV pool ``[2, L, Hkv, slots, D]``: layers over pp, kv heads over tp
    — each stage holds only its own layers' KV, each tp chip its heads."""
    return P(None, "pp", "tp", None, None)


def shard_params_pp(params: dict, cfg: ModelConfig, mesh: Mesh) -> dict:
    """Place the UNCHANGED param pytree onto a ``(pp, tp)`` mesh."""
    specs = pp_layer_specs()
    out = dict(params)
    out["layers"] = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params["layers"].items()
    }
    repl = NamedSharding(mesh, P())
    out["embed"] = jax.device_put(params["embed"], repl)
    out["final_norm"] = jax.device_put(params["final_norm"], repl)
    if "embed_s" in params:
        out["embed_s"] = jax.device_put(params["embed_s"], repl)
    if "lm_head" in params:
        out["lm_head"] = jax.device_put(
            params["lm_head"], NamedSharding(mesh, P(None, "tp"))
        )
    if "lm_head_s" in params:
        out["lm_head_s"] = jax.device_put(
            params["lm_head_s"], NamedSharding(mesh, P("tp"))
        )
    return out


def pp_scale_spec() -> P:
    """int8-pool scales ``[2, L, Hkv, slots]``: shard with their data."""
    return P(None, "pp", "tp", None)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "page_size", "kv_block_pages", "mesh", "n_micro",
        "use_kernel", "interpret",
    ),
    donate_argnames=("kv_pool", "kv_scale"),
)
def pp_forward_chunk(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, C] chunk tokens (C=1 for a decode step)
    positions: jnp.ndarray,  # [B, C] absolute positions
    kv_pool: jnp.ndarray,  # [2, L, Hkv, slots, D] sharded pp_pool_spec()
    slots: jnp.ndarray,  # [B, C] pool slot per token (pad → scratch)
    page_table: jnp.ndarray,  # [B, max_pages]
    kv_lengths: jnp.ndarray,  # [B] valid context incl. this chunk
    *,
    page_size: int = 16,
    kv_block_pages: int = 32,
    mesh: Mesh,
    n_micro: int = 1,
    kv_scale: jnp.ndarray | None = None,  # [2, L, Hkv, slots] int8 pool
    use_kernel: bool | None = None,
    interpret: bool = False,
):
    """Logits + updated pool for one chunk through the layer pipeline.

    ``B`` must divide into ``n_micro`` microbatches. Returns
    ``(logits [B, C, V], kv_pool)`` with logits replicated — plus the
    updated ``kv_scale`` when the pool is int8-quantized (the chunk is
    quantized in-layer and attended dequantized, the same
    see-what-you-store invariant ``prefill_chunk_paged`` keeps).

    Stage bodies dispatch chunk attention by backend exactly like the
    single-chip path (``ops/attention.py::paged_chunk_attention``): the
    Pallas chunk kernel on TPU (heads already local inside the
    shard_map), the jnp hybrid elsewhere (VERDICT round-3 next-step #3).
    """
    pp = mesh.shape["pp"]
    tp = mesh.shape.get("tp", 1)
    if use_kernel is None:
        use_kernel = default_use_kernel(cfg.head_dim)
    L = cfg.n_layers
    if L % pp:
        raise ValueError(f"n_layers={L} not divisible by pp={pp}")
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError("head counts must divide tp")
    B, C = tokens.shape
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
    mb = B // n_micro
    n_ticks = n_micro + pp - 1
    hq_loc = cfg.n_heads // tp
    hkv_loc = cfg.n_kv_heads // tp
    D = cfg.head_dim
    num_slots = kv_pool.shape[3]
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)

    # Embed outside the shard_map (table replicated); group rows into
    # microbatches. Aux arrays get the same [n_micro, mb, ...] grouping.
    x_all = _embed_lookup(params, tokens).reshape(n_micro, mb, C, cfg.hidden)
    pos_all = positions.reshape(n_micro, mb, C)
    slots_all = slots.reshape(n_micro, mb, C)
    pt_all = page_table.reshape(n_micro, mb, -1)
    kvlen_all = kv_lengths.reshape(n_micro, mb)

    layer_specs = {
        k: v for k, v in pp_layer_specs().items() if k in params["layers"]
    }
    quant = kv_scale is not None
    scale_in_spec = pp_scale_spec() if quant else P()
    scale_arg = kv_scale if quant else jnp.zeros((), jnp.float32)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            layer_specs, pp_pool_spec(), scale_in_spec,
            P(), P(), P(), P(), P(),
        ),
        out_specs=(P(), pp_pool_spec(), scale_in_spec),
        check_vma=False,
    )
    def run(layers, pool, scale, x_all, pos_all, slots_all, pt_all, kvlen_all):
        # Per-device views: layers leaves [L/pp, ...] head-sliced; pool
        # [2, L/pp, Hkv/tp, slots, D]; scale [2, L/pp, Hkv/tp, slots].
        idx = jax.lax.axis_index("pp")
        l_loc = pool.shape[1]
        pages = pool.reshape(
            2, l_loc, hkv_loc, num_slots // page_size, page_size, D
        )
        scale_pages = (
            scale.reshape(
                2, l_loc, hkv_loc, num_slots // page_size, page_size
            )
            if quant
            else None
        )

        def stage(h, pos, pt, kvlen):
            """This stage's L/pp layers over one microbatch's chunk.
            Returns (h, per-layer chunk K/V payloads) — scattered into the
            pool AFTER the tick scan."""
            prior = jnp.minimum(pos[:, 0], kvlen)

            def body(h, xs):
                l_idx, lp = xs
                hn = rms_norm(h, lp["attn_norm"], cfg.rms_eps)
                q = _wmm(lp, "wq", "bsh,hd->bsd", hn)
                k = _wmm(lp, "wk", "bsh,hd->bsd", hn)
                v = _wmm(lp, "wv", "bsh,hd->bsd", hn)
                if cfg.qkv_bias:
                    q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
                q = q.reshape(mb, C, hq_loc, D)
                k = k.reshape(mb, C, hkv_loc, D)
                v = v.reshape(mb, C, hkv_loc, D)
                q = apply_rope(q, pos, inv_freq)
                k = apply_rope(k, pos, inv_freq)
                if quant:
                    # Quantize NOW, attend the dequantized copy — the
                    # shared see-what-you-store step (ops/quant.py).
                    from radixmesh_tpu.ops.quant import quantize_for_store

                    k_int, v_int, k_sc, v_sc, k, v = quantize_for_store(k, v)
                attn = paged_chunk_attention(
                    q, k, v, pages, pt, pos, prior, kvlen, l_idx,
                    kv_block_pages=kv_block_pages,
                    kv_scales=scale_pages,
                    use_kernel=use_kernel,
                    interpret=interpret,
                )
                # Row-split projections: the per-out-channel W8A16
                # scale is constant across tp shards, so applying it to
                # the partial sums before the psum is exact.
                o = _wmm(
                    lp, "wo", "bsqd,qdh->bsh",
                    attn.reshape(mb, C, hq_loc, D),
                    reshape=(hq_loc, D, cfg.hidden),
                )
                h = h + jax.lax.psum(o, "tp")
                h2 = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
                gate = jax.nn.silu(_wmm(lp, "w_gate", "bsh,hi->bsi", h2))
                up = _wmm(lp, "w_up", "bsh,hi->bsi", h2)
                down = _wmm(lp, "w_down", "bsi,ih->bsh", gate * up)
                h = h + jax.lax.psum(down, "tp")
                if quant:
                    return h, (k_int, v_int, k_sc, v_sc)
                return h, (k.astype(pool.dtype), v.astype(pool.dtype))

            return jax.lax.scan(
                body, h, (jnp.arange(l_loc), layers)
            )

        last = pp - 1

        def tick(carry, t):
            buf, outs = carry
            # Stage `idx` processes microbatch m = t - idx this tick (the
            # activation that entered stage 0 at tick m). Out-of-range m
            # is warm-up/drain garbage: computed (lockstep SPMD), masked
            # out of `outs` and out of the KV scatter below.
            m = t - idx
            safe_m = jnp.clip(m, 0, n_micro - 1)
            feed = jax.lax.dynamic_index_in_dim(
                x_all, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            inp = jnp.where(idx == 0, feed, buf)
            pos = jax.lax.dynamic_index_in_dim(pos_all, safe_m, 0, keepdims=False)
            pt = jax.lax.dynamic_index_in_dim(pt_all, safe_m, 0, keepdims=False)
            kvlen = jax.lax.dynamic_index_in_dim(
                kvlen_all, safe_m, 0, keepdims=False
            )
            y, kv_new = stage(inp, pos, pt, kvlen)
            done = y  # last stage's finished hidden for microbatch m
            cur = jax.lax.dynamic_index_in_dim(outs, safe_m, 0, keepdims=False)
            keep = jnp.logical_and(idx == last, jnp.logical_and(m >= 0, m < n_micro))
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(keep, done, cur), safe_m, 0
            )
            buf = jax.lax.ppermute(
                y, "pp", [(i, i + 1) for i in range(pp - 1)]
            )
            return (buf, outs), kv_new

        buf0 = jnp.zeros((mb, C, cfg.hidden), x_all.dtype)
        outs0 = jnp.zeros((n_micro, mb, C, cfg.hidden), x_all.dtype)
        (_, outs), kv_ticks = jax.lax.scan(
            tick, (buf0, outs0), jnp.arange(n_ticks)
        )
        # kv_ticks leaves: [ticks, L/pp, mb, C, Hkv/tp(, D)]. Scatter each
        # valid tick's microbatch payloads into the local pool shard;
        # invalid (warm-up/drain) ticks re-write the existing values.
        for t in range(n_ticks):
            m = t - idx
            safe_m = jnp.clip(m, 0, n_micro - 1)
            valid = jnp.logical_and(m >= 0, m < n_micro)
            sl = jax.lax.dynamic_index_in_dim(
                slots_all, safe_m, 0, keepdims=False
            )  # [mb, C]
            # [L/pp, mb, C, Hkv/tp, D] → pool target [2, L/pp, Hkv/tp, mb, C, D]
            new = jnp.stack(
                [kv_ticks[0][t], kv_ticks[1][t]]
            ).transpose(0, 1, 4, 2, 3, 5)
            old = pool[:, :, :, sl]
            pool = pool.at[:, :, :, sl].set(jnp.where(valid, new, old))
            if quant:
                new_s = jnp.stack(
                    [kv_ticks[2][t], kv_ticks[3][t]]
                ).transpose(0, 1, 4, 2, 3)
                old_s = scale[:, :, :, sl]
                scale = scale.at[:, :, :, sl].set(
                    jnp.where(valid, new_s, old_s)
                )
        # Finished activations live on the last stage; psum replicates
        # them over pp (other stages contribute zeros). tp is already
        # uniform (both block psums precede every write into `outs`).
        hidden = jax.lax.psum(
            jnp.where(idx == last, outs.astype(jnp.float32), 0.0), "pp"
        ).astype(x_all.dtype)
        return hidden, pool, scale

    hidden, kv_pool, kv_scale_out = run(
        params["layers"], kv_pool, scale_arg, x_all, pos_all, slots_all,
        pt_all, kvlen_all,
    )
    logits = _logits(params, cfg, hidden.reshape(B, C, cfg.hidden))
    if quant:
        return logits, kv_pool, kv_scale_out
    return logits, kv_pool


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "page_size", "k_steps", "mesh", "use_kernel", "interpret"
    ),
    donate_argnames=("kv_pool", "kv_scale"),
)
def pp_decode_multi(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] current token per sequence
    kv_pool: jnp.ndarray,  # [2, L, Hkv, slots, D] sharded pp_pool_spec()
    page_table: jnp.ndarray,  # [B, max_pages] — pages preallocated k ahead
    lengths: jnp.ndarray,  # [B] context length incl. the first fed token
    key: jax.Array,
    temperatures: jnp.ndarray,  # [B]
    top_ps: jnp.ndarray,  # [B]
    top_ks: jnp.ndarray,  # [B] (0 = off)
    *,
    page_size: int = 16,
    k_steps: int = 8,
    mesh: Mesh,
    kv_scale: jnp.ndarray | None = None,  # [2, L, Hkv, slots] int8 pool
    use_kernel: bool | None = None,
    interpret: bool = False,
    scratch_slot: jnp.ndarray | int | None = None,
):
    """``k_steps`` fused decode iterations through the layer PIPELINE:
    one host round trip per k tokens per batch, under pp×tp.

    Schedule: a rotating token-level pipeline with ``n_micro = pp``
    microbatches of rows. At tick ``t`` stage ``idx`` processes
    ``v = t - idx``: microbatch ``v mod pp`` at decode step ``v div pp``.
    Activations ``ppermute`` forward (stage i → i+1); the LAST stage
    norms + head-projects (column-parallel, all-gathered over tp),
    samples on device, and the sampled token ``ppermute``s back to stage
    0 (pp-1 → 0), which embeds it next tick — so every stage is busy
    every tick and the wrap IS the step boundary. Total ticks
    ``k·pp + pp - 1``; warm-up/drain ticks compute garbage whose KV
    writes are masked to re-write existing values.

    The pool shard rides the tick scan in PAGES layout (step s+1 reads
    step s's KV, so the deferred-scatter trick of ``pp_forward_chunk``
    cannot apply). On TPU backends each stage's per-layer write+attend is
    the aliased Pallas ``paged_decode_fused_kernel`` — the pool buffer
    flows through the layer scan in place (``input_output_aliases``), so
    no stage ever materializes a pool copy (VERDICT round-3 weak #3; the
    single-chip ``paged_decode_attention`` rationale,
    ``ops/attention.py:503-505``). Backend selection matches that path:
    kernel on non-CPU with lane-aligned heads, jnp reference elsewhere
    (or when ``use_kernel=False`` is forced). Warm-up/drain ticks can't
    mask a kernel's in-place write, so their writes are REDIRECTED to
    ``scratch_slot`` (the engine's reserved scratch page — required when
    the kernel is engaged); the jnp path keeps the masked-where write.

    Returns ``(sampled [k, B], kv_pool)`` — the single-chip
    ``decode_multi`` contract, so the engine's bookkeeping is shared.
    """
    pp = mesh.shape["pp"]
    tp = mesh.shape.get("tp", 1)
    L = cfg.n_layers
    B = tokens.shape[0]
    if B % pp:
        raise ValueError(f"batch {B} must divide into n_micro=pp={pp}")
    if use_kernel is None:
        use_kernel = default_use_kernel(cfg.head_dim)
    if use_kernel and scratch_slot is None:
        raise ValueError(
            "pp_decode_multi with the fused kernel engaged needs "
            "scratch_slot (warm-up/drain writes are redirected, not masked)"
        )
    scratch_arr = (
        jnp.asarray(scratch_slot, dtype=jnp.int32)
        if scratch_slot is not None
        else jnp.zeros((), jnp.int32)
    )
    mb = B // pp
    n_micro = pp
    n_ticks = k_steps * pp + pp - 1
    hq_loc = cfg.n_heads // tp
    hkv_loc = cfg.n_kv_heads // tp
    D = cfg.head_dim
    num_slots = kv_pool.shape[3]
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)

    toks_all = tokens.reshape(n_micro, mb)
    pt_all = page_table.reshape(n_micro, mb, -1)
    len_all = lengths.reshape(n_micro, mb)
    temp_all = temperatures.reshape(n_micro, mb)
    topp_all = top_ps.reshape(n_micro, mb)
    topk_all = top_ks.reshape(n_micro, mb)

    layer_specs = {
        k: v for k, v in pp_layer_specs().items() if k in params["layers"]
    }
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    head_spec = P() if cfg.tie_embeddings else P(None, "tp")
    # W8A16 (ops/wquant.py): int8 embed/head ride with their scale
    # vectors; zeros stand in when full-precision so the shard_map
    # signature is static.
    w8_embed = params.get("embed_s")
    w8_head = (
        params.get("embed_s") if cfg.tie_embeddings
        else params.get("lm_head_s")
    )
    embed_s_arg = w8_embed if w8_embed is not None else jnp.zeros((), jnp.float32)
    head_s_arg = w8_head if w8_head is not None else jnp.zeros((), jnp.float32)
    head_s_spec = (
        P() if (w8_head is None or cfg.tie_embeddings) else P("tp")
    )
    quant = kv_scale is not None
    scale_in_spec = pp_scale_spec() if quant else P()
    scale_arg = kv_scale if quant else jnp.zeros((), jnp.float32)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            layer_specs, pp_pool_spec(), scale_in_spec, P(), P(), head_spec,
            P(), head_s_spec,
            P(), P(), P(), P(), P(), P(), P(), P(),
        ),
        out_specs=(P(), pp_pool_spec(), scale_in_spec),
        check_vma=False,
    )
    def run(layers, pool, scale, embed, final_norm, head_local,
            embed_s, head_s, toks_all,
            pt_all, len_all, temp_all, topp_all, topk_all, key, scratch):
        from radixmesh_tpu.ops.attention import attend_decode_ref
        from radixmesh_tpu.ops.sampling import sample_tokens

        idx = jax.lax.axis_index("pp")
        last = pp - 1
        l_loc = pool.shape[1]
        rows = jnp.arange(mb)
        n_pages = num_slots // page_size
        # The tick/layer scans carry the pool in PAGES layout (the fused
        # kernel's native view; contiguous reshape = metadata only).
        pool = pool.reshape(2, l_loc, hkv_loc, n_pages, page_size, D)
        if quant:
            scale = scale.reshape(2, l_loc, hkv_loc, n_pages, page_size)

        def stage(pool, scale, x, pt, kvlen, slot, valid):
            """This stage's layers over one microbatch's single token.
            ``x`` [mb, H]; KV lands at ``slot`` — masked (jnp) or
            scratch-redirected (kernel) on invalid ticks."""
            pos = (kvlen - 1)[:, None]  # [mb, 1] absolute position
            slot_eff = jnp.where(valid, slot, jnp.full_like(slot, scratch))

            def body(carry, xs):
                pool, scale, h = carry
                l_idx, lp = xs
                hn = rms_norm(h[:, None, :], lp["attn_norm"], cfg.rms_eps)
                q = _wmm(lp, "wq", "bsh,hd->bsd", hn)
                k_ = _wmm(lp, "wk", "bsh,hd->bsd", hn)
                v_ = _wmm(lp, "wv", "bsh,hd->bsd", hn)
                if cfg.qkv_bias:
                    q, k_, v_ = q + lp["bq"], k_ + lp["bk"], v_ + lp["bv"]
                q = apply_rope(q.reshape(mb, 1, hq_loc, D), pos, inv_freq)
                k_ = apply_rope(k_.reshape(mb, 1, hkv_loc, D), pos, inv_freq)
                v_ = v_.reshape(mb, 1, hkv_loc, D)
                if use_kernel:
                    # Aliased write+attend in one pallas_call: the pool
                    # buffer flows through the layer scan in place.
                    from radixmesh_tpu.ops.paged_attention import (
                        paged_decode_fused_kernel,
                    )

                    if quant:
                        attn, pool, scale = paged_decode_fused_kernel(
                            q[:, 0], k_[:, 0], v_[:, 0], pool, slot_eff,
                            pt, kvlen, l_idx, interpret=interpret,
                            kv_scales=scale,
                        )
                    else:
                        attn, pool = paged_decode_fused_kernel(
                            q[:, 0], k_[:, 0], v_[:, 0], pool, slot_eff,
                            pt, kvlen, l_idx, interpret=interpret,
                        )
                else:
                    pg, off = slot // page_size, slot % page_size
                    # Masked in-place write at this layer's slot column;
                    # invalid (warm-up/drain) ticks re-write old values.
                    # The mixed scalar+array index puts the advanced axes
                    # FIRST: target shape is [mb, 2, Hkv/tp, D].
                    if quant:
                        from radixmesh_tpu.ops.quant import quantize_for_store

                        k_int, v_int, k_sc, v_sc, _, _ = quantize_for_store(
                            k_, v_
                        )
                        new_kv = jnp.stack(
                            [k_int[:, 0], v_int[:, 0]], axis=1
                        ).astype(pool.dtype)
                        new_sc = jnp.stack([k_sc[:, 0], v_sc[:, 0]], axis=1)
                        old_s = scale[:, l_idx, :, pg, off]
                        scale = scale.at[:, l_idx, :, pg, off].set(
                            jnp.where(valid, new_sc, old_s)
                        )
                    else:
                        new_kv = jnp.stack(
                            [k_[:, 0], v_[:, 0]], axis=1
                        ).astype(pool.dtype)
                    old = pool[:, l_idx, :, pg, off]
                    pool = pool.at[:, l_idx, :, pg, off].set(
                        jnp.where(valid, new_kv, old)
                    )
                    pages = jax.lax.dynamic_index_in_dim(
                        pool, l_idx, 1, keepdims=False
                    )
                    if quant:
                        sc_pages = jax.lax.dynamic_index_in_dim(
                            scale, l_idx, 1, keepdims=False
                        )
                        attn = attend_decode_ref(
                            q[:, 0], pages[0], pages[1], pt, kvlen,
                            k_scales=sc_pages[0], v_scales=sc_pages[1],
                        )
                    else:
                        attn = attend_decode_ref(
                            q[:, 0], pages[0], pages[1], pt, kvlen
                        )
                # Per-out-channel W8A16 scales are shard-constant, so
                # scaling the partial sums before the psum is exact.
                o = _wmm(
                    lp, "wo", "bqd,qdh->bh",
                    attn.reshape(mb, hq_loc, D),
                    reshape=(hq_loc, D, cfg.hidden),
                )
                h = h + jax.lax.psum(o, "tp")
                h2 = rms_norm(h[:, None, :], lp["mlp_norm"], cfg.rms_eps)
                gate = jax.nn.silu(_wmm(lp, "w_gate", "bsh,hi->bsi", h2))
                up = _wmm(lp, "w_up", "bsh,hi->bsi", h2)
                down = _wmm(lp, "w_down", "bsi,ih->bsh", gate * up)[:, 0]
                h = h + jax.lax.psum(down, "tp")
                return (pool, scale, h), None

            (pool, scale, h), _ = jax.lax.scan(
                body, (pool, scale, x), (jnp.arange(l_loc), layers)
            )
            return pool, scale, h

        def tick(carry, t):
            pool, scale, act_buf, tok_buf, outs = carry
            v = t - idx
            s = jnp.clip(v // pp, 0, k_steps - 1)
            m = jnp.clip(v, 0, None) % pp
            valid = jnp.logical_and(v >= 0, v // pp < k_steps)
            pt = jax.lax.dynamic_index_in_dim(pt_all, m, 0, keepdims=False)
            base_len = jax.lax.dynamic_index_in_dim(
                len_all, m, 0, keepdims=False
            )
            kvlen = base_len + s
            pos = kvlen - 1
            slot = (
                pt[rows, pos // page_size] * page_size + pos % page_size
            )
            # Stage 0's input token: the first step feeds the caller's
            # token, later steps the sample that wrapped around.
            first = jax.lax.dynamic_index_in_dim(
                toks_all, m, 0, keepdims=False
            )
            tok_in = jnp.where(s == 0, first, tok_buf)
            # One dequant rule for the whole stack: route through
            # _embed_lookup so the pp path can never drift from the
            # single-device embedding math.
            x0 = _embed_lookup(
                {"embed": embed, "embed_s": embed_s,
                 "final_norm": final_norm},
                tok_in,
            )
            x = jnp.where(idx == 0, x0, act_buf)
            pool, scale, y = stage(pool, scale, x, pt, kvlen, slot, valid)

            # Last stage: head + on-device sampling for (m, s).
            hn = rms_norm(y[:, None, :], final_norm, cfg.rms_eps)[:, 0]
            logits_part = jnp.einsum(
                "bh,hv->bv", hn, head_local.astype(hn.dtype)
                if w8_head is not None else head_local,
                preferred_element_type=jnp.float32, precision=_PREC,
            )
            if w8_head is not None:
                logits_part = logits_part * head_s
            if tp > 1 and not cfg.tie_embeddings:
                logits = jax.lax.all_gather(
                    logits_part, "tp", axis=1, tiled=True
                )
            else:
                logits = logits_part
            sampled = sample_tokens(
                logits,
                jax.random.fold_in(key, jnp.clip(v, 0, None)),
                temperature=jax.lax.dynamic_index_in_dim(
                    temp_all, m, 0, keepdims=False
                ),
                top_p=jax.lax.dynamic_index_in_dim(
                    topp_all, m, 0, keepdims=False
                ),
                top_k=jax.lax.dynamic_index_in_dim(
                    topk_all, m, 0, keepdims=False
                ),
            ).astype(jnp.int32)
            keep = jnp.logical_and(idx == last, valid)
            cur = outs[m, :, s]
            outs = outs.at[m, :, s].set(jnp.where(keep, sampled, cur))
            act_buf = jax.lax.ppermute(
                y, "pp", [(i, i + 1) for i in range(pp - 1)]
            )
            tok_buf = jax.lax.ppermute(sampled, "pp", [(last, 0)])
            return (pool, scale, act_buf, tok_buf, outs), None

        # Activation dtype follows the norms, NOT the embedding table —
        # an int8 (W8A16) table must not make the pipeline buffer int8.
        act0 = jnp.zeros((mb, cfg.hidden), final_norm.dtype)
        tok0 = jnp.zeros((mb,), jnp.int32)
        outs0 = jnp.zeros((n_micro, mb, k_steps), jnp.int32)
        (pool, scale, _, _, outs), _ = jax.lax.scan(
            tick, (pool, scale, act0, tok0, outs0), jnp.arange(n_ticks)
        )
        # Sampled tokens live on the last stage; psum replicates (other
        # stages hold zeros). tp already uniform: the gathered logits and
        # the folded key are identical on every tp peer.
        outs = jax.lax.psum(jnp.where(idx == last, outs, 0), "pp")
        pool = pool.reshape(2, l_loc, hkv_loc, num_slots, D)
        if quant:
            scale = scale.reshape(2, l_loc, hkv_loc, num_slots)
        return outs, pool, scale

    outs, kv_pool, kv_scale_out = run(
        params["layers"], kv_pool, scale_arg, params["embed"],
        params["final_norm"], head, embed_s_arg, head_s_arg,
        toks_all, pt_all, len_all, temp_all,
        topp_all, topk_all, key, scratch_arr,
    )
    # [n_micro, mb, k] → the decode_multi contract [k, B] (row-major
    # microbatch grouping mirrors every other reshape in this module).
    sampled = outs.reshape(B, k_steps).T
    if quant:
        return sampled, kv_pool, kv_scale_out
    return sampled, kv_pool
