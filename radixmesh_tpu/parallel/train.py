"""Sharded causal-LM training step (pjit over the (dp, sp, tp) mesh).

The reference has no training anywhere (SURVEY: "no training, and no
parallelism ... anywhere in the tree"); this is the net-new piece that
makes the framework's model side complete and gives the driver's
``dryrun_multichip`` a full sharded step to compile: params tp-sharded by
logical axis, batch dp×sp-sharded, grad reduction + TP psums all inserted
by XLA from the sharding annotations alone.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from radixmesh_tpu.models.llama import (
    ModelConfig,
    init_params,
    param_logical_axes,
    prefill_forward,
)
from radixmesh_tpu.parallel.sharding import batch_sharding, param_sharding

__all__ = ["TrainState", "causal_lm_loss", "make_train_state", "make_train_step"]


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def _empty_prefix(cfg: ModelConfig, batch: int):
    """Zero-length cached prefix: training attends over the raw sequence."""
    shape = (cfg.n_layers, batch, 0, cfg.n_kv_heads, cfg.head_dim)
    k = jnp.zeros(shape, dtype=cfg.dtype)
    return k, k, jnp.zeros((batch,), dtype=jnp.int32)


def causal_lm_loss(params: Any, cfg: ModelConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross-entropy over ``tokens [B, S]``."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    b, s = inputs.shape
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    ck, cv, plen = _empty_prefix(cfg, b)
    logits, _, _ = prefill_forward(params, cfg, inputs, positions, ck, cv, plen)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def opt_state_sharding(opt_state_shapes: Any, p_shard: Any, mesh) -> Any:
    """Sharding pytree for an optax state: any subtree that mirrors the
    param pytree (adam mu/nu, sgd trace, ...) gets the param shardings;
    every other leaf (step counts, scalars) is replicated."""
    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(mesh, PartitionSpec())
    p_def = jax.tree_util.tree_structure(p_shard)

    def rec(node):
        if jax.tree_util.tree_structure(node) == p_def:
            return p_shard
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if isinstance(node, tuple):  # includes NamedTuple optax states
            mapped = [rec(c) for c in node]
            return type(node)(*mapped) if hasattr(node, "_fields") else tuple(mapped)
        if isinstance(node, list):
            return [rec(c) for c in node]
        return replicated

    return rec(opt_state_shapes)


def make_train_state(
    cfg: ModelConfig,
    key: jax.Array,
    mesh,
    optimizer: optax.GradientTransformation,
) -> TrainState:
    """Initialize params directly sharded on the mesh (out_shardings on the
    jitted init — no host-side full copy), opt state sharded to match."""
    p_shard = param_sharding(param_logical_axes(cfg), mesh)
    params = jax.jit(partial(init_params, cfg), out_shardings=p_shard)(key)
    o_shard = opt_state_sharding(
        jax.eval_shape(optimizer.init, params), p_shard, mesh
    )
    opt_state = jax.jit(optimizer.init, out_shardings=o_shard)(params)
    return TrainState(params, opt_state, jnp.zeros((), jnp.int32))


def make_train_step(
    cfg: ModelConfig,
    mesh,
    optimizer: optax.GradientTransformation,
):
    """Returns jitted ``step(state, tokens) -> (state, loss)``.

    State is donated (params/opt updated in place in HBM); the batch is
    constrained to (dp, sp) so XLA derives: psum over dp+sp for grads,
    psum over tp inside each block's row-parallel matmuls."""
    tok_shard = batch_sharding(mesh)

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, tokens: jnp.ndarray):
        tokens = jax.lax.with_sharding_constraint(tokens, tok_shard)
        loss, grads = jax.value_and_grad(causal_lm_loss)(state.params, cfg, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return step


def run_dryrun_train_step(mesh) -> float:
    """ONE sharded train step on tiny shapes over ``mesh`` — the shared
    body of the single-host multichip dryrun (``__graft_entry__``) and the
    multi-host dryrun (``launch.py multihost-dryrun``); the two must stay
    the same program so identical meshes provably give identical losses
    across process topologies (tests/test_multihost.py pins that)."""
    import numpy as np
    import optax

    cfg = ModelConfig.tiny()
    tp = mesh.shape["tp"]
    # tiny() has 2 kv heads; wider tp needs every shard non-empty.
    cfg = cfg.replace(
        n_heads=max(4, tp), n_kv_heads=max(2, tp), intermediate=max(256, 2 * tp)
    )
    optimizer = optax.adamw(1e-3)
    state = make_train_state(cfg, jax.random.PRNGKey(0), mesh, optimizer)
    step = make_train_step(cfg, mesh, optimizer)
    batch = max(2, mesh.shape["dp"] * 2)
    seq = max(16, mesh.shape["sp"] * 8) + 1
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)),
        dtype=jnp.int32,
    )
    state, loss = step(state, tokens)
    return float(jax.block_until_ready(loss))
