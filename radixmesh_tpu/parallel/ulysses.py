"""Ulysses-style sequence parallelism: all-to-all head↔sequence swap.

The second of the two long-context strategies SURVEY §5 calls for (both
absent in the reference). Where ring attention (``ring_attention.py``)
keeps queries resident and rotates K/V blocks ``n-1`` times around the
``sp`` ring, the Ulysses pattern pays exactly TWO ``all_to_all``
collectives per attention call:

1. Inputs arrive sequence-sharded ``[B, S/n, H, D]``. An ``all_to_all``
   redistributes them to head-sharded ``[B, S, H/n, D]`` — each chip now
   sees the FULL sequence for its slice of heads.
2. Plain dense causal attention runs locally (full MXU tiles, no loop).
3. A second ``all_to_all`` on the output swaps back to sequence-sharded.

Trade-off vs ring: Ulysses moves activations twice regardless of ``n``
(2·B·S·H·D/n per chip) but runs one large fused attention; ring moves K/V
``n-1`` times but overlaps transfer with compute and has no head-count
divisibility requirement. Ulysses requires ``H % n == 0`` (its parallelism
is capped by head count); prefer ring when heads are few (GQA) or the
mesh is large, Ulysses when attention-per-chip is compute-bound.

GQA note: with ``Hkv < n`` the K/V heads cannot be split ``n`` ways, so
K/V are all-gathered over ``sp`` instead — still cheap, K/V being
``G×`` smaller than Q under GQA.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ulysses_attention", "ulysses_self_attention"]

_NEG_INF = -1e30


def _dense_causal(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Full-sequence causal attention; q [B,S,Hq,D], k/v [B,S,Hkv,D]."""
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qg = (q.astype(jnp.float32) * scale).reshape(b, s, hkv, g, d)
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk",
        qg,
        k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    mask = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
    scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bhgqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s, hq, d).astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,  # [B, S/n, Hq, D] local sequence shard
    k: jnp.ndarray,  # [B, S/n, Hkv, D]
    v: jnp.ndarray,  # [B, S/n, Hkv, D]
    axis_name: str,
) -> jnp.ndarray:
    """Per-shard body — call INSIDE ``shard_map`` with the sequence axis
    sharded over ``axis_name``. Returns the local output [B, S/n, Hq, D]."""
    n = jax.lax.psum(1, axis_name)
    hq, hkv = q.shape[2], k.shape[2]
    if hq % n:
        raise ValueError(f"Ulysses needs query heads ({hq}) divisible by sp ({n})")

    # seq-sharded -> head-sharded: split heads (axis 2), gather seq (axis 1).
    ql = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if hkv % n == 0:
        kl = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
        vl = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    else:
        # GQA with fewer KV heads than chips: replicate K/V (G× smaller
        # than Q) and slice the group each local Q-head slice attends to.
        kg = jax.lax.all_gather(k, axis_name, axis=1, tiled=True)
        vg = jax.lax.all_gather(v, axis_name, axis=1, tiled=True)
        idx = jax.lax.axis_index(axis_name)
        g = hq // hkv  # query heads per kv head
        span = hq // n  # query heads per chip
        if span % g and g % span:
            # A local query slice would straddle a kv-group boundary with a
            # non-covering span — the grouped attention below can't express
            # that mapping. (Ring attention has no such constraint.)
            raise ValueError(
                f"Ulysses GQA needs query-head span ({span}) and group size "
                f"({g}) to divide one another; use ring attention instead"
            )
        h_lo = idx * span  # first local query head (global id)
        # kv head span covering local query heads [h_lo, h_lo + span)
        kv_lo = h_lo // g
        kv_span = max(1, span // g)
        kl = jax.lax.dynamic_slice_in_dim(kg, kv_lo, kv_span, axis=2)
        vl = jax.lax.dynamic_slice_in_dim(vg, kv_lo, kv_span, axis=2)
    out = _dense_causal(ql, kl, vl)
    # head-sharded -> seq-sharded: split seq (axis 1), gather heads (axis 2).
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_self_attention(
    q: jnp.ndarray,  # [B, S, Hq, D] full (logically sharded) sequence
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "sp",
) -> jnp.ndarray:
    """Top-level convenience mirroring :func:`ring_self_attention`."""
    spec = P(None, axis)
    fn = jax.shard_map(
        partial(ulysses_attention, axis_name=axis),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
