"""Multi-chip parallelism: device meshes, logical-axis shardings, and the
sharded train/serve steps.

The reference has **no parallelism of any kind** (SURVEY §2 checklist:
``grep -ri "tensor.parallel|pipeline|all_reduce|nccl|mpi"`` over
``/root/reference`` is empty; its only distribution mechanism is the oplog
ring). These are net-new TPU-first components required by the north star
(Llama-3-8B on v5e-16, Qwen2-72B 32k on v5p-64, ``BASELINE.json``):

- ``sharding``  — ``Mesh`` over (dp, sp, tp) axes; logical→physical rules
  mapping ``models.param_logical_axes`` names onto mesh axes.
- ``train``     — pjit'd causal-LM training step (grads ride XLA psum over
  ICI; no hand-written collectives).
- ``ring_attention`` — ``shard_map`` + ``ppermute`` blockwise attention for
  sequence lengths that exceed one chip's HBM (the 32k config).
- ``pipeline``  — GPipe-style layer stages over a ``pp`` mesh axis
  (microbatched ``ppermute`` schedule; the stacked-layer param layout
  makes stages a reshape).
"""

from radixmesh_tpu.parallel.pipeline import (
    make_pp_mesh,
    make_pp_train_step,
    pipeline_forward,
    stage_params,
)

from radixmesh_tpu.parallel.kv_transfer import (
    make_kv_page_transfer,
    prefill_to_decode_perm,
)
from radixmesh_tpu.parallel.ring_attention import (
    ring_attention,
    ring_self_attention,
)
from radixmesh_tpu.parallel.sharding import (
    MeshPlan,
    batch_sharding,
    make_mesh,
    param_sharding,
    shard_params,
)
from radixmesh_tpu.parallel.train import make_train_state, make_train_step

__all__ = [
    "ring_attention",
    "ring_self_attention",
    "MeshPlan",
    "make_mesh",
    "param_sharding",
    "shard_params",
    "batch_sharding",
    "make_kv_page_transfer",
    "prefill_to_decode_perm",
    "make_train_state",
    "make_train_step",
    "make_pp_mesh",
    "stage_params",
    "pipeline_forward",
    "make_pp_train_step",
]
