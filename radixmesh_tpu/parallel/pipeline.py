"""Pipeline parallelism: layer stages over a ``pp`` mesh axis.

The reference has no parallelism of any kind (SURVEY §2 checklist — PP is
"optional for 72B" in the rebuild plan); this module adds the pp layout the
72B/v5p deployment needs when tensor parallelism alone runs out of ICI
neighbors. TPU-first shape: the model already stacks per-layer params on a
leading ``[L, ...]`` axis consumed by ``lax.scan`` (``models/llama.py``),
so a pipeline stage layout is literally a reshape — ``[L, ...] →
[pp, L/pp, ...]`` with the stage axis sharded over the mesh — and each
device scans only its own ``L/pp`` layers.

Schedule: GPipe-style microbatching inside one ``shard_map``:

- ``n_micro`` microbatches enter stage 0 one tick apart; every tick each
  device runs its stage and ``ppermute``s the activation to its successor
  (reverse-mode AD differentiates straight through — the transpose of a
  shift is the opposite shift, so the same schedule trains).
- The loop runs ``n_micro + pp - 1`` ticks; the warm-up/drain bubble does
  throwaway compute on every stage (predicating it off would save nothing
  on TPU — all programs in a shard_map run in lockstep).
- Embedding and the LM head run *outside* the pipeline (they're replicated
  anyway); the pipeline moves pure ``[mb, S, H]`` activations, one dtype,
  one shape, every tick — the static-shape discipline XLA wants.

Composability: pp is for the *layer* axis only; tp/sp/dp still come from
GSPMD sharding annotations (``parallel/sharding.py``). A combined layout
runs this module's shard_map over the pp axis of a (pp, tp) mesh while
each stage's matmuls are manually head-sharded — left for when a target
model actually exceeds single-axis scaling; the pp schedule itself is
deployment-ready and covered by ``tests/test_pipeline.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from radixmesh_tpu.models.llama import (
    ModelConfig,
    _logits,
    _mlp,
    _qkv,
    _PREC,
)
from radixmesh_tpu.ops.attention import attend_prefill
from radixmesh_tpu.ops.norm import rms_norm
from radixmesh_tpu.ops.rope import apply_rope, rope_frequencies

__all__ = [
    "make_pp_mesh",
    "stage_params",
    "pipeline_forward",
    "make_pp_train_step",
]


def make_pp_mesh(pp: int, devices: list | None = None) -> Mesh:
    """A 1-D ``("pp",)`` mesh over the first ``pp`` devices."""
    devices = devices if devices is not None else jax.devices()
    if pp > len(devices):
        raise ValueError(f"pp={pp} exceeds {len(devices)} devices")
    return Mesh(devices[:pp], axis_names=("pp",))


def stage_params(params: dict, pp: int, mesh: Mesh | None = None) -> dict:
    """Reshape the stacked layer axis ``[L, ...] → [pp, L/pp, ...]``; with
    ``mesh``, place the stage axis on the ``pp`` mesh axis (non-layer
    params replicate)."""
    L = params["layers"]["wq"].shape[0]
    if L % pp:
        raise ValueError(f"n_layers={L} not divisible by pp={pp}")
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda x: x.reshape(pp, L // pp, *x.shape[1:]), params["layers"]
    )
    if mesh is not None:
        stage = NamedSharding(mesh, P("pp"))
        repl = NamedSharding(mesh, P())
        out["layers"] = jax.device_put(out["layers"], stage)
        out = {
            k: (v if k == "layers" else jax.device_put(v, repl))
            for k, v in out.items()
        }
    return out


def _block(cfg: ModelConfig, lp: dict, x: jnp.ndarray, positions: jnp.ndarray,
           inv_freq: jnp.ndarray) -> jnp.ndarray:
    """One transformer block, causal self-attention, no KV cache (the
    training/pipeline body — same math as ``prefill_forward``'s layer with
    an empty prefix)."""
    B, S = x.shape[:2]
    h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q, k, v = _qkv(lp, h, cfg)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    kv_end = jnp.full((B,), S, dtype=jnp.int32)
    attn = attend_prefill(q, k, v, positions, kv_end)
    x = x + jnp.einsum(
        "bsqd,qdh->bsh",
        attn.reshape(B, S, cfg.n_heads, cfg.head_dim),
        lp["wo"].reshape(cfg.n_heads, cfg.head_dim, cfg.hidden),
        precision=_PREC,
    )
    h2 = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    return x + _mlp(lp, h2)


@partial(jax.jit, static_argnames=("cfg", "mesh", "n_micro"))
def pipeline_forward(
    params_pp: dict,  # layers leaves [pp, L/pp, ...] sharded over "pp"
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, S]
    mesh: Mesh,
    n_micro: int,
) -> jnp.ndarray:
    """Causal-LM logits through the layer pipeline. ``B`` must divide into
    ``n_micro`` microbatches; returns ``[B, S, V]`` replicated."""
    pp = mesh.shape["pp"]
    B, S = tokens.shape
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
    mb = B // n_micro
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    positions = jnp.broadcast_to(
        jnp.arange(S, dtype=jnp.int32)[None], (mb, S)
    )
    x = params_pp["embed"][tokens].reshape(n_micro, mb, S, cfg.hidden)

    def stage_fn(local_layers, h):
        def body(h, lp):
            return _block(cfg, lp, h, positions, inv_freq), None

        h, _ = jax.lax.scan(body, h, local_layers)
        return h

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("pp"), P()),
        out_specs=P(),
        check_vma=False,
    )
    def run(layers_local, x_all):
        local = jax.tree.map(lambda a: a[0], layers_local)  # drop stage dim
        idx = jax.lax.axis_index("pp")
        last = pp - 1

        def tick(carry, t):
            buf, outs = carry
            feed = jax.lax.dynamic_index_in_dim(
                x_all, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            inp = jnp.where(idx == 0, feed, buf)
            y = stage_fn(local, inp)
            m = t - last
            safe_m = jnp.clip(m, 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, safe_m, 0, keepdims=False)
            newval = jnp.where(jnp.logical_and(idx == last, m >= 0), y, cur)
            outs = jax.lax.dynamic_update_index_in_dim(outs, newval, safe_m, 0)
            buf = jax.lax.ppermute(
                y, "pp", [(i, i + 1) for i in range(pp - 1)]
            )
            return (buf, outs), None

        buf0 = jnp.zeros_like(x_all[0])
        outs0 = jnp.zeros_like(x_all)
        (_, outs), _ = jax.lax.scan(
            tick, (buf0, outs0), jnp.arange(n_micro + pp - 1)
        )
        # Finished activations live on the last stage only; psum replicates
        # them (every other stage contributes zeros).
        return jax.lax.psum(jnp.where(idx == last, outs, 0.0), "pp")

    hidden = run(params_pp["layers"], x).reshape(B, S, cfg.hidden)
    return _logits(params_pp, cfg, hidden)


def make_pp_train_step(cfg: ModelConfig, mesh: Mesh, optimizer, n_micro: int):
    """Jitted ``step((params_pp, opt_state), tokens) -> (state, loss)``
    training through the pipeline — reverse-mode AD runs the schedule
    backwards (ppermute transposes to the opposite shift)."""

    def loss_fn(params_pp, tokens):
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = pipeline_forward(params_pp, cfg, inputs, mesh, n_micro)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return nll.mean()

    @jax.jit
    def step(state, tokens):
        params_pp, opt_state = state
        loss, grads = jax.value_and_grad(loss_fn)(params_pp, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params_pp)
        import optax

        params_pp = optax.apply_updates(params_pp, updates)
        return (params_pp, opt_state), loss

    return step
