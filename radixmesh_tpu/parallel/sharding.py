"""Device mesh + logical-axis sharding rules.

TPU-first design: the model code names every parameter axis *logically*
(``models/llama.py:param_logical_axes`` — "embed", "q_heads", "kv_heads",
"ffn", "vocab", "layer"); this module maps those names onto physical mesh
axes and produces ``NamedSharding`` pytrees for pjit. XLA then inserts all
collectives (psum for TP matmul reductions, all-gathers for sp attention)
— nothing here hand-schedules communication, per the scaling-book recipe:
pick a mesh, annotate shardings, let XLA insert collectives.

Mesh axes:

- ``dp`` — data parallel: batch split; params replicated; grad psum.
- ``sp`` — sequence parallel: prefill/train activations split along the
  sequence axis (long-context prefill; ring attention in
  ``parallel/ring_attention.py`` rides this same axis).
- ``tp`` — tensor parallel: attention heads + FFN hidden split (Megatron
  layout: column-parallel wq/wk/wv/w_gate/w_up, row-parallel wo/w_down,
  so each transformer block needs exactly two psums, inserted by XLA).

No EP axis: both target model families (Llama-3, Qwen2 — ``BASELINE.json``
"configs") are dense, per SURVEY §2's parallelism checklist.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "LOGICAL_RULES",
    "MeshPlan",
    "make_mesh",
    "logical_to_spec",
    "param_sharding",
    "shard_params",
    "batch_sharding",
]

# Logical axis name -> mesh axis (None = replicated along that axis).
# "layer" stays unsharded: layers are consumed by lax.scan; a pipeline
# ("pp") layout would instead split the scan into per-stage scans.
LOGICAL_RULES: dict[str, str | None] = {
    "vocab": "tp",
    "q_heads": "tp",
    "kv_heads": "tp",
    "ffn": "tp",
    "embed": None,
    "layer": None,
}


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Factorization of the device count over (dp, sp, tp)."""

    dp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.sp * self.tp

    @classmethod
    def auto(cls, n_devices: int, max_tp: int = 4) -> "MeshPlan":
        """Default factorization: favor tp (ICI-local, most bandwidth-
        hungry), then sp, then dp — e.g. 8 -> (dp=1, sp=2, tp=4),
        4 -> (1, 1, 4), 16 -> (2, 2, 4).

        ``max_tp`` caps head sharding (kv heads must stay divisible; Llama-3
        has 8 kv heads -> raise to 8 for it). Deployments pass an explicit
        plan; auto exists so the dryrun exercises every axis."""
        tp = math.gcd(n_devices, max_tp)
        rest = n_devices // tp
        sp = 2 if rest % 2 == 0 else 1
        dp = rest // sp
        return cls(dp=dp, sp=sp, tp=tp)


def make_mesh(plan: MeshPlan | None = None, devices: list | None = None) -> Mesh:
    """Build a ``(dp, sp, tp)`` Mesh. With no plan, factorize all visible
    devices. tp is placed on the innermost (fastest-wraparound ICI) axis."""
    devices = devices if devices is not None else jax.devices()
    if plan is None:
        plan = MeshPlan.auto(len(devices))
    if plan.n_devices > len(devices):
        raise ValueError(
            f"mesh plan {plan} needs {plan.n_devices} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[: plan.n_devices]).reshape(plan.dp, plan.sp, plan.tp)
    return Mesh(arr, axis_names=("dp", "sp", "tp"))


def logical_to_spec(axes: tuple) -> P:
    """("layer","embed","q_heads") -> PartitionSpec(None, None, "tp")."""
    return P(*(LOGICAL_RULES.get(name) for name in axes))


def param_sharding(logical_axes: Any, mesh: Mesh) -> Any:
    """Map a pytree of logical-axis tuples (``param_logical_axes(cfg)``)
    to a matching pytree of ``NamedSharding``."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_spec(axes)),
        logical_axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_params(params: Any, logical_axes: Any, mesh: Mesh) -> Any:
    """Place an (unsharded) param pytree onto the mesh."""
    return jax.device_put(params, param_sharding(logical_axes, mesh))


def batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Token batches [B, S, ...]: batch over dp, sequence over sp."""
    return NamedSharding(mesh, P("dp", "sp", *([None] * (ndim - 2))))
