"""Multi-host compute initialization: ``jax.distributed`` glue.

SURVEY §5 requires a distributed communication backend that "scales to
multi-host the way the reference's NCCL/MPI backend" was meant to. The
cache/control plane already rides the C++ DCN transport (``comm/``); this
module is the COMPUTE plane's counterpart: one ``jax.distributed`` process
per host, all chips joined into one global device mesh, XLA emitting the
cross-host collectives (ICI within a slice, DCN across slices) from the
same ``pjit``/``shard_map`` programs used single-host — no NCCL/MPI port,
by design.

On TPU pods the runtime discovers the topology; on CPU (tests, localhost
rehearsal) collectives ride Gloo, so the same multi-process program is
testable anywhere — the reference's multi-node-without-a-cluster strategy
(``correctness.py:22-29``) applied to the compute plane.
"""

from __future__ import annotations

from dataclasses import dataclass

from radixmesh_tpu.utils.platform import pin_platform

__all__ = ["MultihostInfo", "init_multihost", "global_mesh"]


@dataclass(frozen=True)
class MultihostInfo:
    process_index: int
    process_count: int
    local_devices: int
    global_devices: int


def init_multihost(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_device_count: int | None = None,
) -> MultihostInfo:
    """Join this process into the ``jax.distributed`` job and return the
    topology. Call before ANY other jax API touches a backend.

    ``local_device_count`` forces a virtual CPU device count per process
    (rehearsal mode); on real TPU hosts leave it ``None`` and the runtime
    reports the chips attached to this host.
    """
    import os
    import re

    if local_device_count is not None:
        # Override (not merely append) any inherited device-count flag:
        # every process of the job must agree on its local device count,
        # and a stale shell export silently breaking that is worse than
        # clobbering it.
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{local_device_count}"
        ).strip()
    pin_platform()
    import jax

    try:
        plat = jax.config.read("jax_platforms")
    except Exception:  # noqa: BLE001 — config name drift across jax versions
        plat = os.environ.get("JAX_PLATFORMS")
    if plat and "cpu" in str(plat):
        # CPU processes have no ICI; collectives ride Gloo over TCP.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return MultihostInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_devices=len(jax.local_devices()),
        global_devices=len(jax.devices()),
    )


def global_mesh(plan=None):
    """A device mesh over EVERY process's chips. The default plan keeps
    the process (host) boundary on the dp axis: sp/tp factorize ONE
    host's chips (per-layer, latency-sensitive collectives stay on
    intra-host ICI) and dp multiplies across hosts (gradient/batch
    reductions amortize over DCN). ``jax.devices()`` lists devices
    process-contiguously and dp is the outermost mesh axis, so the
    reshape lands each host's chips in their own dp rows."""
    import jax

    from radixmesh_tpu.parallel.sharding import MeshPlan, make_mesh

    if plan is None:
        local = MeshPlan.auto(len(jax.local_devices()))
        plan = MeshPlan(
            dp=jax.process_count() * local.dp, sp=local.sp, tp=local.tp
        )
    return make_mesh(plan, devices=jax.devices())
