"""Intra-slice KV-page movement over ICI via XLA collectives.

The DCN bytes path (``engine/disagg.py``) is right across slices/pods; when
the prefill and decode shards live on ONE TPU slice, the page block should
ride the ICI mesh instead of bouncing through host RAM. SURVEY §5
"distributed communication backend" calls for exactly this split:
``collective_permute``/all-gather over ICI intra-slice, the framed
transport over DCN across.

Design: prefill and decode replicas are ranks along one mesh axis (e.g. the
``dp`` axis carries `P` prefill shards then `D` decode shards). A handoff is
a static source→destination rank map; the page block ``[n_pages, page,
Hkv, D]`` moves with one ``ppermute`` — XLA overlaps it with whatever
compute is in flight, and nothing touches the host.

Shapes must be static under jit, so transfers move fixed-size page batches
(SURVEY §7 hard part (b)): callers round a prompt's pages up to
``n_pages`` and ignore the tail, exactly like the engine's power-of-two
prefill buckets.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["make_kv_page_transfer", "prefill_to_decode_perm"]


def prefill_to_decode_perm(
    n_prefill: int, n_decode: int
) -> list[tuple[int, int]]:
    """Source→destination rank pairs sending each prefill rank's block to a
    decode rank. Ranks follow the reference's global rank space: prefill
    ``[0, P)`` then decode ``[P, P+D)`` (``config/cache_config.py:20-28``).

    Requires ``n_prefill <= n_decode``: one ``ppermute`` needs unique
    destinations, and a destination buffer can hold one source block. With
    more prefill than decode ranks, issue one transfer per round of
    ``n_decode`` senders instead (each round is a valid injective map)."""
    if n_prefill <= 0 or n_decode <= 0:
        raise ValueError("need at least one prefill and one decode rank")
    if n_prefill > n_decode:
        raise ValueError(
            f"{n_prefill} prefill ranks cannot hand off to {n_decode} decode "
            "ranks in one transfer (destinations must be unique); batch the "
            "handoff into ceil(P/D) rounds"
        )
    return [(i, n_prefill + i) for i in range(n_prefill)]


def make_kv_page_transfer(
    mesh: Mesh,
    axis_name: str,
    perm: list[tuple[int, int]],
):
    """Returns a jitted ``transfer(block)``: ``block`` is sharded over
    ``axis_name`` (one page batch per rank); each source rank's shard lands
    on its destination rank. Ranks that are not a destination keep zeros —
    the caller's page table decides what is live, so junk pages are never
    referenced (same discipline as the engine's scratch page)."""

    def shard_fn(x):
        return jax.lax.ppermute(x, axis_name, perm)

    spec = P(axis_name)
    return jax.jit(
        jax.shard_map(shard_fn, mesh=mesh, in_specs=spec, out_specs=spec)
    )
