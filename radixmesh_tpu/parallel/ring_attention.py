"""Ring attention: causal self-attention over a sequence-sharded batch.

The long-context path SURVEY §5 requires ("absent" in the reference; needed
for the Qwen2-72B 32k config, ``BASELINE.json``): when a sequence doesn't
fit one chip's HBM, shard it over the ``sp`` mesh axis and rotate K/V
blocks around the ring with ``ppermute`` while every chip keeps only its
own query block — HBM per chip is O(S/n), compute stays MXU-dense, and the
K/V block transfer for step ``i+1`` overlaps step ``i``'s matmuls (XLA
schedules the collective-permute concurrently with compute since neither
depends on the other inside the loop body).

Blockwise-causal masking: query block ``i`` attends fully to earlier
blocks, triangularly to itself, not at all to later blocks; the online
softmax (running max / sum / accumulator, fp32) makes the blockwise result
exact, not approximate.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "ring_self_attention"]

_NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,  # [B, C, Hq, D] local query block
    k: jnp.ndarray,  # [B, C, Hkv, D] local key block
    v: jnp.ndarray,  # [B, C, Hkv, D]
    axis_name: str,
) -> jnp.ndarray:
    """Per-shard body — call INSIDE ``shard_map`` with the sequence axis
    sharded over ``axis_name``. Returns the local output block [B, C, Hq, D].
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, c, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv

    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qg = (q.astype(jnp.float32) * scale).reshape(b, c, hkv, g, d)
    q_pos = idx * c + jnp.arange(c)  # global positions of local queries

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry):
        m, l, acc, k_cur, v_cur = carry
        j = (idx - step) % n  # which block we currently hold
        kv_pos = j * c + jnp.arange(c)
        # [b, hkv, g, cq, ck] scores.
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk",
            qg,
            k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        mask = q_pos[:, None] >= kv_pos[None, :]  # [cq, ck] causal
        s = jnp.where(mask[None, None, None], s, _NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bhgqk,bkhd->bhgqd",
            p,
            v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr[..., None] + pv
        # Rotate K/V around the ring; the permute overlaps next-step math.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, acc_new, k_nxt, v_nxt

    m0 = jnp.full((b, hkv, g, c), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, hkv, g, c), jnp.float32)
    acc0 = jnp.zeros((b, hkv, g, c, d), jnp.float32)
    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, acc0, k, v))
    # Fully-masked rows (can't happen for causal self-attention, but keep
    # the math total): avoid 0/0.
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    # [b, hkv, g, c, d] -> [b, c, hq, d]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, c, hq, d).astype(q.dtype)


def ring_self_attention(
    q: jnp.ndarray,  # [B, S, Hq, D] full (logically sharded) sequence
    k: jnp.ndarray,  # [B, S, Hkv, D]
    v: jnp.ndarray,  # [B, S, Hkv, D]
    mesh: Mesh,
    axis: str = "sp",
) -> jnp.ndarray:
    """Top-level convenience: shard the sequence dim over ``mesh[axis]``
    and run ring attention; heads stay whole (compose with tp by sharding
    the head dim of the inputs before calling)."""
    spec = P(None, axis)
    fn = jax.shard_map(
        partial(ring_attention, axis_name=axis),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
