"""Int8 KV-cache quantization helpers.

Decode attention is HBM-bandwidth-bound: each step streams the whole
context's K/V per layer, so storing the pool in int8 with per-token,
per-head scales halves that traffic (SURVEY §6: HBM bandwidth is the
usual TPU bottleneck). The reference has no analogue — its "KV" is only
index tensors (``radix_mesh.py:23``) — this is a TPU-first extension of
the pool the same way the Pallas kernels are.

Scheme: symmetric per-(token, head) scaling over the head_dim axis —
``scale = amax/127``, ``q = round(x/scale)`` — the granularity published
int8-KV work uses to keep quality: one outlier token never inflates its
neighbours' quantization step. Dequantization folds into attention as
vector math (scores scale by ``k_scale``, probabilities by ``v_scale``
before the PV contraction), so the int8 tiles feed the MXU directly and
no dequantized copy is ever materialized.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "quantize_kv",
    "dequantize_kv",
    "quantize_for_store",
    "KV_QUANT_DTYPES",
]

KV_QUANT_DTYPES = {"int8": jnp.int8}

# Zero vectors quantize against this floor instead of dividing by zero;
# their int8 payload is all-zero either way.
_EPS = 1e-8


def quantize_kv(x: jnp.ndarray, axis: int = -1):
    """Symmetric int8 quantization along ``axis`` (the head_dim axis).

    Returns ``(q, scale)`` with ``q`` int8 shaped like ``x`` and ``scale``
    float32 shaped like ``x`` minus ``axis``; ``x ≈ q * scale``.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis)
    scale = jnp.maximum(amax, _EPS) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / jnp.expand_dims(scale, axis)),
        -127,
        127,
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, axis: int = -1):
    """Inverse of :func:`quantize_kv` (f32)."""
    return q.astype(jnp.float32) * jnp.expand_dims(scale, axis)


def quantize_for_store(k: jnp.ndarray, v: jnp.ndarray):
    """The see-what-you-store step every quantized producer shares: new
    K/V is quantized NOW and the layer attends the DEQUANTIZED copy, so
    logits are identical between this pass and any later pool read (a
    speculative verify, a prefix hit, a plain decode). One implementation
    — the single-chip chunk path and both pipeline paths call it — so the
    invariant cannot drift per call site.

    Returns ``(k_int, v_int, k_scale, v_scale, k_deq, v_deq)``.
    """
    k_int, k_sc = quantize_kv(k, axis=-1)
    v_int, v_sc = quantize_kv(v, axis=-1)
    return (
        k_int, v_int, k_sc, v_sc,
        dequantize_kv(k_int, k_sc), dequantize_kv(v_int, v_sc),
    )
