"""Pallas TPU decode kernels: attention over non-contiguous radix-cache pages.

This is the op SURVEY §7 calls the hard part (a): the radix cache hands the
scheduler a *page table* (page ids into the paged KV pool, arbitrary order,
shared across requests that share a prefix), and decode attention must
gather those pages without materializing a dense [B, max_ctx, H, D] copy in
HBM — the copy is exactly the bandwidth decode can't afford.

Design (grid = (B, Hkv), one program per sequence × kv-head):

- The KV pool pages stay in HBM (``memory_space=ANY``); the page table,
  sequence lengths, and layer index ride scalar prefetch (SMEM) so DMA
  source addresses are computable before the body runs.
- Each program loops over *compute blocks* of ``pages_per_block`` pages
  (a few hundred tokens per block), bounded by the sequence's true length
  — short sequences cost short loops, not ``max_pages`` iterations.
- Block DMAs are **chain-prefetched across grid steps**: while block ``i``
  of program ``(b, h)`` is being contracted on the MXU, the copy for the
  *next* block — which may belong to the next head or the next sequence —
  is already in flight in the other half of a double buffer. DMA latency
  is exposed once per kernel launch, not once per program.
- Online softmax (running max / sum / fp32 accumulator in VMEM scratch)
  across the block loop; GQA by blocking the query as [G, D] per kv head.

Two entry points share the block loop (``_run_block_loop``):

- ``paged_attention_pool_kernel`` — read-only attention over ``length``
  tokens already resident in pool pages.
- ``paged_decode_fused_kernel`` — the decode hot path: ALSO writes the
  current token's K/V row into the pool through an **aliased** output
  (``input_output_aliases``), so the pool buffer flows through the layer
  scan with zero copies. The freshly written row is never read back from
  HBM within the call: HBM blocks are masked to ``length - 1`` and the
  current token's contribution is folded in from VMEM — which also kills
  the read-after-write hazard with cross-program block prefetch.

The jnp oracle is ``ops/attention.py::attend_decode_ref``; numerics are
compared in ``tests/test_ops.py`` (interpreter mode on CPU) and on real TPU
by ``bench.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "paged_attention_kernel",
    "paged_attention_pool_kernel",
    "paged_chunk_attention_kernel",
    "paged_decode_fused_kernel",
]

# exp(finite - MASK) == 0 without the NaN risk of -inf - -inf.
_MASK = -0.7 * float(np.finfo(np.float32).max)


class _BlockCopy:
    """Async HBM→VMEM gather of one compute block: ``n_pages`` non-contiguous
    [page, D] tiles of one kv head copied into a contiguous VMEM buffer."""

    def __init__(self, kv_hbm, which, layer, head, buf, sem, page_table_ref,
                 flat_offset, n_pages):
        src = kv_hbm.at[which, layer, head]
        self._copies = [
            pltpu.make_async_copy(
                src.at[page_table_ref[flat_offset + i]], buf.at[i], sem
            )
            for i in range(n_pages)
        ]

    def start(self):
        for c in self._copies:
            c.start()

    def wait(self):
        for c in self._copies:
            c.wait()


def _rpp(page: int) -> int:
    """Pages per 128-slot scale row (quantized kernels require the page
    size to divide 128 so scale rows tile exactly)."""
    if 128 % page:
        raise ValueError(
            f"int8 paged kernels need a page_size dividing 128, got {page}"
        )
    return 128 // page


def _scale_rows(kv_scales: jnp.ndarray) -> jnp.ndarray:
    """Per-token scales ``[2, L, Hkv, P, page]`` → rows of 128 consecutive
    SLOTS ``[2, L, Hkv, R, 128]`` (a pure reshape when the slot count is a
    multiple of 128, else a zero pad).

    Real-Mosaic constraint, found the first time the int8 kernels met a
    chip: HBM DMA slices must move whole 128-lane rows — the paged
    ``[..., page]`` view's 16-wide minor dim is tiling-misaligned and
    un-DMA-able ("Slice shape along dimension 4 must be aligned to tiling
    (128)"), and a ``(ppb, page) → (bk,)`` staging reshape inside the
    kernel is an unsupported lane-expanding shape cast. Interpret mode
    and StableHLO-level AOT lowering both accept either, which is why
    only on-chip compilation could surface this."""
    two, L, Hkv = kv_scales.shape[:3]
    flat = kv_scales.reshape(two, L, Hkv, -1)
    S = flat.shape[-1]
    R = -(-S // 128)
    if R * 128 != S:
        flat = jnp.pad(flat, ((0, 0), (0, 0), (0, 0), (0, R * 128 - S)))
    return flat.reshape(two, L, Hkv, R, 128)


class _ScaleCopy:
    """Async HBM→VMEM fetch of the 128-slot scale ROW containing one
    page's per-token scales (see ``_scale_rows``). Page ``i`` of a block
    stages its whole row; ``_lane_scales`` then compacts the staged rows
    into the ``(1, bk)`` per-token lane vector with dynamic lane
    rotations — every transfer and vector op stays 128-lane-aligned."""

    def __init__(self, scale_rows, which, layer, head, buf, sem,
                 page_table_ref, flat_offset, n_pages, page):
        src = scale_rows.at[which, layer, head]
        rpp = 128 // page
        self._copies = [
            pltpu.make_async_copy(
                src.at[pl.ds(page_table_ref[flat_offset + i] // rpp, 1)],
                buf.at[pl.ds(i, 1)],
                sem,
            )
            for i in range(n_pages)
        ]

    def start(self):
        for c in self._copies:
            c.start()

    def wait(self):
        for c in self._copies:
            c.wait()


def _lane_scales(rows, page_table_ref, off, page: int, ppb: int):
    """``(1, ppb·page)`` per-token scale lane vector from the staged
    128-slot rows (one per block page, ``_ScaleCopy``). All vector ops
    are ``(1, 128)``-shaped: row extraction is a static sublane slice,
    placement is a dynamic lane rotation + iota select — Mosaic has no
    lane-granular slicing, no lane-expanding reshape, and rejects 1-D
    dynamic rotates, so this is the shape everything must stay in."""
    rpp = 128 // page
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    chunks = []
    for c in range(ppb // rpp):
        acc = jnp.zeros((1, 128), jnp.float32)
        for j in range(rpp):
            i = c * rpp + j
            pid = page_table_ref[off + i]
            src_off = jax.lax.rem(pid, rpp) * page
            dst = j * page
            r = jax.lax.slice_in_dim(rows, i, i + 1, axis=0)  # (1, 128)
            r = pltpu.roll(r, jnp.mod(dst - src_off, 128), 1)
            acc = jnp.where((lane >= dst) & (lane < dst + page), r, acc)
        chunks.append(acc)
    return chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks, axis=1)


def _run_block_loop(
    *,
    b,
    h,
    layer,
    hbm_len,  # tokens resident in HBM pages for THIS program's sequence
    q,  # [G, D] fp32, pre-scaled
    lengths_ref,
    page_table_ref,
    buffer_index_ref,
    init_flag_ref,
    kv_hbm,
    k_buf,
    v_buf,
    sems,
    m_scr,
    l_scr,
    acc_scr,
    page: int,
    pages_per_block: int,
    pages_per_seq: int,
    batch_size: int,
    num_kv_heads: int,
    min_length: int,  # lengths_ref value below which a row has no HBM work
    scales_hbm=None,  # ANY [2, L, Hkv, R, 128] — int8 scale ROWS (_scale_rows)
    ks_buf=None,  # VMEM [2, ppb, 128] f32 staged rows (see _ScaleCopy)
    vs_buf=None,
    s_sems=None,  # DMA [2, 2]
):
    """Initialize the online-softmax scratch and contract ``hbm_len``
    tokens of HBM pages into it, chain-prefetching block DMAs across grid
    programs. Shared by the read-only and fused kernels (their only
    difference here is how many trailing tokens live outside HBM:
    ``min_length`` is 1 / 2 respectively). With ``scales_hbm`` the pages
    are int8 and dequantization folds into the contractions: scores scale
    by the per-token k-scale, probabilities by the v-scale — the int8
    tiles feed the MXU directly, halving the block DMA bytes."""
    bk = page * pages_per_block
    quantized = scales_hbm is not None

    def block_copies(bb, hh, ii, slot):
        off = bb * pages_per_seq + ii * pages_per_block
        copies = [
            _BlockCopy(kv_hbm, 0, layer, hh, k_buf.at[slot], sems.at[slot, 0],
                       page_table_ref, off, pages_per_block),
            _BlockCopy(kv_hbm, 1, layer, hh, v_buf.at[slot], sems.at[slot, 1],
                       page_table_ref, off, pages_per_block),
        ]
        if quantized:
            copies.append(
                _ScaleCopy(scales_hbm, 0, layer, hh, ks_buf.at[slot],
                           s_sems.at[slot, 0], page_table_ref, off,
                           pages_per_block, page)
            )
            copies.append(
                _ScaleCopy(scales_hbm, 1, layer, hh, vs_buf.at[slot],
                           s_sems.at[slot, 1], page_table_ref, off,
                           pages_per_block, page)
            )
        return copies

    def next_indices(i):
        """Grid-order successor of block ``i`` of this (b, h) program,
        skipping sequences with no HBM work."""

        def advance_b():
            nb = jax.lax.fori_loop(
                b + 1,
                batch_size,
                lambda _, x: jnp.where(
                    jnp.logical_and(
                        x < batch_size,
                        lengths_ref[jax.lax.clamp(0, x, batch_size - 1)]
                        < min_length,
                    ),
                    x + 1,
                    x,
                ),
                b + 1,
            )
            return (nb, 0, 0)

        def advance_h():
            return jax.lax.cond(
                h + 1 < num_kv_heads, lambda: (b, h + 1, 0), advance_b
            )

        return jax.lax.cond(i * bk < hbm_len, lambda: (b, h, i), advance_h)

    m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)

    def body(i, _):
        init_flag = init_flag_ref[0]
        init_flag_ref[0] = 0
        slot = buffer_index_ref[0]
        nb, nh, ni = next_indices(i + 1)

        @pl.when(init_flag)
        def _cold_start():
            for c in block_copies(b, h, i, slot):
                c.start()

        @pl.when(nb < batch_size)
        def _prefetch_next():
            nslot = jnp.where(slot == 0, 1, 0)
            for c in block_copies(nb, nh, ni, nslot):
                c.start()
            buffer_index_ref[0] = nslot

        cs = block_copies(b, h, i, slot)
        cs[0].wait()
        if quantized:
            cs[2].wait()
        k = k_buf[slot].astype(jnp.float32).reshape(bk, -1)  # [bk, D]
        s = jax.lax.dot_general(  # [G, bk]
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if quantized:
            soff = b * pages_per_seq + i * pages_per_block
            s = s * _lane_scales(
                ks_buf[slot], page_table_ref, soff, page, pages_per_block
            )
        pos = i * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < hbm_len, s, _MASK)

        m_prev = m_scr[...]
        m_blk = jnp.max(s, axis=-1, keepdims=True)  # [G, 1]
        m_new = jnp.maximum(m_prev, m_blk)  # lane-replicated [G, D]
        p = jnp.exp(s - m_new[:, :1])  # [G, bk]
        corr = jnp.exp(m_prev - m_new)
        l_blk = jnp.sum(p, axis=-1, keepdims=True)
        l_scr[...] = l_scr[...] * corr + l_blk
        m_scr[...] = m_new

        cs[1].wait()
        if quantized:
            cs[3].wait()
            p = p * _lane_scales(
                vs_buf[slot], page_table_ref, soff, page, pages_per_block
            )
        v = v_buf[slot].astype(jnp.float32).reshape(bk, -1)  # [bk, D]
        pv = jax.lax.dot_general(  # [G, D]
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * corr + pv
        return ()

    jax.lax.fori_loop(0, pl.cdiv(hbm_len, bk), body, ())


def _kernel(
    # scalar prefetch
    lengths_ref,  # SMEM [B]
    page_table_ref,  # SMEM [B * blocks_padded * ppb] flattened
    layer_ref,  # SMEM [1] — which layer's pages to read
    buffer_index_ref,  # SMEM [1] — double-buffer slot, persists across programs
    init_flag_ref,  # SMEM [1] — 1 until the very first program cold-starts
    # then: inputs (q_ref, kv_hbm[, scales_hbm]), outputs (o_ref) and
    # scratch — the quantized variant inserts the scale pool input and the
    # scale staging buffers, so the tail is unpacked by flag.
    *refs,
    page: int,
    pages_per_block: int,
    pages_per_seq: int,
    batch_size: int,
    num_kv_heads: int,
    quantized: bool,
):
    if quantized:
        (q_ref, kv_hbm, scales_hbm, o_ref,
         m_scr, l_scr, acc_scr, k_buf, v_buf, ks_buf, vs_buf, sems,
         s_sems) = refs
    else:
        q_ref, kv_hbm, o_ref, m_scr, l_scr, acc_scr, k_buf, v_buf, sems = refs
        scales_hbm = ks_buf = vs_buf = s_sems = None
    b, h = pl.program_id(0), pl.program_id(1)
    layer = layer_ref[0]
    length = lengths_ref[b]

    # Rows with no work still get a deterministic (zero) output — never
    # whatever happened to be resident in VMEM.
    o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when(length > 0)
    def _program():
        q = q_ref[...].astype(jnp.float32)  # pre-scaled by the wrapper
        _run_block_loop(
            b=b, h=h, layer=layer, hbm_len=length, q=q,
            lengths_ref=lengths_ref, page_table_ref=page_table_ref,
            buffer_index_ref=buffer_index_ref, init_flag_ref=init_flag_ref,
            kv_hbm=kv_hbm, k_buf=k_buf, v_buf=v_buf, sems=sems,
            m_scr=m_scr, l_scr=l_scr, acc_scr=acc_scr,
            page=page, pages_per_block=pages_per_block,
            pages_per_seq=pages_per_seq, batch_size=batch_size,
            num_kv_heads=num_kv_heads, min_length=1,
            scales_hbm=scales_hbm, ks_buf=ks_buf, vs_buf=vs_buf,
            s_sems=s_sems,
        )
        o_ref[...] = (acc_scr[...] / l_scr[...]).astype(o_ref.dtype)


class _MhBlockCopy:
    """Async HBM→VMEM gather of one compute block with ALL kv heads per
    DMA: each page copy moves the strided ``(Hkv, page, D)`` slab instead
    of one head's ``(page, D)`` tile. The per-head-program kernel issues
    ``B × Hkv × blocks × ppb × 2`` small DMAs per launch — on-chip that
    issue count, not bytes, bounds decode attention (23% HBM utilization
    measured at the headline shape); fetching all heads per descriptor
    divides it by ``Hkv``."""

    def __init__(self, kv_hbm, which, layer, buf, sem, page_table_ref,
                 flat_offset, n_pages):
        src = kv_hbm.at[which, layer]  # [Hkv, P, page, D]
        self._copies = [
            pltpu.make_async_copy(
                src.at[:, page_table_ref[flat_offset + i]],  # (Hkv, page, D)
                buf.at[:, i],
                sem,
            )
            for i in range(n_pages)
        ]

    def start(self):
        for c in self._copies:
            c.start()

    def wait(self):
        for c in self._copies:
            c.wait()


class _MhScaleCopy:
    """All-heads analog of ``_ScaleCopy``: one strided DMA per page moves
    the ``(Hkv, 1, 128)`` scale-row slab for every head."""

    def __init__(self, scale_rows, which, layer, buf, sem, page_table_ref,
                 flat_offset, n_pages, page):
        src = scale_rows.at[which, layer]  # [Hkv, R, 128]
        rpp = 128 // page
        self._copies = [
            pltpu.make_async_copy(
                src.at[:, pl.ds(page_table_ref[flat_offset + i] // rpp, 1)],
                buf.at[:, pl.ds(i, 1)],
                sem,
            )
            for i in range(n_pages)
        ]

    def start(self):
        for c in self._copies:
            c.start()

    def wait(self):
        for c in self._copies:
            c.wait()


def _mh_lane_scales(rows, page_table_ref, off, page: int, ppb: int):
    """``(Hkv, 1, ppb·page)`` per-token scales from staged all-heads rows
    ``(Hkv, ppb, 128)``. Identical rotation/select scheme to
    ``_lane_scales`` but vector shapes keep the head axis OUTER and the
    sliced axis in the MIDDLE — ``(Hkv, 1, 128)`` slices avoid every
    relayout class the single-head path had to dodge, and all heads
    share one rotation (their rows have identical lane offsets)."""
    rpp = 128 // page
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 128), 2)
    chunks = []
    for c in range(ppb // rpp):
        acc = None
        for j in range(rpp):
            i = c * rpp + j
            pid = page_table_ref[off + i]
            src_off = jax.lax.rem(pid, rpp) * page
            dst = j * page
            r = jax.lax.slice_in_dim(rows, i, i + 1, axis=1)  # (Hkv, 1, 128)
            r = pltpu.roll(r, jnp.mod(dst - src_off, 128), 2)
            sel = (lane >= dst) & (lane < dst + page)
            acc = jnp.where(sel, r, acc) if acc is not None else jnp.where(
                sel, r, 0.0
            )
        chunks.append(acc)
    return chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks, axis=2)


def _mh_block_loop(
    *,
    b,
    layer,
    hbm_len,  # tokens resident in HBM pages for THIS program's sequence
    q,  # (Hkv, G, D) f32, pre-scaled
    lengths_ref,
    page_table_ref,
    buffer_index_ref,
    init_flag_ref,
    kv_hbm,
    k_buf,
    v_buf,
    sems,
    m_scr,
    l_scr,
    acc_scr,
    page: int,
    pages_per_block: int,
    pages_per_seq: int,
    batch_size: int,
    num_kv_heads: int,
    min_length: int,  # lengths_ref value below which a row has no HBM work
    scales_hbm=None,  # ANY [2, L, Hkv, R, 128] rows (_scale_rows); int8 pools
    ks_buf=None,  # VMEM [2, Hkv, ppb, 128] f32 staged all-heads rows
    vs_buf=None,
    s_sems=None,  # DMA [2, 2]
):
    """The heads-batched analog of ``_run_block_loop``: one program per
    SEQUENCE, ``(Hkv, G, ·)`` batched MXU contractions, chain-prefetched
    ``_MhBlockCopy`` DMAs. Shared by the read-only and fused mh kernels
    (min_length 1 / 2, exactly like the per-head pair).

    DELIBERATE duplication of ``_run_block_loop``'s machinery (parity
    pinned by tests/test_ops.py::TestPoolKernelFusedHeads and
    TestFusedHeadsDecode): merging a head axis into the proven per-head
    path before the chip has judged this candidate would risk the
    production kernel for an experiment. If on-chip numbers keep it,
    fold both into one parameterized loop; if not, delete this. (The
    GQA group axis rides implicitly in ``q``'s shape.)"""
    bk = page * pages_per_block
    Hkv = num_kv_heads
    quantized = scales_hbm is not None

    def block_copies(bb, ii, slot):
        off = bb * pages_per_seq + ii * pages_per_block
        copies = [
            _MhBlockCopy(kv_hbm, 0, layer, k_buf.at[slot], sems.at[slot, 0],
                         page_table_ref, off, pages_per_block),
            _MhBlockCopy(kv_hbm, 1, layer, v_buf.at[slot], sems.at[slot, 1],
                         page_table_ref, off, pages_per_block),
        ]
        if quantized:
            copies.append(
                _MhScaleCopy(scales_hbm, 0, layer, ks_buf.at[slot],
                             s_sems.at[slot, 0], page_table_ref, off,
                             pages_per_block, page)
            )
            copies.append(
                _MhScaleCopy(scales_hbm, 1, layer, vs_buf.at[slot],
                             s_sems.at[slot, 1], page_table_ref, off,
                             pages_per_block, page)
            )
        return copies

    def next_indices(i):
        """Grid-order successor of block ``i`` of program ``b``, skipping
        sequences with no HBM work (mirrors ``_run_block_loop`` minus the
        head axis)."""

        def advance_b():
            nb = jax.lax.fori_loop(
                b + 1,
                batch_size,
                lambda _, x: jnp.where(
                    jnp.logical_and(
                        x < batch_size,
                        lengths_ref[jax.lax.clamp(0, x, batch_size - 1)]
                        < min_length,
                    ),
                    x + 1,
                    x,
                ),
                b + 1,
            )
            return (nb, 0)

        return jax.lax.cond(i * bk < hbm_len, lambda: (b, i), advance_b)

    m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)

    def body(i, _):
        init_flag = init_flag_ref[0]
        init_flag_ref[0] = 0
        slot = buffer_index_ref[0]
        nb, ni = next_indices(i + 1)

        @pl.when(init_flag)
        def _cold_start():
            for c in block_copies(b, i, slot):
                c.start()

        @pl.when(nb < batch_size)
        def _prefetch_next():
            nslot = jnp.where(slot == 0, 1, 0)
            for c in block_copies(nb, ni, nslot):
                c.start()
            buffer_index_ref[0] = nslot

        cs = block_copies(b, i, slot)
        cs[0].wait()
        if quantized:
            cs[2].wait()
        # (Hkv, ppb, page, D) → (Hkv, bk, D): middle collapse, minor
        # dim untouched — a supported relayout-free reshape.
        k = k_buf[slot].astype(jnp.float32).reshape(Hkv, bk, -1)
        s = jax.lax.dot_general(  # (Hkv, G, bk), heads-batched MXU
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        if quantized:
            soff = b * pages_per_seq + i * pages_per_block
            s = s * _mh_lane_scales(
                ks_buf[slot], page_table_ref, soff, page, pages_per_block
            )
        pos = i * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(pos < hbm_len, s, _MASK)

        m_prev = m_scr[...]
        m_blk = jnp.max(s, axis=-1, keepdims=True)  # (Hkv, G, 1)
        m_new = jnp.maximum(m_prev, m_blk)  # lane-replicated (Hkv, G, D)
        p = jnp.exp(s - m_new[:, :, :1])  # (Hkv, G, bk)
        corr = jnp.exp(m_prev - m_new)
        l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[...] = m_new

        cs[1].wait()
        if quantized:
            cs[3].wait()
            p = p * _mh_lane_scales(
                vs_buf[slot], page_table_ref, soff, page, pages_per_block
            )
        v = v_buf[slot].astype(jnp.float32).reshape(Hkv, bk, -1)
        pv = jax.lax.dot_general(  # (Hkv, G, D)
            p, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * corr + pv
        return ()

    jax.lax.fori_loop(0, pl.cdiv(hbm_len, bk), body, ())


def _mh_kernel(
    # scalar prefetch
    lengths_ref,  # SMEM [B]
    page_table_ref,  # SMEM [B * blocks_padded * ppb] flattened
    layer_ref,  # SMEM [1]
    buffer_index_ref,  # SMEM [1]
    init_flag_ref,  # SMEM [1]
    *refs,  # q_ref, kv_hbm[, scale_rows], o_ref, scratch — unpacked by flag
    page: int,
    pages_per_block: int,
    pages_per_seq: int,
    batch_size: int,
    num_kv_heads: int,
    group: int,
    quantized: bool,
):
    """Heads-fused read-only pool attention: grid ``(B,)`` (see
    ``_mh_block_loop``). Opt-in via ``fuse_heads=True`` until
    Mosaic-verified on hardware — the 3D batched-dot shapes are exactly
    the kind interpret mode and StableHLO AOT accept but real lowering
    may not (see _scale_rows)."""
    if quantized:
        (q_ref, kv_hbm, scales_hbm, o_ref,
         m_scr, l_scr, acc_scr, k_buf, v_buf, ks_buf, vs_buf, sems,
         s_sems) = refs
    else:
        q_ref, kv_hbm, o_ref, m_scr, l_scr, acc_scr, k_buf, v_buf, sems = refs
        scales_hbm = ks_buf = vs_buf = s_sems = None
    b = pl.program_id(0)
    layer = layer_ref[0]
    length = lengths_ref[b]
    Hkv, G = num_kv_heads, group

    o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when(length > 0)
    def _program():
        q = q_ref[...].astype(jnp.float32).reshape(Hkv, G, -1)  # pre-scaled
        _mh_block_loop(
            b=b, layer=layer, hbm_len=length, q=q,
            lengths_ref=lengths_ref, page_table_ref=page_table_ref,
            buffer_index_ref=buffer_index_ref, init_flag_ref=init_flag_ref,
            kv_hbm=kv_hbm, k_buf=k_buf, v_buf=v_buf, sems=sems,
            m_scr=m_scr, l_scr=l_scr, acc_scr=acc_scr,
            page=page, pages_per_block=pages_per_block,
            pages_per_seq=pages_per_seq, batch_size=batch_size,
            num_kv_heads=num_kv_heads, min_length=1,
            scales_hbm=scales_hbm, ks_buf=ks_buf, vs_buf=vs_buf,
            s_sems=s_sems,
        )
        out = acc_scr[...] / l_scr[...]
        o_ref[...] = out.reshape(Hkv * G, -1).astype(o_ref.dtype)


def _mh_fused_kernel(
    # scalar prefetch
    lengths_ref,  # SMEM [B] context length INCLUDING the current token
    page_table_ref,  # SMEM [B * blocks_padded * ppb] flattened
    slots_ref,  # SMEM [B] pool slot receiving this token's K/V
    layer_ref,  # SMEM [1]
    buffer_index_ref,  # SMEM [1]
    init_flag_ref,  # SMEM [1]
    *refs,
    page: int,
    pages_per_block: int,
    pages_per_seq: int,
    batch_size: int,
    num_kv_heads: int,
    group: int,
):
    """Heads-fused decode step: the ``_fused_kernel`` contract (write the
    current token's K/V row through the aliased pool output, fold it in
    from VMEM) at grid ``(B,)`` — the page-row RMW also moves all heads
    per DMA (2 reads + 2 writes per SEQUENCE instead of per (b, h))."""
    (q_ref, k_new_ref, v_new_ref, kv_hbm,
     kv_out, o_ref,
     m_scr, l_scr, acc_scr, k_buf, v_buf, row_scr, sems, w_sem) = refs
    b = pl.program_id(0)
    layer = layer_ref[0]
    length = lengths_ref[b]
    hbm_len = length - 1
    Hkv, G = num_kv_heads, group

    slot = slots_ref[b]
    pg, off = slot // page, slot % page

    def page_window(which):
        return kv_out.at[which, layer, :, pg]  # (Hkv, page, D) strided

    rk = pltpu.make_async_copy(page_window(0), row_scr.at[0], w_sem)
    rv = pltpu.make_async_copy(page_window(1), row_scr.at[1], w_sem)
    wk = pltpu.make_async_copy(row_scr.at[0], page_window(0), w_sem)
    wv = pltpu.make_async_copy(row_scr.at[1], page_window(1), w_sem)

    k_cur = k_new_ref[...].astype(jnp.float32)  # (Hkv, 1, D)
    v_cur = v_new_ref[...].astype(jnp.float32)

    o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when(length > 0)
    def _write():
        rk.start()
        rv.start()
        rk.wait()
        rv.wait()
        mask = jax.lax.broadcasted_iota(jnp.int32, row_scr.shape[1:], 1) == off
        row_scr[0] = jnp.where(
            mask, jnp.broadcast_to(k_new_ref[...], row_scr.shape[1:]), row_scr[0]
        )
        row_scr[1] = jnp.where(
            mask, jnp.broadcast_to(v_new_ref[...], row_scr.shape[1:]), row_scr[1]
        )
        wk.start()
        wv.start()

    @pl.when(length > 0)
    def _program():
        q = q_ref[...].astype(jnp.float32).reshape(Hkv, G, -1)  # pre-scaled
        _mh_block_loop(
            b=b, layer=layer, hbm_len=hbm_len, q=q,
            lengths_ref=lengths_ref, page_table_ref=page_table_ref,
            buffer_index_ref=buffer_index_ref, init_flag_ref=init_flag_ref,
            kv_hbm=kv_hbm, k_buf=k_buf, v_buf=v_buf, sems=sems,
            m_scr=m_scr, l_scr=l_scr, acc_scr=acc_scr,
            page=page, pages_per_block=pages_per_block,
            pages_per_seq=pages_per_seq, batch_size=batch_size,
            num_kv_heads=num_kv_heads, min_length=2,
        )
        s_cur = jax.lax.dot_general(  # (Hkv, G, 1)
            q, k_cur,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, s_cur)
        p_cur = jnp.exp(s_cur - m_new[:, :, :1])  # (Hkv, G, 1)
        corr = jnp.exp(m_prev - m_new)
        l_fin = l_scr[...] * corr + p_cur
        acc_fin = acc_scr[...] * corr + p_cur * v_cur
        out = acc_fin / l_fin
        o_ref[...] = out.reshape(Hkv * G, -1).astype(o_ref.dtype)
        wk.wait()
        wv.wait()


def _fused_kernel(
    # scalar prefetch
    lengths_ref,  # SMEM [B] context length INCLUDING the current token
    page_table_ref,  # SMEM [B * blocks_padded * ppb] flattened
    slots_ref,  # SMEM [B] pool slot receiving this token's K/V
    layer_ref,  # SMEM [1]
    buffer_index_ref,  # SMEM [1]
    init_flag_ref,  # SMEM [1]
    # then (quantized only): ksc_ref/vsc_ref — SMEM [B * Hkv] f32
    # per-(row, head) scales of the incoming token; then inputs
    # (q, k_new, v_new, kv_hbm[, scales_hbm]), outputs (kv_out, o_ref)
    # and scratch — unpacked by flag like ``_kernel``.
    *refs,
    page: int,
    pages_per_block: int,
    pages_per_seq: int,
    batch_size: int,
    num_kv_heads: int,
    quantized: bool,
):
    """Fused decode attention: write this token's K/V row into the pool
    (replacing the XLA scatter — the pool is aliased through the call, so
    the scan carry never copies) and attend over all ``length`` tokens,
    the current one folded in from VMEM (see module docstring). Quantized
    pools receive the row ALREADY quantized (the wrapper runs the same
    ``ops/quant.py`` quantizer) plus its per-(b, h) scale via scalar
    prefetch; the current token is folded in DEQUANTIZED, so the
    attention output matches exactly what any later read of the pool
    will see. The scale POOL is updated by the wrapper with one XLA
    scatter — an in-kernel scale-row RMW costs four extra serialized
    DMAs per program, which measured out to a 1.75x slowdown of the
    whole fused step on chip."""
    if quantized:
        (ksc_ref, vsc_ref,
         q_ref, k_new_ref, v_new_ref, kv_hbm, scales_hbm,
         kv_out, o_ref,
         m_scr, l_scr, acc_scr, k_buf, v_buf, ks_buf, vs_buf,
         row_scr, sems, s_sems, w_sem) = refs
    else:
        (q_ref, k_new_ref, v_new_ref, kv_hbm,
         kv_out, o_ref,
         m_scr, l_scr, acc_scr, k_buf, v_buf, row_scr, sems, w_sem) = refs
        scales_hbm = ks_buf = vs_buf = s_sems = None
    b, h = pl.program_id(0), pl.program_id(1)
    layer = layer_ref[0]
    length = lengths_ref[b]
    hbm_len = length - 1  # tokens resident in HBM pages

    slot = slots_ref[b]
    pg, off = slot // page, slot % page
    # Write through the ALIASED output ref (same HBM buffer as kv_hbm on
    # hardware; in interpret mode the alias is simulated by a copy, so
    # writing the input would be lost). Sublane tiling forbids partial
    # slices on the page axis, so read-modify-write the WHOLE page: every
    # other row (earlier, immutable tokens — or never-read future slots)
    # is rewritten byte-identical, so racing block reads are unaffected.
    def page_window(which):
        return kv_out.at[which, layer, h, pg]  # [page, D], full-dim slice

    rk = pltpu.make_async_copy(page_window(0), row_scr.at[0], w_sem)
    rv = pltpu.make_async_copy(page_window(1), row_scr.at[1], w_sem)
    wk = pltpu.make_async_copy(row_scr.at[0], page_window(0), w_sem)
    wv = pltpu.make_async_copy(row_scr.at[1], page_window(1), w_sem)

    # Current token, dequantized where the pool is int8 so attention sees
    # the pool's eventual contents bit-exactly.
    k_cur = k_new_ref[...].astype(jnp.float32)  # [1, D]
    v_cur = v_new_ref[...].astype(jnp.float32)
    if quantized:
        k_cur = k_cur * ksc_ref[b * num_kv_heads + h]
        v_cur = v_cur * vsc_ref[b * num_kv_heads + h]

    o_ref[...] = jnp.zeros_like(o_ref)  # deterministic for length==0 rows

    @pl.when(length > 0)
    def _write():
        rk.start()
        rv.start()
        rk.wait()
        rv.wait()
        mask = jax.lax.broadcasted_iota(jnp.int32, row_scr.shape[1:], 0) == off
        new_k_row = jnp.broadcast_to(k_new_ref[...], row_scr.shape[1:])
        new_v_row = jnp.broadcast_to(v_new_ref[...], row_scr.shape[1:])
        row_scr[0] = jnp.where(mask, new_k_row, row_scr[0])
        row_scr[1] = jnp.where(mask, new_v_row, row_scr[1])
        wk.start()
        wv.start()

    @pl.when(length > 0)
    def _program():
        q = q_ref[...].astype(jnp.float32)  # pre-scaled by the wrapper
        _run_block_loop(
            b=b, h=h, layer=layer, hbm_len=hbm_len, q=q,
            lengths_ref=lengths_ref, page_table_ref=page_table_ref,
            buffer_index_ref=buffer_index_ref, init_flag_ref=init_flag_ref,
            kv_hbm=kv_hbm, k_buf=k_buf, v_buf=v_buf, sems=sems,
            m_scr=m_scr, l_scr=l_scr, acc_scr=acc_scr,
            page=page, pages_per_block=pages_per_block,
            pages_per_seq=pages_per_seq, batch_size=batch_size,
            num_kv_heads=num_kv_heads, min_length=2,
            scales_hbm=scales_hbm, ks_buf=ks_buf, vs_buf=vs_buf,
            s_sems=s_sems,
        )
        # Fold in the current token from VMEM (one more online-softmax
        # step with a single-position block).
        s_cur = jax.lax.dot_general(  # [G, 1]
            q, k_cur,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, s_cur)
        p_cur = jnp.exp(s_cur - m_new[:, :1])  # [G, 1]
        corr = jnp.exp(m_prev - m_new)
        l_fin = l_scr[...] * corr + p_cur
        acc_fin = acc_scr[...] * corr + p_cur * v_cur
        o_ref[...] = (acc_fin / l_fin).astype(o_ref.dtype)
        wk.wait()
        wv.wait()


def _block_geometry(page_table, page: int, pages_per_block: int | None,
                    multiple: int = 1):
    """(padded page table, ppb): pad max_pages up to a block multiple.
    ``multiple`` rounds ppb up so a block is a whole number of scale
    rows (quantized kernels pass ``_rpp(page)``; the pad entries index
    page 0, whose reads are masked by the length bound like every other
    table pad)."""
    max_pages = page_table.shape[1]
    if pages_per_block is None:
        # ~256 tokens per compute block: large enough to amortize per-block
        # overhead, small enough that double-buffered K+V fits VMEM easily.
        pages_per_block = max(1, min(max_pages, -(-256 // page)))
    ppb = min(pages_per_block, max_pages)
    ppb = -(-ppb // multiple) * multiple
    blocks = -(-max_pages // ppb)
    padded = blocks * ppb
    if padded != max_pages:
        page_table = jnp.pad(page_table, ((0, 0), (0, padded - max_pages)))
    return page_table, ppb, padded


@functools.partial(
    jax.jit, static_argnames=("pages_per_block", "interpret", "fuse_heads")
)
def paged_attention_pool_kernel(
    q: jnp.ndarray,  # [B, Hq, D]
    kv_pages: jnp.ndarray,  # [2, L, Hkv, P, page, D] — full pool pages view
    page_table: jnp.ndarray,  # [B, max_pages] int32
    lengths: jnp.ndarray,  # [B] int32
    layer: jnp.ndarray | int,  # which layer's pages to attend over
    pages_per_block: int | None = None,
    interpret: bool = False,
    kv_scales: jnp.ndarray | None = None,  # [2, L, Hkv, P, page] (int8 pool)
    fuse_heads: bool = False,  # heads-batched variant (_mh_kernel); bf16 + int8
) -> jnp.ndarray:
    """Read-only entry: the whole (multi-layer) pool rides in HBM untouched
    and the kernel DMAs only ``layer``'s pages — so a scan-over-layers
    decode step costs O(context pages) HBM traffic per layer, never a
    materialized per-layer slice (which would be O(pool size)). With
    ``kv_scales`` the pool is int8 (page DMA bytes halve) and scales ride
    small per-page side copies (``[page]`` f32 rows)."""
    B, Hq, D = q.shape
    _, _, Hkv, _, page, _ = kv_pages.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must divide by Hkv={Hkv}")
    G = Hq // Hkv
    quantized = kv_scales is not None
    if fuse_heads:
        return _pool_kernel_mh(
            q, kv_pages, page_table, lengths, layer,
            pages_per_block=pages_per_block, interpret=interpret,
            kv_scales=kv_scales,
        )
    page_table, ppb, padded = _block_geometry(
        page_table, page, pages_per_block,
        multiple=_rpp(page) if quantized else 1,
    )

    scale = 1.0 / (D ** 0.5)
    # [B, Hq, 1, D] + a [G, D] f32 block: hints a <1x128>-friendly layout
    # for small GQA group sizes (G is often 1-4, far off the 8-sublane tile).
    q4 = (q.astype(jnp.float32) * scale).reshape(B, Hq, 1, D)
    q_spec = pl.BlockSpec((None, G, None, D), lambda b, h, *_: (b, h, 0, 0))

    kernel = functools.partial(
        _kernel,
        page=page,
        pages_per_block=ppb,
        pages_per_seq=padded,
        batch_size=B,
        num_kv_heads=Hkv,
        quantized=quantized,
    )
    in_specs = [q_spec, pl.BlockSpec(memory_space=pl.ANY)]
    scratch = [
        pltpu.VMEM((G, D), jnp.float32),
        pltpu.VMEM((G, D), jnp.float32),
        pltpu.VMEM((G, D), jnp.float32),
        pltpu.VMEM((2, ppb, page, D), kv_pages.dtype),
        pltpu.VMEM((2, ppb, page, D), kv_pages.dtype),
    ]
    if quantized:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        scratch += [
            pltpu.VMEM((2, ppb, 128), jnp.float32),
            pltpu.VMEM((2, ppb, 128), jnp.float32),
        ]
    scratch.append(pltpu.SemaphoreType.DMA((2, 2)))
    if quantized:
        scratch.append(pltpu.SemaphoreType.DMA((2, 2)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(B, Hkv),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=scratch,
    )
    args = [
        jnp.asarray(lengths, dtype=jnp.int32),
        jnp.asarray(page_table, dtype=jnp.int32).reshape(-1),
        jnp.asarray(layer, dtype=jnp.int32).reshape(1),
        jnp.zeros((1,), jnp.int32),  # double-buffer slot
        jnp.ones((1,), jnp.int32),  # cold-start flag
        q4,
        kv_pages,
    ]
    if quantized:
        args.append(_scale_rows(kv_scales))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, 1, D), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(*args)
    return out.reshape(B, Hq, D).astype(q.dtype)


def _pool_kernel_mh(
    q, kv_pages, page_table, lengths, layer,
    pages_per_block: int | None = None, interpret: bool = False,
    kv_scales=None,
):
    """Heads-batched pool attention wrapper (see ``_mh_kernel``). Smaller
    default blocks than the per-head kernel: each staged block is
    ``Hkv ×`` bigger, so bk=128 keeps the double buffers ≤ ~16 MB VMEM
    at Hkv=8/D=128 bf16."""
    B, Hq, D = q.shape
    _, _, Hkv, _, page, _ = kv_pages.shape
    G = Hq // Hkv
    quantized = kv_scales is not None
    if pages_per_block is None:
        pages_per_block = max(1, -(-128 // page))
    page_table, ppb, padded = _block_geometry(
        page_table, page, pages_per_block,
        multiple=_rpp(page) if quantized else 1,
    )

    scale = 1.0 / (D ** 0.5)
    q4 = (q.astype(jnp.float32) * scale).reshape(B, Hq, 1, D)
    q_spec = pl.BlockSpec((None, Hq, None, D), lambda b, *_: (b, 0, 0, 0))

    kernel = functools.partial(
        _mh_kernel,
        page=page,
        pages_per_block=ppb,
        pages_per_seq=padded,
        batch_size=B,
        num_kv_heads=Hkv,
        group=G,
        quantized=quantized,
    )
    in_specs = [q_spec, pl.BlockSpec(memory_space=pl.ANY)]
    scratch = [
        pltpu.VMEM((Hkv, G, D), jnp.float32),
        pltpu.VMEM((Hkv, G, D), jnp.float32),
        pltpu.VMEM((Hkv, G, D), jnp.float32),
        pltpu.VMEM((2, Hkv, ppb, page, D), kv_pages.dtype),
        pltpu.VMEM((2, Hkv, ppb, page, D), kv_pages.dtype),
    ]
    if quantized:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        scratch += [
            pltpu.VMEM((2, Hkv, ppb, 128), jnp.float32),
            pltpu.VMEM((2, Hkv, ppb, 128), jnp.float32),
        ]
    scratch.append(pltpu.SemaphoreType.DMA((2, 2)))
    if quantized:
        scratch.append(pltpu.SemaphoreType.DMA((2, 2)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(B,),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=scratch,
    )
    args = [
        jnp.asarray(lengths, dtype=jnp.int32),
        jnp.asarray(page_table, dtype=jnp.int32).reshape(-1),
        jnp.asarray(layer, dtype=jnp.int32).reshape(1),
        jnp.zeros((1,), jnp.int32),
        jnp.ones((1,), jnp.int32),
        q4,
        kv_pages,
    ]
    if quantized:
        args.append(_scale_rows(kv_scales))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, 1, D), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(*args)
    return out.reshape(B, Hq, D).astype(q.dtype)


def _fused_decode_mh(
    q, k_new, v_new, kv_pages, slots, page_table, lengths, layer,
    pages_per_block: int | None = None, interpret: bool = False,
):
    """Heads-batched fused decode wrapper (see ``_mh_fused_kernel``)."""
    B, Hq, D = q.shape
    _, _, Hkv, _, page, _ = kv_pages.shape
    G = Hq // Hkv
    if pages_per_block is None:
        pages_per_block = max(1, -(-128 // page))
    page_table, ppb, padded = _block_geometry(page_table, page, pages_per_block)

    scale = 1.0 / (D ** 0.5)
    q4 = (q.astype(jnp.float32) * scale).reshape(B, Hq, 1, D)
    q_spec = pl.BlockSpec((None, Hq, None, D), lambda b, *_: (b, 0, 0, 0))
    kv_new_spec = pl.BlockSpec((None, Hkv, 1, D), lambda b, *_: (b, 0, 0, 0))

    kernel = functools.partial(
        _mh_fused_kernel,
        page=page,
        pages_per_block=ppb,
        pages_per_seq=padded,
        batch_size=B,
        num_kv_heads=Hkv,
        group=G,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(B,),
        in_specs=[
            q_spec, kv_new_spec, kv_new_spec,
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY), q_spec],
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, D), jnp.float32),
            pltpu.VMEM((Hkv, G, D), jnp.float32),
            pltpu.VMEM((Hkv, G, D), jnp.float32),
            pltpu.VMEM((2, Hkv, ppb, page, D), kv_pages.dtype),
            pltpu.VMEM((2, Hkv, ppb, page, D), kv_pages.dtype),
            pltpu.VMEM((2, Hkv, page, D), kv_pages.dtype),  # row RMW
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA,
        ],
    )
    # Args: 6 scalars, q (6), k_new (7), v_new (8), kv_pages (9) → alias
    # kv_pages onto output 0.
    kv_out, out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(kv_pages.shape, kv_pages.dtype),
            jax.ShapeDtypeStruct((B, Hq, 1, D), jnp.float32),
        ],
        input_output_aliases={9: 0},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(
        jnp.asarray(lengths, dtype=jnp.int32),
        jnp.asarray(page_table, dtype=jnp.int32).reshape(-1),
        jnp.asarray(slots, dtype=jnp.int32),
        jnp.asarray(layer, dtype=jnp.int32).reshape(1),
        jnp.zeros((1,), jnp.int32),
        jnp.ones((1,), jnp.int32),
        q4,
        k_new.astype(kv_pages.dtype).reshape(B, Hkv, 1, D),
        v_new.astype(kv_pages.dtype).reshape(B, Hkv, 1, D),
        kv_pages,
    )
    return out.reshape(B, Hq, D).astype(q.dtype), kv_out


@functools.partial(
    jax.jit, static_argnames=("pages_per_block", "interpret", "fuse_heads")
)
def paged_decode_fused_kernel(
    q: jnp.ndarray,  # [B, Hq, D]
    k_new: jnp.ndarray,  # [B, Hkv, D] this token's K (post-rope)
    v_new: jnp.ndarray,  # [B, Hkv, D]
    kv_pages: jnp.ndarray,  # [2, L, Hkv, P, page, D] — donated/aliased
    slots: jnp.ndarray,  # [B] pool slot for this token
    page_table: jnp.ndarray,  # [B, max_pages] int32
    lengths: jnp.ndarray,  # [B] context length incl. current token
    layer: jnp.ndarray | int,
    pages_per_block: int | None = None,
    interpret: bool = False,
    kv_scales: jnp.ndarray | None = None,  # [2, L, Hkv, P, page] int8 pool
    fuse_heads: bool = False,  # heads-batched variant; bf16 only
):
    """Fused decode step attention: returns ``(attn_out [B, Hq, D],
    kv_pages)`` — plus the updated ``kv_scales`` when quantized — where
    the pool buffers are the SAME memory updated in place (the caller
    threads them as scan carries with zero copies)."""
    B, Hq, D = q.shape
    _, _, Hkv, _, page, _ = kv_pages.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must divide by Hkv={Hkv}")
    G = Hq // Hkv
    quantized = kv_scales is not None
    if fuse_heads:
        if quantized:
            raise NotImplementedError(
                "fuse_heads does not support int8 pools yet"
            )
        return _fused_decode_mh(
            q, k_new, v_new, kv_pages, slots, page_table, lengths, layer,
            pages_per_block=pages_per_block, interpret=interpret,
        )
    page_table, ppb, padded = _block_geometry(
        page_table, page, pages_per_block,
        multiple=_rpp(page) if quantized else 1,
    )
    scale_rows = _scale_rows(kv_scales) if quantized else None
    if quantized:
        from radixmesh_tpu.ops.quant import quantize_kv

        # Quantize the incoming row OUTSIDE the kernel (the SAME
        # quantizer the pool's host write path uses, so attention and
        # later reads agree bit-exactly); the kernel gets the int8 row
        # plus its per-(b, h) scale via scalar prefetch, and the scale
        # POOL is updated below with one XLA scatter. An in-kernel
        # scale-row RMW costs four extra serialized DMAs per program —
        # measured at 1.75x the whole fused step on chip.
        k_q, k_sc = quantize_kv(k_new.astype(jnp.float32), axis=-1)
        v_q, v_sc = quantize_kv(v_new.astype(jnp.float32), axis=-1)
        k_new, v_new = k_q, v_q

    scale = 1.0 / (D ** 0.5)
    q4 = (q.astype(jnp.float32) * scale).reshape(B, Hq, 1, D)
    q_spec = pl.BlockSpec((None, G, None, D), lambda b, h, *_: (b, h, 0, 0))
    kv_new_spec = pl.BlockSpec((None, None, 1, D), lambda b, h, *_: (b, h, 0, 0))
    new_dtype = kv_pages.dtype

    kernel = functools.partial(
        _fused_kernel,
        page=page,
        pages_per_block=ppb,
        pages_per_seq=padded,
        batch_size=B,
        num_kv_heads=Hkv,
        quantized=quantized,
    )
    in_specs = [
        q_spec,
        kv_new_spec,
        kv_new_spec,
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    out_specs = [pl.BlockSpec(memory_space=pl.ANY)]
    out_shape = [jax.ShapeDtypeStruct(kv_pages.shape, kv_pages.dtype)]
    # Flat arg order: the scalar-prefetch args (6, +2 scale vectors when
    # quantized), then q, k_new, v_new, kv_pages[, scale_rows] → alias
    # kv_pages onto output 0. The scale pool is read-only inside the
    # kernel; its update happens by XLA scatter below.
    n_scalars = 8 if quantized else 6
    aliases = {n_scalars + 3: 0}
    if quantized:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
    out_specs.append(q_spec)
    out_shape.append(jax.ShapeDtypeStruct((B, Hq, 1, D), jnp.float32))

    scratch = [
        pltpu.VMEM((G, D), jnp.float32),
        pltpu.VMEM((G, D), jnp.float32),
        pltpu.VMEM((G, D), jnp.float32),
        pltpu.VMEM((2, ppb, page, D), kv_pages.dtype),
        pltpu.VMEM((2, ppb, page, D), kv_pages.dtype),
    ]
    if quantized:
        scratch += [
            pltpu.VMEM((2, ppb, 128), jnp.float32),
            pltpu.VMEM((2, ppb, 128), jnp.float32),
        ]
    scratch.append(pltpu.VMEM((2, page, D), kv_pages.dtype))
    scratch.append(pltpu.SemaphoreType.DMA((2, 2)))
    if quantized:
        scratch.append(pltpu.SemaphoreType.DMA((2, 2)))
    scratch.append(pltpu.SemaphoreType.DMA)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_scalars,
        grid=(B, Hkv),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    args = [
        jnp.asarray(lengths, dtype=jnp.int32),
        jnp.asarray(page_table, dtype=jnp.int32).reshape(-1),
        jnp.asarray(slots, dtype=jnp.int32),
        jnp.asarray(layer, dtype=jnp.int32).reshape(1),
        jnp.zeros((1,), jnp.int32),  # double-buffer slot
        jnp.ones((1,), jnp.int32),  # cold-start flag
    ]
    if quantized:
        args += [
            k_sc.astype(jnp.float32).reshape(-1),  # SMEM [B * Hkv]
            v_sc.astype(jnp.float32).reshape(-1),
        ]
    args += [
        q4,
        k_new.astype(new_dtype).reshape(B, Hkv, 1, D),
        v_new.astype(new_dtype).reshape(B, Hkv, 1, D),
        kv_pages,
    ]
    if quantized:
        args.append(scale_rows)
    res = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(*args)
    kv_out, out = res
    attn = out.reshape(B, Hq, D).astype(q.dtype)
    if quantized:
        # Scale-pool update by XLA scatter (same convention as the jnp
        # fallback: an ARRAY layer index makes the advanced indices
        # non-adjacent, so the batch axis lands first → [B, Hkv]),
        # masked so inactive (length == 0) rows leave their target
        # slot's scales untouched.
        slots = jnp.asarray(slots, dtype=jnp.int32)
        lengths = jnp.asarray(lengths, dtype=jnp.int32)
        layer_ix = jnp.asarray(layer)
        pg_b, off_b = slots // page, slots % page
        valid = (lengths > 0)[:, None]  # [B, 1] vs [B, Hkv] gathers
        cur_k = kv_scales[0, layer_ix, :, pg_b, off_b]
        cur_v = kv_scales[1, layer_ix, :, pg_b, off_b]
        scales_out = kv_scales.at[0, layer_ix, :, pg_b, off_b].set(
            jnp.where(valid, k_sc, cur_k)
        )
        scales_out = scales_out.at[1, layer_ix, :, pg_b, off_b].set(
            jnp.where(valid, v_sc, cur_v)
        )
        return attn, kv_out, scales_out
    return attn, kv_out


def _chunk_kernel(
    # scalar prefetch
    prior_ref,  # SMEM [B] pool-context tokens per row (page-part bound)
    kvlen_ref,  # SMEM [B] valid context incl. this chunk
    page_table_ref,  # SMEM [B * padded] flattened
    layer_ref,  # SMEM [1]
    *refs,
    page: int,
    pages_per_block: int,
    pages_per_seq: int,
    chunk: int,  # C — dense keys per program
    c_block: int,  # Cblk — queries per program
    group: int,  # G — q heads per kv head
    quantized: bool,
):
    """Chunk-prefill attention program for one ``(b, h, c-block)``: stream
    the row's PRIOR context from pool pages through the online softmax
    (double-buffered DMA within the program), then fold the current chunk
    in as one dense causal block from VMEM. Query positions are canonical
    (``prior + chunk offset`` — see the wrapper's contract), so masks
    derive from scalars: prior bound for the page part, intra-chunk
    causality + ``kvlen`` bound for the dense part."""
    if quantized:
        (q_ref, kc_ref, vc_ref, kv_hbm, scales_hbm, o_ref,
         m_scr, l_scr, acc_scr, k_buf, v_buf, ks_buf, vs_buf,
         sems, s_sems) = refs
    else:
        (q_ref, kc_ref, vc_ref, kv_hbm, o_ref,
         m_scr, l_scr, acc_scr, k_buf, v_buf, sems) = refs
        scales_hbm = ks_buf = vs_buf = s_sems = None
    b, h, cb = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    layer = layer_ref[0]
    prior = prior_ref[b]
    kvlen = kvlen_ref[b]
    bk = page * pages_per_block
    q_rows = c_block * group

    def block_copies(i, slot):
        off = b * pages_per_seq + i * pages_per_block
        copies = [
            _BlockCopy(kv_hbm, 0, layer, h, k_buf.at[slot], sems.at[slot, 0],
                       page_table_ref, off, pages_per_block),
            _BlockCopy(kv_hbm, 1, layer, h, v_buf.at[slot], sems.at[slot, 1],
                       page_table_ref, off, pages_per_block),
        ]
        if quantized:
            copies.append(
                _ScaleCopy(scales_hbm, 0, layer, h, ks_buf.at[slot],
                           s_sems.at[slot, 0], page_table_ref, off,
                           pages_per_block, page)
            )
            copies.append(
                _ScaleCopy(scales_hbm, 1, layer, h, vs_buf.at[slot],
                           s_sems.at[slot, 1], page_table_ref, off,
                           pages_per_block, page)
            )
        return copies

    q = q_ref[...].astype(jnp.float32).reshape(q_rows, -1)  # pre-scaled
    m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)
    n_blocks = pl.cdiv(prior, bk)

    @pl.when(n_blocks > 0)
    def _cold_start():
        for c in block_copies(0, 0):
            c.start()

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_blocks)
        def _prefetch_next():
            for c in block_copies(i + 1, 1 - slot):
                c.start()

        cs = block_copies(i, slot)
        cs[0].wait()
        if quantized:
            cs[2].wait()
        k = k_buf[slot].astype(jnp.float32).reshape(bk, -1)
        s = jax.lax.dot_general(  # [q_rows, bk]
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if quantized:
            soff = b * pages_per_seq + i * pages_per_block
            s = s * _lane_scales(
                ks_buf[slot], page_table_ref, soff, page, pages_per_block
            )
        kv_pos = i * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # Canonical query positions sit at/after ``prior``, so the page
        # part needs only the prior bound (strictly causal already).
        s = jnp.where(kv_pos < prior, s, _MASK)

        m_prev = m_scr[...]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new[:, :1])
        corr = jnp.exp(m_prev - m_new)
        l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[...] = m_new

        cs[1].wait()
        if quantized:
            cs[3].wait()
            p = p * _lane_scales(
                vs_buf[slot], page_table_ref, soff, page, pages_per_block
            )
        v = v_buf[slot].astype(jnp.float32).reshape(bk, -1)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * corr + pv
        return ()

    jax.lax.fori_loop(0, n_blocks, body, ())

    # Dense block: the chunk itself, causal in chunk coordinates. Key
    # c_k's absolute position is prior + c_k; query row r (= c*G + g of
    # this c-block) sits at prior + cb*Cblk + c.
    kc = kc_ref[...].astype(jnp.float32)  # [C, D]
    vc = vc_ref[...].astype(jnp.float32)
    s2 = jax.lax.dot_general(  # [q_rows, C]
        q, kc,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    c_q = (
        cb * c_block
        + jax.lax.broadcasted_iota(jnp.int32, s2.shape, 0) // group
    )
    c_k = jax.lax.broadcasted_iota(jnp.int32, s2.shape, 1)
    ok = (c_k <= c_q) & (prior + c_k < kvlen)
    s2 = jnp.where(ok, s2, _MASK)
    m_prev = m_scr[...]
    m_new = jnp.maximum(m_prev, jnp.max(s2, axis=-1, keepdims=True))
    p2 = jnp.exp(s2 - m_new[:, :1])
    corr = jnp.exp(m_prev - m_new)
    l_fin = l_scr[...] * corr + jnp.sum(p2, axis=-1, keepdims=True)
    acc_fin = acc_scr[...] * corr + jax.lax.dot_general(
        p2, vc,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    out = jnp.where(l_fin > 0, acc_fin / jnp.maximum(l_fin, 1e-30), 0.0)
    o_ref[...] = out.reshape(c_block, group, -1).astype(o_ref.dtype)


def _chunk_block(chunk: int, group: int, max_rows: int = 1024) -> int:
    """Largest power-of-two divisor of ``chunk`` whose query-row count
    (``Cblk * G``) stays within the VMEM scratch budget."""
    cblk = 1
    while (
        chunk % (cblk * 2) == 0 and cblk * 2 * group <= max_rows
    ):
        cblk *= 2
    return cblk


@functools.partial(
    jax.jit, static_argnames=("pages_per_block", "q_block", "interpret")
)
def paged_chunk_attention_kernel(
    q: jnp.ndarray,  # [B, C, Hq, D] — pre-rope'd chunk queries
    k_cur: jnp.ndarray,  # [B, C, Hkv, D] this chunk's K (post-rope, dequantized)
    v_cur: jnp.ndarray,  # [B, C, Hkv, D]
    kv_pages: jnp.ndarray,  # [2, L, Hkv, P, page, D] full pool pages view
    page_table: jnp.ndarray,  # [B, max_pages] int32
    prior_lengths: jnp.ndarray,  # [B] pool tokens BEFORE this chunk
    kv_lengths: jnp.ndarray,  # [B] valid context incl. this chunk
    layer: jnp.ndarray | int,
    pages_per_block: int | None = None,
    q_block: int | None = None,
    interpret: bool = False,
    kv_scales: jnp.ndarray | None = None,  # [2, L, Hkv, P, page] int8 pool
) -> jnp.ndarray:
    """Pallas chunk-prefill attention: SURVEY §7 hard part (a) for the
    PREFILL side (VERDICT round-3 next-step #3 "pool-page chunk
    attention"). The jnp oracle is ``ops/attention.py::attend_chunk_hybrid``
    — same online-softmax merge of prior pool pages + the dense causal
    chunk, but pages stream HBM→VMEM per (sequence, kv-head, query-block)
    program instead of gathering [B, Hkv, bk, D] copies through XLA.

    CONTRACT: query positions are canonical —
    ``q_positions == prior_lengths[:, None] + arange(C)`` (the only form
    the serving stack produces; both chunked prefill and the speculative
    verify chunk satisfy it) — so causal masks derive from
    ``prior_lengths``/``kv_lengths`` and the chunk offset alone, and the
    chunk's K/V arrive dense from the layer activations (``k_cur``
    already dequantized when the pool is int8, preserving the
    see-what-you-store invariant).

    Returns ``[B, C, Hq, D]``.
    """
    B, C, Hq, D = q.shape
    _, _, Hkv, _, page, _ = kv_pages.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must divide by Hkv={Hkv}")
    G = Hq // Hkv
    quantized = kv_scales is not None
    page_table, ppb, padded = _block_geometry(
        page_table, page, pages_per_block,
        multiple=_rpp(page) if quantized else 1,
    )
    cblk = q_block if q_block is not None else _chunk_block(C, G)
    if C % cblk:
        raise ValueError(f"q_block={cblk} must divide chunk C={C}")

    scale = 1.0 / (D ** 0.5)
    # [B, Hkv, C, G, D]: kv-head-major so each program's q block is one
    # contiguous [Cblk, G, D] tile.
    q5 = (q.astype(jnp.float32) * scale).reshape(B, C, Hkv, G, D).transpose(
        0, 2, 1, 3, 4
    )
    kc = k_cur.transpose(0, 2, 1, 3)  # [B, Hkv, C, D]
    vc = v_cur.transpose(0, 2, 1, 3)
    q_spec = pl.BlockSpec(
        (None, None, cblk, G, D), lambda b, h, cb, *_: (b, h, cb, 0, 0)
    )
    kc_spec = pl.BlockSpec(
        (None, None, C, D), lambda b, h, cb, *_: (b, h, 0, 0)
    )

    kernel = functools.partial(
        _chunk_kernel,
        page=page,
        pages_per_block=ppb,
        pages_per_seq=padded,
        chunk=C,
        c_block=cblk,
        group=G,
        quantized=quantized,
    )
    in_specs = [q_spec, kc_spec, kc_spec, pl.BlockSpec(memory_space=pl.ANY)]
    if quantized:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
    scratch = [
        pltpu.VMEM((cblk * G, D), jnp.float32),
        pltpu.VMEM((cblk * G, D), jnp.float32),
        pltpu.VMEM((cblk * G, D), jnp.float32),
        pltpu.VMEM((2, ppb, page, D), kv_pages.dtype),
        pltpu.VMEM((2, ppb, page, D), kv_pages.dtype),
    ]
    if quantized:
        scratch += [
            pltpu.VMEM((2, ppb, 128), jnp.float32),
            pltpu.VMEM((2, ppb, 128), jnp.float32),
        ]
    scratch.append(pltpu.SemaphoreType.DMA((2, 2)))
    if quantized:
        scratch.append(pltpu.SemaphoreType.DMA((2, 2)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, Hkv, C // cblk),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=scratch,
    )
    args = [
        jnp.asarray(prior_lengths, dtype=jnp.int32),
        jnp.asarray(kv_lengths, dtype=jnp.int32),
        jnp.asarray(page_table, dtype=jnp.int32).reshape(-1),
        jnp.asarray(layer, dtype=jnp.int32).reshape(1),
        q5,
        kc,
        vc,
        kv_pages,
    ]
    if quantized:
        args.append(_scale_rows(kv_scales))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, C, G, D), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(*args)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, C, Hq, D).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_kernel(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages: jnp.ndarray,  # [Hkv, P, page, D] head-major (PagedKVPool.pages_for_layer)
    v_pages: jnp.ndarray,  # [Hkv, P, page, D]
    page_table: jnp.ndarray,  # [B, max_pages] int32
    lengths: jnp.ndarray,  # [B] int32
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-layer convenience wrapper (tests, layer-at-a-time callers)."""
    kv_pages = jnp.stack([k_pages, v_pages])[:, None]  # [2, 1, Hkv, P, page, D]
    return paged_attention_pool_kernel(
        q, kv_pages, page_table, lengths, 0, interpret=interpret
    )
