"""Pallas TPU decode kernel: attention over non-contiguous radix-cache pages.

This is the op SURVEY §7 calls the hard part (a): the radix cache hands the
scheduler a *page table* (page ids into the paged KV pool, arbitrary order,
shared across requests that share a prefix), and decode attention must
gather those pages without materializing a dense [B, max_ctx, H, D] copy in
HBM — the copy is exactly the bandwidth decode can't afford.

Design (one program per sequence, grid = (B,)):

- The KV pool pages stay in HBM (``memory_space=ANY``); the page table and
  sequence lengths ride scalar prefetch (SMEM) so the kernel can compute
  DMA source addresses before the body runs.
- Pages are DMA'd HBM→VMEM **double-buffered**: page ``i+1``'s copy is in
  flight while page ``i`` is being contracted on the MXU.
- Online softmax (running max / sum / weighted accumulator, fp32) across
  the page loop, GQA via a [Hkv, G, D] query layout contracted against
  each [page, Hkv, D] KV tile.
- Per-sequence page counts bound the loop work: DMA start *and* wait are
  predicated on the same ``page < n_pages(seq)`` condition (no hangs), and
  out-of-range lanes are masked to -inf before the softmax update.

The jnp oracle is ``ops/attention.py::attend_decode_ref``; numerics are
compared in ``tests/test_ops.py`` (interpreter mode on CPU) and on real TPU
by ``bench.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_attention_kernel", "paged_attention_pool_kernel"]


def _kernel(
    # scalar prefetch
    page_table_ref,  # SMEM [B, max_pages]
    lengths_ref,  # SMEM [B]
    layer_ref,  # SMEM [1] — which layer's pages to read
    # inputs
    q_ref,  # VMEM [1, Hq, D]
    kv_hbm,  # ANY  [2, L, Hkv, P, page, D] — the whole pool, zero-copy
    # outputs
    o_ref,  # VMEM [1, Hq, D]
    # scratch
    k_buf,  # VMEM [2, Hkv, page, D]
    v_buf,  # VMEM [2, Hkv, page, D]
    sem,  # DMA [2, 2]
    *,
    page: int,
    n_kv_heads: int,
    max_pages: int,
):
    b = pl.program_id(0)
    n = lengths_ref[b]
    layer = layer_ref[0]
    n_pages = pl.cdiv(n, page)
    hq = q_ref.shape[1]
    d = q_ref.shape[2]
    g = hq // n_kv_heads

    scale = 1.0 / (d ** 0.5)
    # [Hkv, G, D] query layout so one einsum covers all GQA groups.
    q = (q_ref[0].astype(jnp.float32) * scale).reshape(n_kv_heads, g, d)

    def dma(buf_ref, slot, page_idx, which):
        # which: 0 = K, 1 = V. Source block [Hkv, page, D] — contiguous
        # [page, D] rows per head in the head-major pool layout.
        return pltpu.make_async_copy(
            kv_hbm.at[which, layer, :, page_table_ref[b, page_idx]],
            buf_ref.at[slot],
            sem.at[which, slot],
        )

    @pl.when(n_pages > 0)
    def _():
        dma(k_buf, 0, 0, 0).start()
        dma(v_buf, 0, 0, 1).start()

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        next_slot = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            dma(k_buf, next_slot, i + 1, 0).start()
            dma(v_buf, next_slot, i + 1, 1).start()

        @pl.when(i < n_pages)
        def _():
            dma(k_buf, slot, i, 0).wait()
            dma(v_buf, slot, i, 1).wait()

        k = k_buf[slot].astype(jnp.float32)  # [Hkv, page, D]
        v = v_buf[slot].astype(jnp.float32)
        # [Hkv, G, page] scores on the MXU (batch dim 0 on both operands —
        # Mosaic requires batch dims in matching positions).
        s = jax.lax.dot_general(
            q,
            k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        pos = i * page + jax.lax.broadcasted_iota(jnp.int32, (1, 1, page), 2)
        s = jnp.where(pos < n, s, -jnp.inf)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # [Hkv, G, page]
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        # [Hkv, G, D] accumulator update.
        pv = jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr + pv
        valid = i < n_pages
        return (
            jnp.where(valid, m_new, m),
            jnp.where(valid, l_new, l),
            jnp.where(valid, acc_new, acc),
        )

    m0 = jnp.full((n_kv_heads, g, 1), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((n_kv_heads, g, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((n_kv_heads, g, d), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, max_pages, body, (m0, l0, acc0))
    out = (acc / l).reshape(hq, d)
    o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_pool_kernel(
    q: jnp.ndarray,  # [B, Hq, D]
    kv_pages: jnp.ndarray,  # [2, L, Hkv, P, page, D] — full pool pages view
    page_table: jnp.ndarray,  # [B, max_pages] int32
    lengths: jnp.ndarray,  # [B] int32
    layer: jnp.ndarray | int,  # which layer's pages to attend over
    interpret: bool = False,
) -> jnp.ndarray:
    """Primary entry: the whole (multi-layer) pool rides in HBM untouched
    and the kernel DMAs only ``layer``'s pages — so a scan-over-layers
    decode step costs O(context pages) HBM traffic per layer, never a
    materialized per-layer slice (which would be O(pool size))."""
    B, Hq, D = q.shape
    _, _, Hkv, _, page, _ = kv_pages.shape
    max_pages = page_table.shape[1]
    kernel = functools.partial(
        _kernel, page=page, n_kv_heads=Hkv, max_pages=max_pages
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, Hkv, page, D), kv_pages.dtype),
            pltpu.VMEM((2, Hkv, page, D), kv_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(page_table, dtype=jnp.int32),
        jnp.asarray(lengths, dtype=jnp.int32),
        jnp.asarray(layer, dtype=jnp.int32).reshape(1),
        q,
        kv_pages,
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_kernel(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages: jnp.ndarray,  # [Hkv, P, page, D] head-major (PagedKVPool.pages_for_layer)
    v_pages: jnp.ndarray,  # [Hkv, P, page, D]
    page_table: jnp.ndarray,  # [B, max_pages] int32
    lengths: jnp.ndarray,  # [B] int32
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-layer convenience wrapper (tests, layer-at-a-time callers)."""
    kv_pages = jnp.stack([k_pages, v_pages])[:, None]  # [2, 1, Hkv, P, page, D]
    return paged_attention_pool_kernel(
        q, kv_pages, page_table, lengths, 0, interpret=interpret
    )
